"""MXNet frontend (reference: ``horovod/mxnet/__init__.py:40-158`` +
``mxnet/mpi_ops.cc:1-291``).

Import-gated on mxnet like the other framework shims.  The reference
pushes async ops onto the MXNet engine through a C++ binding; here every
collective crosses to numpy on the host and rides the shared eager data
plane (negotiated + fused by the native control plane when it is up, the
same path the torch and TF shims use), writing results back into the
NDArray in place.  MXNet is not part of this image — the unit tests
exercise this module against a mocked ``mxnet`` (documented gate); the
module works unchanged against the real library.
"""

from __future__ import annotations

import types
from typing import Optional

try:
    import mxnet as mx
except ImportError as _e:  # pragma: no cover - exercised via mock in tests
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet, which is not installed in this "
        "image; see tests/test_mxnet_frontend.py for the mocked-module "
        "contract this frontend is verified against."
    ) from _e

import numpy as np

from horovod_tpu.basics import (  # noqa: F401
    cross_rank, cross_size, init, is_initialized, local_rank, local_size,
    rank, shutdown, size,
)
from horovod_tpu.ops import collectives as C


def _to_np(tensor) -> np.ndarray:
    return tensor.asnumpy() if hasattr(tensor, "asnumpy") else np.asarray(tensor)


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Allreduce returning a NEW NDArray (reference ``hvd.allreduce``)."""
    out = C.allreduce(_to_np(tensor), C.Average if average else C.Sum,
                      name=name)
    return mx.nd.array(out)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None):
    """In-place allreduce (reference ``allreduce_``): the NDArray's
    contents are replaced with the reduced values."""
    out = C.allreduce(_to_np(tensor), C.Average if average else C.Sum,
                      name=name)
    tensor[:] = out
    return tensor


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    return mx.nd.array(C.broadcast(_to_np(tensor), root_rank, name=name))


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None):
    tensor[:] = C.broadcast(_to_np(tensor), root_rank, name=name)
    return tensor


def allgather(tensor, name: Optional[str] = None):
    return mx.nd.array(C.allgather(_to_np(tensor), name=name))


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wrap an mx optimizer so ``update`` reduces gradients first
    (reference ``mxnet/__init__.py:40-80``): ``rescale_grad`` is divided
    by the worker count so a SUM allreduce performs the average inside
    the optimizer's own rescaling — one fused multiply instead of a
    separate division pass."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        # The eager data plane reduces across PROCESSES (cross_size); in
        # the reference size()==processes, but here size() counts devices,
        # so the average-by-rescale must divide by the actual participant
        # count.
        self._optimizer.rescale_grad /= cross_size()

    def __getattr__(self, item):
        if item == "_optimizer":
            # only reachable when __init__ hasn't run (deepcopy/unpickle
            # protocol probes) — delegating would recurse forever
            raise AttributeError(item)
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if cross_size() == 1:
            return
        if isinstance(index, (tuple, list)):
            # Grouped submission: one negotiation window sees the whole
            # gradient set and fuses it (the reference expresses the same
            # intent with descending priorities on the async engine).
            for i, g in zip(index, grad):
                allreduce_(g, average=False, name=f"grad.{i}")
        else:
            allreduce_(grad, average=False, name=f"grad.{index}")

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer whose gradient reduction is a horovod allreduce
    instead of kvstore push/pull (reference ``mxnet/__init__.py:83-110``);
    sum + pre-divided ``_scale`` performs the average."""

    def __init__(self, params, optimizer, optimizer_params=None):
        # If handed an already-wrapped DistributedOptimizer, unwrap WITHOUT
        # touching it: its inner rescale_grad is already divided by
        # cross_size() (that division performs the average), so the
        # trainer must not divide _scale again — and mutating the shared
        # inner optimizer would break the wrapper for its other users.
        already_scaled = isinstance(optimizer, DistributedOptimizer)
        if already_scaled:
            optimizer = optimizer._optimizer
        super().__init__(params, optimizer,
                         optimizer_params=optimizer_params, kvstore=None)
        if not already_scaled:
            self._scale /= cross_size()

    def _allreduce_grads(self):
        if cross_size() == 1:
            return
        for param in self._params:
            if getattr(param, "grad_req", None) != "null":
                allreduce_(param.list_grad()[0], average=False,
                           name=f"grad.{param.name}")


def _append_broadcast_init(param, root_rank: int):
    """Hook deferred-initialization so a parameter broadcasts right after
    its shape is finally known (reference ``_append_broadcast_init``)."""
    init_impl = param._init_impl

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank)
        self.data().wait_to_read()

    return wrapped_init_impl


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a dict of NDArrays or a gluon ParameterDict from
    ``root_rank`` (reference ``mxnet/__init__.py:120-158``); parameters
    still pending deferred initialization broadcast post-init."""
    if cross_size() == 1:
        return
    tensors = []
    param_dict_cls = getattr(mx.gluon.parameter, "ParameterDict", None)
    if param_dict_cls is not None and isinstance(params, param_dict_cls):
        deferred_err = mx.gluon.parameter.DeferredInitializationError
        for _, p in sorted(params.items()):
            try:
                tensors.append(p.data())
            except deferred_err:
                p._init_impl = types.MethodType(
                    _append_broadcast_init(p, root_rank), p)
    elif isinstance(params, dict):
        tensors = [p for _, p in sorted(params.items())]
    else:
        raise ValueError(f"invalid params of type: {type(params)}")

    for i, tensor in enumerate(tensors):
        broadcast_(tensor, root_rank, name=f"param.{i}")
    for tensor in tensors:
        tensor.wait_to_read()
