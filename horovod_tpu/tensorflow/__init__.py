"""TensorFlow frontend (reference: ``horovod/tensorflow/__init__.py``).

Import-gated on TF like the other framework shims: with TF installed the
API below works (eager/tf.function TF2 style — TF tensors bridge through
numpy into the shared eager path, exactly like the torch frontend);
without TF, importing this module raises with a pointer to the JAX-native
API.

Provided (reference parity, tensorflow/__init__.py):
``allreduce`` (43-118), ``broadcast_variables`` (139-148),
``DistributedGradientTape`` (474-531), ``DistributedOptimizer`` factory
for keras optimizers (410-471), ``broadcast_global_variables``.
"""

from __future__ import annotations

try:
    import tensorflow as tf  # noqa: F401
except ImportError as _e:  # pragma: no cover - TF absent in this image
    raise ImportError(
        "horovod_tpu.tensorflow requires tensorflow, which is not "
        "installed in this environment.  The JAX-native API "
        "(horovod_tpu.DistributedOptimizer / DistributedGradientTape) and "
        "the torch frontend (horovod_tpu.torch) provide the same "
        "capabilities."
    ) from _e

import numpy as np

from horovod_tpu.basics import (  # noqa: F401
    cross_rank, cross_size, init, is_initialized, local_rank, local_size,
    num_processes, process_rank, rank, shutdown, size,
)
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.compression import Compression  # noqa: F401 — parity
# surface of the reference's tensorflow/compression.py

Average, Sum, Adasum = C.Average, C.Sum, C.Adasum


def _to_np(t):
    return t.numpy() if hasattr(t, "numpy") else np.asarray(t)


def allreduce(tensor, average=None, op=None, name=None,
              prescale_factor=1.0, postscale_factor=1.0,
              sparse_as_dense=False):
    """TF allreduce through the shared runtime (reference
    tensorflow/__init__.py:43-118).

    ``tf.IndexedSlices`` (sparse embedding gradients) ride the
    reference's sparse path by default — allgather of values and indices
    (``tensorflow/__init__.py:74-89``), so the wire cost scales with the
    touched rows, not the embedding table; ``sparse_as_dense=True``
    densifies first (the reference's opt-in flag, useful when nearly all
    rows are touched).

    Works eagerly AND inside ``tf.function``: under a function trace the
    op embeds as a ``tf.py_function`` bridging to the eager data plane,
    with the collective name captured at trace time from the symbolic
    tensor (identical across ranks since the traced program is), so
    out-of-order runtime execution of independent allreduces is matched
    by name in the native coordinator.

    DIFFERENTIABLE on both paths: the dense op carries a
    ``tf.custom_gradient`` whose backward is an allreduce of the
    upstream gradient with the same op (the reference registers exactly
    this, ``tensorflow/mpi_ops.py:110-121`` ``_allreduce_grad``), so
    ``tf.GradientTape`` flows through ``hvd.allreduce`` calls inside a
    loss — eagerly AND under ``tf.function`` — instead of silently
    detaching at the numpy bridge."""
    if op is None:
        op = Average if (average is None or average) else Sum
    if isinstance(tensor, tf.IndexedSlices):
        if sparse_as_dense or tf.inside_function():
            # Under a tf.function trace the sparse tensors are symbolic
            # (no .numpy()), so the traced path densifies and rides the
            # py_function bridge below; the row-proportional sparse wire
            # format is an eager-path optimization.
            tensor = tf.convert_to_tensor(tensor)
        else:
            if op not in (Average, Sum):
                raise NotImplementedError(
                    "sparse allreduce supports Sum/Average (reference "
                    "raises the same way for Adasum on IndexedSlices)")
            nm = name or "sparse.allreduce"
            vals = _to_np(tensor.values) * prescale_factor
            values = tf.convert_to_tensor(
                C.allgather(vals, name=f"{nm}.values") * postscale_factor)
            if op == Average:
                values = values / cross_size()  # eager-path participants
            indices = tf.convert_to_tensor(
                C.allgather(_to_np(tensor.indices), name=f"{nm}.indices"))
            return tf.IndexedSlices(values, indices,
                                    dense_shape=tensor.dense_shape)

    in_fn = tf.inside_function()
    cname = _auto_name(tensor, name, in_fn)
    # Allreduce is linear, so the VJP of Sum/Average is the same op on
    # the cotangent (scaled by the linear pre/post factors); the
    # reference's _allreduce_grad uses a plain sum-allreduce for the
    # nonlinear ops too, mirrored here.  The allreduce forward is
    # chip-weighted (docs/concepts.md), so its same-op backward IS the
    # true VJP — unlike the process-level gather/broadcast below.
    grad_op = op if op in (Average, Sum) else Sum
    scale = prescale_factor * postscale_factor

    def _run(the_op, nm, pre, post):
        return lambda a: C.allreduce(a, the_op, name=nm,
                                     prescale_factor=pre,
                                     postscale_factor=post)

    @tf.custom_gradient
    def _fn(t):
        result = _bridge_call(
            _run(op, cname, prescale_factor, postscale_factor),
            [t], t.shape, t.dtype, in_fn)

        def grad(dy):
            dy = _densify(dy)
            gname = f"{cname}.grad" if cname else None
            return _bridge_call(_run(grad_op, gname, scale, 1.0),
                                [dy], dy.shape, dy.dtype, in_fn)

        return result, grad

    # Variables convert BEFORE _fn so custom_gradient doesn't demand a
    # variables= grad signature.
    return _fn(tf.convert_to_tensor(tensor))


def _auto_name(tensor, name, in_fn):
    """Trace-time deterministic collective name (identical across ranks
    since the traced programs are); eagerly None defers to the runtime's
    program-order auto-naming."""
    return name or ("tf." + tensor.name.replace(":", ".") if in_fn else None)


def _densify(dy):
    """Sparse cotangents (a loss that gathered rows) densify before the
    backward collective, as TF does implicitly for registered op grads."""
    return tf.convert_to_tensor(dy) if isinstance(dy, tf.IndexedSlices) else dy


def _bridge_call(fn_np, inputs, out_shape, dtype, in_fn):
    """Run a host-side collective on numpy values; under a tf.function
    trace the call embeds as a ``tf.py_function`` with the static shape
    re-attached."""
    if in_fn:
        r = tf.py_function(
            lambda *tt: tf.convert_to_tensor(
                fn_np(*[x.numpy() for x in tt])),
            inputs, Tout=dtype)
        r.set_shape(out_shape)
        return r
    return tf.convert_to_tensor(fn_np(*[_to_np(x) for x in inputs]))


def allgather(tensor, name=None):
    """Concatenate across processes on dim 0; DIFFERENTIABLE like the
    reference's registered gradient (``tensorflow/mpi_ops.py:143-166``
    ``_allgather_grad``): the backward sums the cotangent across
    processes and returns this process's row slice.  The sum is a
    :func:`~horovod_tpu.ops.collectives.process_sum` — the gather is a
    process-level concat (one contribution per process), so its VJP must
    not pick up the chip weighting (tape gradients stay finite-
    difference-correct for the loss this process computed)."""
    in_fn = tf.inside_function()
    nm = _auto_name(tensor, name, in_fn)

    @tf.custom_gradient
    def _fn(t):
        r = _bridge_call(lambda a: C.allgather(a, name=nm), [t],
                         [None] + list(t.shape[1:]), t.dtype, in_fn)

        def grad(dy):
            dy = _densify(dy)
            gname = f"{nm}.grad" if nm else None

            def _g(dd, tt):
                g = C.process_sum(dd, name=gname)
                rows = np.asarray([tt.shape[0]], np.int64)
                sizes = C.allgather(
                    rows, name=f"{gname}.sizes" if gname else None)
                off = int(sizes[:process_rank()].sum())
                return g[off:off + int(rows[0])]

            return _bridge_call(_g, [dy, t], t.shape, dy.dtype, in_fn)

        return r, grad

    return _fn(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank=0, name=None):
    """Broadcast from ``root_rank``; DIFFERENTIABLE like the reference's
    registered gradient (``tensorflow/mpi_ops.py:186-201``
    ``_broadcast_grad``): the backward sums the cotangent across
    processes (process-level, like the forward — see :func:`allgather`)
    to the root and is zero elsewhere."""
    in_fn = tf.inside_function()
    nm = _auto_name(tensor, name, in_fn)

    @tf.custom_gradient
    def _fn(t):
        r = _bridge_call(lambda a: C.broadcast(a, root_rank, name=nm),
                         [t], t.shape, t.dtype, in_fn)

        def grad(dy):
            dy = _densify(dy)
            gname = f"{nm}.grad" if nm else None

            def _g(dd):
                g = C.process_sum(dd, name=gname)
                # root_rank is a worker (chip) rank; this process owns it
                # iff it falls in [rank(), rank() + local_size()).
                owns = rank() <= root_rank < rank() + local_size()
                return g if owns else np.zeros_like(g)

            return _bridge_call(_g, [dy], t.shape, dy.dtype, in_fn)

        return r, grad

    return _fn(tf.convert_to_tensor(tensor))


def broadcast_variables(variables, root_rank=0):
    """Assign every variable rank ``root_rank``'s value (reference
    broadcast_variables, tensorflow/__init__.py:139-148)."""
    for i, v in enumerate(variables):
        v.assign(broadcast(v, root_rank, name=f"broadcast.var.{i}"))


def broadcast_global_variables(root_rank=0):
    """Broadcast every variable tracked by the v1-compat global collection
    (reference ``broadcast_global_variables``,
    ``tensorflow/__init__.py:150-175``).

    Only meaningful for v1-style code running with eager execution whose
    variables landed in the v1 global collection (e.g. ``tf.compat.v1``
    layers under an eager-enabled compat setup).  TF1 graph-session mode is
    not supported by this shim (raises ``NotImplementedError``), and pure
    TF2 eager code has an empty global collection (raises ``ValueError``)
    — in both cases pass your variables to :func:`broadcast_variables` or
    use :class:`BroadcastGlobalVariablesCallback` instead."""
    if not tf.executing_eagerly():
        raise NotImplementedError(
            "TF1 graph-mode sessions are not supported by the TPU eager "
            "shim; use BroadcastGlobalVariablesCallback (the "
            "BroadcastGlobalVariablesHook equivalent) or TF2 eager mode.")
    gvars = tf.compat.v1.global_variables()
    if not gvars:
        raise ValueError(
            "No global variables are tracked (pure TF2 eager mode has no "
            "global collection); call "
            "broadcast_variables(model.variables, root_rank) instead.")
    broadcast_variables(gvars, root_rank)


class BroadcastGlobalVariablesCallback(object):
    """Keras-style callback that broadcasts all model variables from
    ``root_rank`` at the start of training — the TF2 equivalent of the
    reference's ``BroadcastGlobalVariablesHook``
    (``tensorflow/__init__.py:194-227``), which wrapped a TF1
    SessionRunHook.  Duck-types ``tf.keras.callbacks.Callback``.
    """

    def __init__(self, root_rank=0):
        self.root_rank = root_rank
        self.model = None
        self._done = False

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        pass

    def on_train_begin(self, logs=None):
        if not self._done and self.model is not None:
            broadcast_variables(self.model.variables, self.root_rank)
            self._done = True

    def __getattr__(self, item):
        # Remaining callback hooks (on_epoch_begin, on_batch_end, ...) are
        # no-ops.
        if item.startswith("on_") or item.startswith("set_"):
            return lambda *a, **k: None
        raise AttributeError(item)


class DistributedGradientTape(object):
    """Wrap tf.GradientTape so gradient() allreduces the grads
    (reference tensorflow/__init__.py:474-531)."""

    def __init__(self, tape, compression=None, op=Average):
        self._tape = tape
        self._compression = compression
        self._op = op

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        arrs = [None if g is None else _to_np(
            tf.convert_to_tensor(g) if isinstance(g, tf.IndexedSlices) else g)
            for g in grads]
        present = [i for i, a in enumerate(arrs) if a is not None]
        reduced = _reduce_group([arrs[i] for i in present], self._op,
                                self._compression)
        out = list(grads)
        for i, r in zip(present, reduced):
            out[i] = tf.convert_to_tensor(r)
        return out


def _reduce_group(arrs, op, compression):
    """Grouped allreduce with optional 16-bit wire compression (the
    reference compresses per tensor before enqueue,
    ``tensorflow/__init__.py:43-118`` + ``compression.py``)."""
    if compression is None or compression is Compression.none:
        return C.grouped_allreduce(arrs, op)
    pairs = [compression.compress(a) for a in arrs]
    reduced = C.grouped_allreduce([p[0] for p in pairs], op)
    return [np.asarray(compression.decompress(r, p[1]))
            for r, p in zip(reduced, pairs)]


def _adasum_reduce_deltas(arrs, compression):
    """Adasum-allreduce a group of parameter deltas.  Per-tensor pairwise
    coefficients are guaranteed by :func:`~horovod_tpu.ops.collectives.
    grouped_allreduce` (native path: the controller fuses the group and
    the executor runs ``eager_adasum_group``; direct path: the group
    kernel shares the log2(P) rounds — reference ``adasum.h:194-338``
    FusedAllreduce semantics)."""
    return [np.asarray(r)
            for r in _reduce_group([np.asarray(a) for a in arrs],
                                   C.Adasum, compression)]


def distributed_optimizer_class(base_cls, op=Average, compression=None,
                                backward_passes_per_step=1):
    """Subclass ``base_cls`` so ``apply_gradients`` averages gradients
    across workers first.  Keeps the base class's name so keras
    (de)serialization round-trips — ``load_model`` resolves the saved
    class through these wrappers (reference ``_keras/__init__.py:103-115``
    custom-objects mechanism).

    ``backward_passes_per_step > 1`` turns on local gradient aggregation
    (reference ``tensorflow/__init__.py:328-365``): the first N-1 calls
    accumulate on the host and apply NOTHING; the Nth reduces the
    accumulated average across workers and applies it."""

    bpps = int(backward_passes_per_step)

    class _Wrapped(base_cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, **kwargs):
            gv = list(grads_and_vars)
            arrs = [None if g is None else _to_np(
                tf.convert_to_tensor(g) if isinstance(g, tf.IndexedSlices)
                else g) for g, _ in gv]
            if bpps > 1:
                # plain __dict__ storage: keras 3 optimizers TRACK
                # attribute assignments (lists get copied into tracked
                # structures), which would silently detach this state
                state = self.__dict__.setdefault(
                    "_hvd_agg_state", {"agg": None, "passes": 0})
                if state["agg"] is None:
                    state["agg"] = [None] * len(arrs)
                agg = state["agg"]
                if len(agg) != len(arrs):
                    raise ValueError(
                        "apply_gradients called with a different variable "
                        "set mid-aggregation window")
                for i, a in enumerate(arrs):
                    if a is not None:
                        agg[i] = a if agg[i] is None else agg[i] + a
                state["passes"] += 1
                if state["passes"] % bpps != 0:
                    return None  # accumulate only; nothing applied yet
                arrs = [None if a is None else a / bpps for a in agg]
                state["agg"] = None
            present = [i for i, a in enumerate(arrs) if a is not None]
            reduced = _reduce_group([arrs[i] for i in present], op,
                                    compression)
            for i, r in zip(present, reduced):
                gv[i] = (tf.convert_to_tensor(r), gv[i][1])
            return super().apply_gradients(gv, **kwargs)

    _Wrapped.__name__ = base_cls.__name__
    return _Wrapped


def distributed_adasum_optimizer_class(base_cls, compression=None,
                                       backward_passes_per_step=1):
    """Delta-model Adasum subclass of ``base_cls`` — the published Adasum
    usage mode (reference ``_DistributedAdasumOptimizer``,
    ``tensorflow/__init__.py:313-407``): each worker applies its own
    optimizer step (so the delta carries the optimizer's adaptive
    scaling), and the cumulative parameter delta since the last sync is
    Adasum-combined and written back:

        start  = params at the last sync
        apply_gradients() -> LOCAL update (k times for bpps=k)
        delta  = params - start
        start += adasum_allreduce(delta) ; params = start

    Matches the optax ``DistributedAdasumOptimizer`` (``optim.py:151``)
    and the torch factory dispatch (``torch/__init__.py:153-243``)
    step-for-step.

    ORDERING CONTRACT (same as the reference's): broadcast the initial
    variables to all workers BEFORE the first ``apply_gradients`` —
    ``start`` is snapshotted lazily on the first step, so a
    post-broadcast-after-step ordering would capture divergent
    pre-broadcast weights and the first sync would silently write back
    divergent deltas."""

    bpps = int(backward_passes_per_step)
    if bpps < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    class _Wrapped(base_cls):
        _hvd_wrapped = True
        _hvd_adasum = True

        def apply_gradients(self, grads_and_vars, **kwargs):
            gv = list(grads_and_vars)
            variables = [v for _, v in gv]
            # plain __dict__ storage: keras 3 optimizers TRACK attribute
            # assignments (see distributed_optimizer_class above)
            state = self.__dict__.setdefault(
                "_hvd_adasum_state", {"start": None, "passes": 0})
            if state["start"] is None:
                # params at the last sync = the broadcast initial model
                state["start"] = [v.numpy().copy() for v in variables]
            if len(state["start"]) != len(variables):
                raise ValueError(
                    "apply_gradients called with a different variable set "
                    "mid-sync window")
            result = super().apply_gradients(gv, **kwargs)  # LOCAL update
            state["passes"] += 1
            if state["passes"] % bpps != 0:
                return result  # workers drift locally until the comm step
            deltas = [v.numpy() - s
                      for v, s in zip(variables, state["start"])]
            combined = _adasum_reduce_deltas(deltas, compression)
            new_start = [s + np.asarray(g, dtype=s.dtype)
                         for s, g in zip(state["start"], combined)]
            for v, ns in zip(variables, new_start):
                v.assign(tf.convert_to_tensor(ns))
            state["start"] = new_start
            return result

    _Wrapped.__name__ = base_cls.__name__
    return _Wrapped


def DistributedOptimizer(optimizer, compression=None, op=Average,
                         backward_passes_per_step=1):
    """Wrap a keras optimizer so apply_gradients averages gradients
    across workers first (reference factory, 410-471).  ``op=Adasum``
    selects the delta-model optimizer (local update, Adasum-combined
    parameter deltas) exactly as the reference factory does
    (``tensorflow/__init__.py:410-471`` dispatching to
    ``_DistributedAdasumOptimizer``)."""
    if op == Adasum:
        cls = distributed_adasum_optimizer_class(
            optimizer.__class__, compression=compression,
            backward_passes_per_step=backward_passes_per_step)
    else:
        cls = distributed_optimizer_class(
            optimizer.__class__, op=op, compression=compression,
            backward_passes_per_step=backward_passes_per_step)
    return cls.from_config(optimizer.get_config())
