"""``horovod_tpu.tensorflow.keras`` — alias of :mod:`horovod_tpu.keras`
(the reference exposes the keras surface at both ``horovod.keras`` and
``horovod.tensorflow.keras``; scripts import either)."""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import (  # noqa: F401 — explicit for tooling
    BroadcastGlobalVariablesCallback,
    DistributedOptimizer,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    init,
    load_model,
    rank,
    size,
)
