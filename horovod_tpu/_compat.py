"""JAX version compatibility shims.

The codebase targets current JAX public APIs; deployment environments
often pin older runtimes (this repo's CI images ship 0.4.x).  Rather
than sprinkling per-call-site fallbacks, the few APIs we rely on that
older JAX lacks are installed here, once, at ``import horovod_tpu``
time.  Every shim is gated on ``hasattr`` — on a current JAX this
module is a no-op.

Shimmed:

* ``jax.lax.axis_size(name)`` — older JAX spells the size of a bound
  mesh axis ``lax.psum(1, name)``, which constant-folds to a static int
  and raises the same ``NameError`` on an unbound name.
* ``jax.shard_map`` — re-export of ``jax.experimental.shard_map`` on
  versions where it has not been promoted to the top level.
"""

from __future__ import annotations

import os

import jax
from jax import lax


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices (tests/benchmarks simulating a
    multi-chip slice).  Must run before the CPU backend initializes.
    Newer JAX has a config option; older JAX only honors the XLA flag."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        )


def _install() -> None:
    if not hasattr(lax, "axis_size"):

        def axis_size(axis_name):
            """Size of the bound mesh axis (or product over a tuple)."""
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    if not hasattr(jax, "shard_map"):
        try:
            import inspect

            from jax.experimental.shard_map import shard_map

            if "check_vma" not in inspect.signature(shard_map).parameters:
                # Newer JAX renamed check_rep -> check_vma when
                # shard_map was promoted to the top level; translate so
                # callers can use the modern spelling on either.
                import functools

                _shard_map = shard_map

                @functools.wraps(_shard_map)
                def shard_map(*args, **kwargs):
                    # wraps copies __wrapped__, so signature-based
                    # capability sniffing (inspect.signature) still
                    # sees the REAL parameter list, not (*args, **kw).
                    if "check_vma" in kwargs:
                        kwargs["check_rep"] = kwargs.pop("check_vma")
                    return _shard_map(*args, **kwargs)

            jax.shard_map = shard_map
        except ImportError:  # pragma: no cover - shard_map predates 0.4
            pass


_install()
