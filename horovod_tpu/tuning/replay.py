"""Journaled-trace replay: re-drive an engine with REAL traffic.

The request journal (PR 12) already records everything a request's
re-execution needs — the original prompt, sampling params + seed,
priority class, streaming flag, and (since the ``arrival`` field)
when it arrived relative to journal open.  This module turns any
journal file into a replayable TRACE and drives a fresh engine with
it, either **open-loop at original arrival spacing** (``timing=
"original"``: each request is submitted at its recorded offset
whether or not the engine kept up — the honest load model) or
**as-fast-as-possible** (``timing="afap"``: next request the moment
the queue has room — a saturation benchmark).

Two consumers:

* the OFFLINE tuning backend: :func:`tune` runs Bayesian optimization
  over replay runs — one engine built (and warmed) per sample, scored
  by the same :class:`~horovod_tpu.tuning.tuner.Objective` the online
  tuner uses, so constructor-level knobs (``kv_dtype``, ``n_slots``,
  ``page_size``, ``spec_k``, ``paged_kernel`` — the fused Pallas
  decode kernel switch) that no live engine could ever apply are
  tunable here;
* the PERF-REGRESSION GATE (``benchmarks/replay_gate.py``): replay a
  committed miniature trace on CPU, compare the score JSON against a
  committed baseline.

Replay is also a FIDELITY check: greedy decode is a pure function of
the token sequence and sampled decode of (sequence, seed) — so every
replayed request's output must be token-identical to what the journal
recorded (complete outputs for ended entries, prefixes for requests
that were still in flight when the journal stopped).  The report
carries the comparison.

Caveat bounded by design: journal COMPACTION rewrites the file with
only LIVE entries once ``COMPACT_AFTER`` ended lines accumulate, so a
long-lived replica's journal is a sliding window, not a full history
— capture a trace by copying the journal file while the workload of
interest is in flight, or point the engine at a fresh journal path
for the capture run.

CLI::

    python -m horovod_tpu.tuning.replay trace.jsonl --seed 0 --warm 8
    python -m horovod_tpu.tuning.replay trace.jsonl --params model.pkl \\
        --afap --json score.json --set prefill_chunk_tokens=16
"""

from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["TraceRequest", "ReplayReport", "read_trace", "replay",
           "warm_lens", "tune", "main"]


@dataclass
class TraceRequest:
    """One journaled request, reconstructed for replay."""

    id: int
    prompt: Tuple[int, ...]
    max_new_tokens: int
    eos_id: Optional[int] = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    priority: str = "interactive"
    stream: bool = False
    #: monotonic offset (s) from journal open; 0.0 for pre-arrival
    #: journals (those replay in file order with no spacing).
    arrival: float = 0.0
    #: tokens the ORIGINAL run emitted (complete iff ``ended``).
    emitted: List[int] = field(default_factory=list)
    ended: bool = False


def read_trace(path: str) -> List[TraceRequest]:
    """Parse a journal file into a replayable trace.

    Unlike :meth:`RequestJournal.read_live` (the failover reader,
    which keeps only entries that never ended), this keeps EVERY begun
    entry with its full emitted-token record — ended entries are the
    fidelity oracle, live ones replay their remaining budget too.
    Tolerates a torn final line.  Entries are ordered by arrival
    offset (file order for pre-arrival journals, whose offsets are all
    0 — Python's sort is stable, so file order survives)."""
    reqs: Dict[int, TraceRequest] = {}
    order: List[int] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    except OSError:
        return []
    for line in raw.splitlines():
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn write at the capture instant
        e, rid = ev.get("e"), ev.get("id")
        if e == "b":
            samp = ev.get("samp") or [0.0, 0, 0.0, 0]
            arr = ev.get("arr") or [0.0, None]
            if rid not in reqs:
                order.append(rid)
            reqs[rid] = TraceRequest(
                id=rid, prompt=tuple(ev.get("prompt") or ()),
                max_new_tokens=int(ev.get("max_new") or 0),
                eos_id=ev.get("eos"),
                temperature=float(samp[0]), top_k=int(samp[1]),
                top_p=float(samp[2]), seed=int(samp[3]),
                priority=ev.get("pri") or "interactive",
                stream=bool(ev.get("stream")),
                arrival=float(arr[0] or 0.0))
        elif e == "t" and rid in reqs:
            reqs[rid].emitted.append(int(ev["t"]))
        elif e == "e" and rid in reqs:
            reqs[rid].ended = True
    out = [reqs[rid] for rid in order if reqs[rid].prompt]
    out.sort(key=lambda r: r.arrival)
    return out


def warm_lens(trace: Sequence[TraceRequest], engine) -> List[int]:
    """One representative prompt length per compile bucket the trace
    will touch — what :meth:`InferenceEngine.warmup` needs so replay
    measures serving, not XLA."""
    seen: Dict[int, int] = {}
    for r in trace:
        b = engine._bucket(len(r.prompt))
        seen.setdefault(b, len(r.prompt))
    return sorted(seen.values())


@dataclass
class ReplayReport:
    """The score JSON one replay run emits."""

    requests: int
    completed: int
    failed: int
    duration_s: float
    ticks: int
    tokens: int
    tokens_per_sec: float
    tokens_per_tick: float
    ttft_p99: Dict[str, float]
    preemptions: int
    decode_recompiles: int
    #: fidelity: replayed outputs compared against the journal record
    compared: int
    token_identical: int
    mismatched_ids: List[int]
    timing: str
    score: float

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


def replay(engine, trace: Sequence[TraceRequest], *,
           timing: str = "original", speed: float = 1.0,
           objective=None, max_seconds: float = 600.0) -> ReplayReport:
    """Drive ``engine`` (already warmed) with ``trace``, synchronously
    (the replay owns the tick loop — do not ``start()`` the engine).

    ``timing="original"`` submits each request at ``arrival / speed``
    seconds after replay start, stepping the engine while waiting
    (open-loop: a slow engine falls behind, which is the point);
    ``"afap"`` submits as fast as admission control accepts.
    """
    if timing not in ("original", "afap"):
        raise ValueError(f"timing must be 'original' or 'afap', "
                         f"got {timing!r}")
    from horovod_tpu.serving.scheduler import QueueFullError
    from horovod_tpu.tuning.tuner import Objective, _Window

    objective = objective or Objective()
    metrics = engine.metrics
    base = _Window(metrics)
    ticks0 = metrics.decode_ticks.value
    compiles0 = engine.decode_compilations
    preempt0 = metrics.preemptions.value

    futures: List[Tuple[TraceRequest, object]] = []
    failed = 0
    t0 = time.monotonic()
    deadline = t0 + max_seconds
    for r in sorted(trace, key=lambda x: x.arrival):
        if timing == "original":
            due = t0 + r.arrival / max(speed, 1e-9)
            while time.monotonic() < due:
                if not engine.step():
                    # idle and early: sleep the remainder in small
                    # slices so arrival spacing stays honest
                    time.sleep(min(0.001, max(0.0, due - time.monotonic())))
        streamed: List[int] = []
        on_token = (lambda tok, piece, _s=streamed: _s.append(int(tok))) \
            if r.stream else None
        while True:
            try:
                fut = engine.submit(
                    list(r.prompt), max_new_tokens=r.max_new_tokens,
                    eos_id=r.eos_id, on_token=on_token,
                    temperature=r.temperature, top_k=r.top_k,
                    top_p=r.top_p, seed=r.seed, priority=r.priority)
                futures.append((r, fut))
                break
            except QueueFullError:
                if time.monotonic() > deadline:
                    failed += 1
                    break
                engine.step()  # drain some queue, retry
            except Exception:
                failed += 1  # typed rejection (too long for this cfg…)
                break
    while (not all(f.done() for _, f in futures)
           and time.monotonic() < deadline):
        engine.step()
    duration = time.monotonic() - t0

    compared = identical = completed = 0
    mismatched: List[int] = []
    for r, fut in futures:
        if not fut.done():
            failed += 1
            continue
        try:
            toks = fut.result(timeout=0)
        except Exception:
            failed += 1
            continue
        completed += 1
        if not r.emitted:
            continue
        compared += 1
        # Ended entries recorded their COMPLETE output; a journal that
        # stopped mid-request holds a prefix — compare what exists.
        want = r.emitted if r.ended else r.emitted[:len(toks)]
        got = toks if r.ended else toks[:len(r.emitted)]
        if got == want:
            identical += 1
        else:
            mismatched.append(r.id)

    stats = base.close(max(metrics.decode_ticks.value - ticks0, 1))
    score, _ = objective.score(stats)
    ticks = metrics.decode_ticks.value - ticks0
    return ReplayReport(
        requests=len(trace), completed=completed, failed=failed,
        duration_s=round(duration, 4), ticks=ticks,
        tokens=stats.tokens,
        tokens_per_sec=round(stats.tokens / max(duration, 1e-9), 3),
        tokens_per_tick=round(stats.tokens / max(ticks, 1), 4),
        ttft_p99={k: round(v, 6) for k, v in stats.ttft_p99.items()},
        preemptions=metrics.preemptions.value - preempt0,
        decode_recompiles=engine.decode_compilations - compiles0,
        compared=compared, token_identical=identical,
        mismatched_ids=mismatched[:32], timing=timing,
        score=round(score, 6))


def tune(build_engine: Callable[[Dict], object],
         trace: Sequence[TraceRequest], *,
         bounds: Dict[str, Tuple[float, float]],
         samples: int = 8, seed: int = 0, timing: str = "afap",
         objective=None) -> Dict:
    """Offline Bayesian optimization over replay runs.

    ``build_engine(settings)`` must return a WARMED engine constructed
    with the integer-rounded ``settings`` (one fresh engine per sample
    — constructor knobs are fair game here).  ``bounds`` maps knob
    name -> (lo, hi) inclusive.  Returns the winning settings, their
    report, and the full objective trajectory."""
    from horovod_tpu.tuning.gp import BayesianOptimizer

    names = sorted(bounds)
    bo = BayesianOptimizer(
        bounds=[tuple(map(float, bounds[n])) for n in names], seed=seed)
    history: List[Dict] = []
    best: Optional[Dict] = None
    for i in range(samples):
        x = bo.suggest()
        settings = {n: int(round(float(x[j])))
                    for j, n in enumerate(names)}
        engine = build_engine(settings)
        try:
            report = replay(engine, trace, timing=timing,
                            objective=objective)
        finally:
            stop = getattr(engine, "stop", None)
            if stop is not None:
                try:
                    stop()
                except Exception:
                    pass
        bo.register([float(settings[n]) for n in names], report.score)
        entry = {"sample": i + 1, "settings": settings,
                 "score": report.score,
                 "report": report.to_json()}
        history.append(entry)
        if best is None or report.score > best["score"]:
            best = entry
    return {"best": best, "trajectory": [
        {"sample": h["sample"], "settings": h["settings"],
         "score": h["score"]} for h in history]}


def _build_cli_engine(args, settings: Optional[Dict] = None):
    """Model + engine from the replica_main flag conventions (shared
    loader — a replayed replica and a live one must agree on what a
    ``--params`` pickle means)."""
    from horovod_tpu import serving
    from horovod_tpu.serving.router.replica_main import (
        build_model,
        load_model,
    )

    if args.params:
        params, cfg = load_model(args.params)
    else:
        params, cfg = build_model(args)
    overrides = dict(args.set or {})
    if settings:
        overrides.update(settings)
    ecfg_kw = dict(
        n_slots=args.slots, max_len=cfg.max_seq,
        max_queue_depth=args.max_queue_depth,
        max_prefills_per_tick=args.max_prefills_per_tick,
        prefill_chunk_tokens=args.chunk,
        tick_timeout=0.0)   # synchronous replay: no watchdog thread
    ecfg_kw.update(overrides)
    engine = serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**ecfg_kw))
    return engine


def _parse_set(text: str) -> Tuple[str, object]:
    """``name=value`` -> (name, typed value) for EngineConfig fields."""
    if "=" not in text:
        raise ValueError(f"--set wants name=value, got {text!r}")
    name, raw = text.split("=", 1)
    for cast in (int, float):
        try:
            return name, cast(raw)
        except ValueError:
            pass
    if raw in ("true", "True"):
        return name, True
    if raw in ("false", "False"):
        return name, False
    if raw in ("none", "None"):
        return name, None
    return name, raw


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.tuning.replay",
        description="replay a journaled serving trace and emit a "
                    "score JSON (offline tuning backend + perf gate)")
    ap.add_argument("trace", help="journal JSONL file to replay")
    ap.add_argument("--params", default="",
                    help="model pickle from replica_main.dump_model()")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=48)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--max-prefills-per-tick", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=0,
                    help="prefill_chunk_tokens (0 = whole-prompt)")
    ap.add_argument("--set", type=_parse_set, action="append",
                    default=[], metavar="FIELD=VALUE",
                    help="override any EngineConfig field "
                         "(repeatable), e.g. --set kv_dtype=bf16")
    ap.add_argument("--afap", action="store_true",
                    help="submit as fast as admission control accepts "
                         "instead of at original arrival spacing")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="arrival-spacing speedup for original timing "
                         "(2.0 = replay at twice the recorded rate)")
    ap.add_argument("--json", default="",
                    help="write the score JSON here (also printed)")
    args = ap.parse_args(argv)
    args.set = dict(args.set)

    trace = read_trace(args.trace)
    if not trace:
        print(json.dumps({"error": f"no requests in {args.trace}"}))
        return 2
    engine = _build_cli_engine(args)
    engine.warmup(warm_lens(trace, engine))
    report = replay(engine, trace,
                    timing="afap" if args.afap else "original",
                    speed=args.speed)
    blob = report.to_json()
    print(json.dumps(blob))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(blob, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0 if report.token_identical == report.compared else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
