"""Self-tuning serving: Bayesian autotuning over serving knobs plus a
journaled-trace replay harness (docs/serving.md "Autotuning").

The paper's signature layer-2 subsystem — ``ParameterManager`` scoring
live throughput and tuning knobs by GP/EI Bayesian optimization —
re-designed for the serving engine:

* :mod:`~horovod_tpu.tuning.gp` — the ``common/optim/`` math in NumPy
  (RBF-kernel GP with a conditioning guard, Expected-Improvement
  acquisition, categorical sweep);
* :mod:`~horovod_tpu.tuning.params` — the tunable-knob registry with
  COMPILE-SAFE bounds: every online candidate maps to an
  already-warmed executable shape, so tuning never triggers a
  mid-serving XLA compile;
* :mod:`~horovod_tpu.tuning.tuner` — the online tuner driven from the
  engine's tick loop (``EngineConfig.autotune``): perturb per scoring
  window, score against the existing SLO metrics, converge and pin,
  roll back constraint violations;
* :mod:`~horovod_tpu.tuning.replay` — reconstruct a journaled traffic
  trace and re-drive an engine at original arrival spacing (or
  as-fast-as-possible): the offline tuning backend and the
  perf-regression gate (``python -m horovod_tpu.tuning.replay``).
"""

from horovod_tpu.tuning.gp import (
    BayesianOptimizer,
    CategoricalSweep,
    ExpectedImprovement,
    GaussianProcess,
)
from horovod_tpu.tuning.params import (
    Knob,
    KnobSpace,
    apply_settings,
    online_knob_space,
)
from horovod_tpu.tuning.tuner import (
    Objective,
    OnlineTuner,
    WindowStats,
)
from horovod_tpu.tuning.replay import (
    ReplayReport,
    TraceRequest,
    read_trace,
    replay,
)

__all__ = [
    "GaussianProcess", "ExpectedImprovement", "BayesianOptimizer",
    "CategoricalSweep",
    "Knob", "KnobSpace", "online_knob_space", "apply_settings",
    "Objective", "OnlineTuner", "WindowStats",
    "TraceRequest", "ReplayReport", "read_trace", "replay",
]
