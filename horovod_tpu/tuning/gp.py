"""GP regression + Expected-Improvement Bayesian optimization — the
``common/optim/`` math (``gaussian_process.{h,cc}``,
``bayesian_optimization.h``) in NumPy, shared by the online serving
tuner (:mod:`horovod_tpu.tuning.tuner`) and the offline replay tuner
(:mod:`horovod_tpu.tuning.replay`).

This is the serving twin of the training-side port in
:mod:`horovod_tpu.autotune` with two hardenings the serving loop
needs:

* a kernel-matrix CONDITIONING GUARD: serving scores repeat (two
  windows at the same knob can score near-identically, and the online
  tuner revisits pinned points), which drives the RBF Gram matrix
  toward singularity.  ``fit`` escalates the diagonal jitter by 10x
  per Cholesky failure up to ``max_jitter`` instead of raising out of
  the engine's tick loop;
* ``maximize=False`` support, because serving objectives mix
  directions (throughput up, p99 TTFT down) — the optimizer works on
  a single scalar but each knob declares its direction in
  :mod:`horovod_tpu.tuning.params`.

Problem sizes are tiny (≤ a few dozen samples, ≤ 4 dims), so exact
Cholesky inference on the host is the right tool — no Eigen, no GPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "GaussianProcess",
    "ExpectedImprovement",
    "BayesianOptimizer",
    "CategoricalSweep",
]


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz & Stegun 7.1.26, vectorized; |error| < 1.5e-7 —
    # plenty for an acquisition argmax.
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    y = 1.0 - (
        ((((1.061405429 * t - 1.453152027) * t) + 1.421413741) * t
         - 0.284496736) * t + 0.254829592
    ) * t * np.exp(-x * x)
    return sign * y


class GaussianProcess:
    """RBF-kernel GP with exact Cholesky inference and a jitter-
    escalation conditioning guard.

    ``k(a, b) = exp(-0.5 |a-b|^2 / length_scale^2)``; targets are
    normalized to zero mean / unit variance before the solve (the
    reference normalizes the same way), so ``length_scale`` and
    ``noise`` are scale-free.
    """

    def __init__(self, length_scale: float = 0.3,
                 noise: float = 1e-6, max_jitter: float = 1e-2) -> None:
        self.length_scale = float(length_scale)
        self.noise = float(noise)
        self.max_jitter = float(max_jitter)
        #: jitter actually used by the last ``fit`` (== ``noise``
        #: unless the conditioning guard escalated it).
        self.last_jitter = float(noise)
        self._x: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = a[:, None, :] - b[None, :, :]
        sq = np.sum(d * d, axis=-1)
        return np.exp(-0.5 * sq / (self.length_scale ** 2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        if x.shape[0] != y.shape[0]:
            raise ValueError(
                f"GP fit: {x.shape[0]} inputs vs {y.shape[0]} targets")
        self._x = x
        self._ymean = float(y.mean()) if y.size else 0.0
        self._ystd = float(y.std()) + 1e-12
        yn = (y - self._ymean) / self._ystd
        k = self._kernel(x, x)
        # Conditioning guard: duplicate / near-duplicate rows (repeat
        # scores at a pinned knob) make K singular.  Escalate the
        # diagonal jitter instead of letting LinAlgError escape into
        # the serving tick loop.
        jitter = self.noise
        while True:
            try:
                self._L = np.linalg.cholesky(k + jitter * np.eye(len(x)))
                break
            except np.linalg.LinAlgError:
                jitter *= 10.0
                if jitter > self.max_jitter:
                    raise
        self.last_jitter = jitter
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at ``x`` (denormalized)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._L, ks.T)
        var = np.clip(1.0 - np.sum(v * v, axis=0), 1e-12, None)
        return mu * self._ystd + self._ymean, np.sqrt(var) * self._ystd


class ExpectedImprovement:
    """EI acquisition (``bayesian_optimization.h:93``):
    ``EI(u) = (mu - best - xi) Phi(z) + sigma phi(z)``."""

    def __init__(self, xi: float = 0.01) -> None:
        self.xi = float(xi)

    def __call__(self, gp: GaussianProcess, u: np.ndarray,
                 best: float) -> np.ndarray:
        mu, sigma = gp.predict(u)
        imp = mu - best - self.xi
        z = imp / sigma
        phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1 + _erf(z / np.sqrt(2)))
        ei = imp * Phi + sigma * phi
        ei[sigma < 1e-10] = 0.0
        return ei


class BayesianOptimizer:
    """EI-driven maximizer over a box domain.

    ``register(x, y)`` feeds observed (knobs, score) pairs;
    ``suggest()`` returns the next point — random exploration while
    fewer than ``bootstrap`` samples exist, then the EI argmax over
    ``n_candidates`` uniform candidates (equivalent to the reference's
    L-BFGS restarts at these dimensionalities).  All randomness comes
    from the seeded ``RandomState``, so two optimizers built with the
    same seed propose the same trajectory — the property the online
    tuner's determinism tests rely on.
    """

    def __init__(self, bounds: Sequence[Tuple[float, float]], *,
                 xi: float = 0.01, seed: int = 0,
                 bootstrap: int = 3, n_candidates: int = 512) -> None:
        self.bounds = np.asarray(bounds, np.float64)
        if self.bounds.ndim != 2 or self.bounds.shape[1] != 2:
            raise ValueError(f"bounds must be (d, 2), got {self.bounds.shape}")
        self.gp = GaussianProcess(length_scale=0.3)
        self.acq = ExpectedImprovement(xi=xi)
        self.bootstrap = int(bootstrap)
        self.n_candidates = int(n_candidates)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self._rng = np.random.RandomState(seed)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / np.maximum(hi - lo, 1e-12)

    def _denormalize(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def register(self, x: Sequence[float], y: float) -> None:
        self.xs.append(self._normalize(np.asarray(x, np.float64)))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    @property
    def best(self) -> Tuple[np.ndarray, float]:
        """(knobs, score) of the best observation so far."""
        i = int(np.argmax(self.ys))
        return self._denormalize(self.xs[i]), self.ys[i]

    def suggest(self) -> np.ndarray:
        if len(self.xs) < self.bootstrap:
            return self._denormalize(self._rng.rand(self.bounds.shape[0]))
        cand = self._rng.rand(self.n_candidates, self.bounds.shape[0])
        ei = self.acq(self.gp, cand, best=max(self.ys))
        return self._denormalize(cand[int(np.argmax(ei))])


@dataclass
class CategoricalSweep:
    """Chained exhaustive sweep over discrete knobs — the
    ``CategoricalParameterChain`` half of the reference's
    ``ParameterManager`` split: categoricals are swept one value per
    scoring window (others held), best fixed before moving on;
    continuous knobs go to the jointly-BO'd half.

    ``names[i]`` has candidates ``values[i]``; ``values[i][0]`` must
    be what the system is ACTUALLY running when the sweep starts (the
    first window's score is attributed to it without an apply).

    Drive it with ``current()`` (the settings dict to run next) and
    ``observe(score)`` (returns True while the sweep is still live).
    """

    names: List[str]
    values: List[List]

    def __post_init__(self) -> None:
        if len(self.names) != len(self.values):
            raise ValueError("names/values length mismatch")
        for name, vals in zip(self.names, self.values):
            if not vals:
                raise ValueError(f"categorical {name!r} has no values")
        self._i = 0          # which param is being swept
        self._j = 0          # which value of that param
        self._scores: List[float] = []
        self._fixed: Dict[str, object] = {
            n: v[0] for n, v in zip(self.names, self.values)}
        self.done = not self.names

    def current(self) -> Dict[str, object]:
        """Settings to run for the NEXT scoring window."""
        out = dict(self._fixed)
        if not self.done:
            out[self.names[self._i]] = self.values[self._i][self._j]
        return out

    def observe(self, score: float) -> bool:
        """Record the window score for ``current()``.  Returns True
        while more sweep windows remain."""
        if self.done:
            return False
        self._scores.append(float(score))
        param = self.names[self._i]
        if self._j + 1 < len(self.values[self._i]):
            self._j += 1
            return True
        # This param's sweep is complete: pin its best value.
        best = int(np.argmax(self._scores))
        self._fixed[param] = self.values[self._i][best]
        self._scores = []
        self._j = 0
        self._i += 1
        self.done = self._i >= len(self.names)
        return not self.done

    @property
    def fixed(self) -> Dict[str, object]:
        """Best-so-far pinned values (all params once ``done``)."""
        return dict(self._fixed)
