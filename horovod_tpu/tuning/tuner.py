"""Online serving autotuner — the reference ``ParameterManager``
driven from the engine's tick loop.

Enable with ``EngineConfig(autotune=True)``: after :meth:`warmup` the
engine installs an :class:`OnlineTuner` over the compile-safe knob
space :func:`~horovod_tpu.tuning.params.online_knob_space` derives,
and every :meth:`~horovod_tpu.serving.engine.InferenceEngine.step`
calls :meth:`OnlineTuner.on_tick` after the tick's work (outside the
step lock — applies re-acquire it, so the swap is a clean tick
boundary, the serving analogue of
``Controller::SynchronizeParameters``).

The reference phase machine, re-used shape for shape
(``parameter_manager.h:42-246``):

1. **warmup** — ``warmup_windows`` scoring windows discarded;
2. **sweep** — categorical knobs by chained exhaustive sweep
   (:class:`~horovod_tpu.tuning.gp.CategoricalSweep`): one value per
   window, best pinned per knob;
3. **bo** — the joint integer box (``max_prefills_per_tick``, chunk
   budget) by GP + Expected Improvement for ``bo_samples`` windows;
4. **pinned** — frozen at the best constraint-satisfying sample
   (defaults, when nothing beat them).

Scoring is pure observation: one window = ``window_ticks`` WORKED
ticks (idle ticks don't advance it), scored from deltas of the
metrics the engine already keeps — ``tokens_generated`` per tick
(speculation's multiplier folds in automatically),
``serving_ttft_seconds{class=}`` windowed p99 (cumulative bucket
counts diffed at the window edges), and preemptions.  The weighted
objective carries per-class TTFT SLO constraints: a sample whose p99
exceeds its class SLO is penalized proportionally, and one beyond
``slo x (1 + guard_band)`` is ROLLED BACK — the last known-good
setting is re-applied immediately, the violating score still teaches
the GP where not to go.

Because every knob in the online space is admission/batching policy,
no sample can change emitted tokens (the engine's token-identity
invariant holds for any admission order) and no sample can compile
(the params.py contract) — tuning is oracle-safe and compile-stable
by construction, which ``tests/test_tuning.py`` enforces.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from horovod_tpu.obs import tracing as obs_tracing
from horovod_tpu.tuning.gp import BayesianOptimizer, CategoricalSweep
from horovod_tpu.tuning.params import (
    KnobSpace,
    apply_settings,
    online_knob_space,
)

__all__ = ["Objective", "WindowStats", "OnlineTuner"]


@dataclass
class WindowStats:
    """What one scoring window observed (metric deltas)."""

    ticks: int
    tokens: int
    preemptions: int
    ttft_p99: Dict[str, float]    # class -> windowed p99 seconds
    spec_acceptance: Optional[float] = None

    @property
    def tokens_per_tick(self) -> float:
        return self.tokens / max(self.ticks, 1)


@dataclass
class Objective:
    """Weighted serving objective with per-class TTFT SLO constraints.

    ``score = tokens_per_tick
              - ttft_weight * sum_c max(0, p99_c - slo_c) / slo_c
              - preempt_weight * preemptions_per_tick``

    Speculation needs no term of its own: accepted drafts raise
    tokens_per_tick, wasted drafts lower it through slower ticks.
    ``score()`` also returns each class's fractional SLO excess so the
    tuner can apply its rollback guard band.
    """

    ttft_slo: Dict[str, float] = field(
        default_factory=lambda: {"interactive": 0.5})
    ttft_weight: float = 2.0
    preempt_weight: float = 0.5

    def score(self, w: WindowStats) -> Tuple[float, Dict[str, float]]:
        s = w.tokens_per_tick
        excess: Dict[str, float] = {}
        for cls, slo in self.ttft_slo.items():
            p99 = w.ttft_p99.get(cls)
            if p99 is None or slo <= 0:
                continue
            over = max(0.0, p99 - slo) / slo
            if over > 0:
                excess[cls] = over
                s -= self.ttft_weight * over
        s -= self.preempt_weight * (w.preemptions / max(w.ticks, 1))
        return s, excess


class _Window:
    """Baseline snapshot of the cumulative metrics a window diffs."""

    def __init__(self, metrics):
        self.metrics = metrics
        self.tokens = metrics.tokens_generated.value
        self.preempt = metrics.preemptions.value
        self.drafted = metrics.spec_drafted.value
        self.accepted = metrics.spec_accepted.value
        self.ttft = {key[0]: child.state()
                     for key, child in metrics.ttft.children()}

    @staticmethod
    def _p99(now: Dict, base: Optional[Dict]) -> Optional[float]:
        counts = list(now["counts"])
        if base is not None:
            counts = [a - b for a, b in zip(counts, base["counts"])]
        total = sum(counts)
        if not total:
            return None
        buckets = now["buckets"]
        rank, cum = 0.99 * total, 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return buckets[i] if i < len(buckets) else buckets[-1]
        return buckets[-1]

    def close(self, ticks: int) -> WindowStats:
        m = self.metrics
        p99 = {}
        for key, child in m.ttft.children():
            v = self._p99(child.state(), self.ttft.get(key[0]))
            if v is not None:
                p99[key[0]] = v
        drafted = m.spec_drafted.value - self.drafted
        acc = None
        if drafted > 0:
            acc = (m.spec_accepted.value - self.accepted) / drafted
        return WindowStats(
            ticks=ticks,
            tokens=m.tokens_generated.value - self.tokens,
            preemptions=m.preemptions.value - self.preempt,
            ttft_p99=p99, spec_acceptance=acc)


class OnlineTuner:
    """Tick-driven knob tuner over one engine's compile-safe space."""

    #: trajectory entries kept for /tuning (full history lives in the
    #: timeline instants)
    MAX_TRAJECTORY = 128

    def __init__(self, space: KnobSpace, *,
                 objective: Optional[Objective] = None,
                 window_ticks: int = 32, warmup_windows: int = 1,
                 bo_samples: int = 10, guard_band: float = 0.5,
                 seed: int = 0):
        self.space = space
        self.objective = objective or Objective()
        self.window_ticks = int(window_ticks)
        self.warmup_windows = int(warmup_windows)
        self.bo_samples = int(bo_samples)
        self.guard_band = float(guard_band)
        self._lock = threading.Lock()
        self._defaults = space.defaults()
        self._current: Dict[str, object] = dict(self._defaults)
        self._best: Tuple[Optional[float], Dict] = (None, dict(self._defaults))
        self._samples = 0
        self._rollbacks = 0
        self._discard = 0
        self._warmups_left = self.warmup_windows
        self._ticks = 0
        self._window: Optional[_Window] = None
        self._trajectory: List[Dict] = []
        self._sweep: Optional[CategoricalSweep] = None
        self._bo: Optional[BayesianOptimizer] = None
        self._bo_x: Optional[List[float]] = None
        sweep_knobs = space.sweep_knobs
        if sweep_knobs:
            self._sweep = CategoricalSweep(
                names=[k.name for k in sweep_knobs],
                values=[list(k.candidates) for k in sweep_knobs])
        bo_knobs = space.bo_knobs
        if bo_knobs:
            self._bo = BayesianOptimizer(
                bounds=[(float(k.bounds[0]), float(k.bounds[1]))
                        for k in bo_knobs], seed=seed)
        if not space.knobs:
            self.phase = "pinned"     # nothing tunable: inert
        elif self._warmups_left > 0:
            self.phase = "warmup"
        else:
            self.phase = "sweep" if self._sweep is not None else "bo"

    # -- installation -------------------------------------------------------

    @classmethod
    def install(cls, engine, **kw) -> "OnlineTuner":
        """Derive the compile-safe space from a WARMED engine, attach
        the tuner, return it.  Idempotent per engine."""
        if getattr(engine, "_tuner", None) is not None:
            return engine._tuner
        tuner = cls(online_knob_space(engine), **kw)
        engine._tuner = tuner
        return tuner

    # -- tick hook (called by InferenceEngine.step, outside its lock) -------

    def on_tick(self, engine, worked: bool) -> None:
        with self._lock:
            metrics = engine.metrics
            if self._window is None or self._window.metrics is not metrics:
                # First tick, or a benchmark swapped in a fresh
                # ServingMetrics: cumulative deltas against the old
                # object would go negative — restart the window.
                self._window = _Window(metrics)
                self._ticks = 0
                return
            if self.phase == "pinned" or not worked:
                return
            self._ticks += 1
            if self._ticks < self.window_ticks:
                return
            stats = self._window.close(self._ticks)
            self._window = _Window(metrics)
            self._ticks = 0
            self._advance(engine, stats)

    def reset_window(self) -> None:
        """Drop the current scoring window's baseline — called by the
        engine's supervised-restart path (``_recover``), so the first
        post-restart window starts from post-restart counters instead
        of scoring the crash (dead time, resume re-prefills, inflated
        TTFT) against whatever knob setting happened to be live."""
        with self._lock:
            self._window = None
            self._ticks = 0

    def _advance(self, engine, stats: WindowStats) -> None:
        metrics = engine.metrics
        if self.phase == "warmup":
            self._warmups_left -= 1
            if self._warmups_left <= 0:
                self.phase = "sweep" if self._sweep is not None else "bo"
                self._propose(engine)
            return
        if self._discard > 0:
            # Settling: requests admitted under the previous setting
            # are still in flight — this window's score would blend
            # two settings.
            self._discard -= 1
            return
        score, excess = self.objective.score(stats)
        self._samples += 1
        violated = any(v > self.guard_band for v in excess.values())
        metrics.tuning_samples.inc()
        metrics.tuning_objective.set(score)
        entry = {
            "sample": self._samples, "phase": self.phase,
            "settings": dict(self._current), "objective": round(score, 6),
            "tokens_per_tick": round(stats.tokens_per_tick, 4),
            "ttft_p99": {k: round(v, 6) for k, v in stats.ttft_p99.items()},
            "violated": violated,
        }
        self._trajectory.append(entry)
        del self._trajectory[:-self.MAX_TRAJECTORY]
        obs_tracing.instant("tuning.sample", {
            "sample": self._samples, "phase": self.phase,
            "objective": round(score, 6), "violated": violated,
            **{f"knob.{k}": v for k, v in self._current.items()}})
        if not violated and (self._best[0] is None or score > self._best[0]):
            self._best = (score, dict(self._current))
            metrics.tuning_best_objective.set(score)
        # Feed the active optimizer FIRST (a violating sample still
        # teaches the GP where not to go), then pick the next setting
        # — or roll back to known-good if this one breached the band.
        if self.phase == "sweep":
            assert self._sweep is not None
            alive = self._sweep.observe(score)
            if alive:
                self._apply(engine, self._sweep.current())
            else:
                pinned = self._sweep.fixed
                self._current.update(pinned)
                if self._bo is not None:
                    self.phase = "bo"
                    self._apply(engine, pinned)
                else:
                    self._pin(engine, extra=pinned)
        elif self.phase == "bo":
            assert self._bo is not None
            x = self._bo_x or [float(self._current[k.name])
                               for k in self.space.bo_knobs]
            self._bo.register(x, score)
            if len(self._bo.ys) >= self.bo_samples:
                self._pin(engine)
            else:
                self._propose(engine)
        if violated and self.phase != "pinned":
            self._rollback(engine, metrics)

    def _propose(self, engine) -> None:
        """Apply the next setting for the (new) current phase."""
        if self.phase == "sweep" and self._sweep is not None:
            self._apply(engine, self._sweep.current())
        elif self.phase == "bo" and self._bo is not None:
            if not self._bo.ys:
                # First BO window scores the settings ALREADY running
                # (the sweep's pinned categoricals + BO defaults) — the
                # reference credits the incumbent before exploring.
                self._bo_x = [float(self._current[k.name])
                              for k in self.space.bo_knobs]
                return
            u = self._bo.suggest()
            proposal = {k.name: u[i]
                        for i, k in enumerate(self.space.bo_knobs)}
            clamped = self.space.clamp(proposal)
            self._bo_x = [float(clamped[k.name])
                          for k in self.space.bo_knobs]
            self._apply(engine, clamped)

    def _apply(self, engine, settings: Dict[str, object]) -> None:
        settings = self.space.clamp(settings)
        changed = {k: v for k, v in settings.items()
                   if self._current.get(k) != v}
        self._current.update(settings)
        if not changed:
            return
        with engine._lock:   # tick boundary: step() is not mid-tick
            apply_settings(engine, changed)
        by_name = {k.name: k for k in self.space.knobs}
        self._discard = max(
            (by_name[n].discard_windows for n in changed if n in by_name),
            default=0)

    def _rollback(self, engine, metrics) -> None:
        """A sample breached an SLO constraint beyond the guard band:
        re-apply the last known-good settings NOW instead of waiting
        out another proposal cycle."""
        self._rollbacks += 1
        metrics.tuning_rollbacks.inc()
        good = self._best[1] if self._best[0] is not None \
            else dict(self._defaults)
        obs_tracing.instant("tuning.rollback", {
            "sample": self._samples,
            **{f"knob.{k}": v for k, v in good.items()}})
        self._apply(engine, dict(good))

    def _pin(self, engine, extra: Optional[Dict] = None) -> None:
        """Converge: freeze at the best constraint-satisfying sample."""
        best = dict(self._best[1])
        if extra:
            best.update({k: v for k, v in extra.items() if k not in best})
        self.phase = "pinned"
        self._apply(engine, best)
        obs_tracing.instant("tuning.pinned", {
            "samples": self._samples,
            "objective": self._best[0],
            **{f"knob.{k}": v for k, v in best.items()}})

    # -- introspection (GET /tuning, /stats) --------------------------------

    @property
    def converged(self) -> bool:
        return self.phase == "pinned"

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "phase": self.phase,
                "samples": self._samples,
                "rollbacks": self._rollbacks,
                "window_ticks": self.window_ticks,
                "current": dict(self._current),
                "best": {
                    "objective": self._best[0],
                    "settings": dict(self._best[1]),
                },
                "constraints": dict(self.objective.ttft_slo),
                "guard_band": self.guard_band,
                "space": self.space.describe(),
                "trajectory": list(self._trajectory),
            }
