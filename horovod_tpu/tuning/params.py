"""Tunable serving knobs with COMPILE-SAFE bounds.

The reference ``ParameterManager`` tunes knobs whose application is
free (buffer sizes, cycle times).  A serving engine's knobs are not
free by default: most config fields select XLA *shapes*, and an XLA
compile inside the serving loop blows the watchdog budget and every
latency SLO.  This module is the contract that makes online tuning
safe: a knob enters the online space ONLY with candidate values that
map to executables the engine has ALREADY WARMED, so the tuner can
apply any sample at any tick boundary and the engine never traces —
``decode_compilations`` stays at the warmed count across the whole
tuning trajectory (the acceptance guard in ``tests/test_tuning.py``).

The online space, derived from a warmed engine by
:func:`online_knob_space`:

* ``max_prefills_per_tick`` — BO'd integer in ``[1, warmed_kmax]``:
  warmup compiled batched prefill for every k up to the construction
  value, so any smaller k is a warm shape.  Applied by rebuilding the
  frozen ``EngineConfig`` (``dataclasses.replace``) AND mutating the
  live ``Scheduler.max_prefills_per_tick`` — both read the knob.
* ``prefill_chunk_tokens`` — BO'd integer WITHIN the warmed chunk
  bucket ``(B/2, B]`` (present only when chunking is on): every value
  in that interval buckets to the same power-of-two compile shape
  (``_ingest_step`` pads each chunk to ``_bucket(chunk)``), so the
  knob moves the per-tick ingestion/admission token budget at
  constant shape.  Cross-bucket moves mint new prefill + suffix
  shapes and are OFFLINE (replay) territory.
* ``page_grant_ahead`` — swept categorical {0, 1, 2} pages: how far
  past the write position decode growth grants pages
  (``_ensure_write_page``).  Pure page-table data — trades grant-call
  overhead against page-pressure eviction headroom.
* ``spec_enabled`` — swept categorical {on, off} (speculative engines
  only): both tick executables (draft/verify and plain) are warmed by
  construction, and the toggle is admission-mask DATA
  (``_spec_runtime_enabled``), so flipping it never compiles and —
  like every knob here — never changes emitted tokens.

Every knob also declares its score direction (informational — the
tuner scalarizes one weighted objective), the number of scoring
windows to DISCARD after an apply (settling time: in-flight requests
still reflect the old setting), and a human-readable apply path for
``GET /tuning`` and the docs table.

Constructor-level knobs (``kv_dtype``, ``n_slots``, ``page_size``,
``spec_k``, ``paged_kernel``) cannot be applied to a live engine at any
price — they are the offline space :mod:`horovod_tpu.tuning.replay`
explores by rebuilding an engine per sample (``paged_kernel`` is baked
into the tick executables at trace time, exactly like ``kv_dtype``:
``--set paged_kernel=true`` on a replay run A/Bs the fused Pallas
decode kernel against the unfused gather path).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Tuple

__all__ = ["Knob", "KnobSpace", "online_knob_space", "apply_settings"]


@dataclass(frozen=True)
class Knob:
    """One tunable knob and its compile-safe candidate set.

    ``kind`` routes it to the reference split: ``"sweep"`` knobs are
    exhaustively swept (``CategoricalSweep``), ``"bo"`` knobs form the
    jointly-BO'd box (integer-valued; suggestions are rounded then
    clamped).  ``candidates`` (sweep) / ``bounds`` (bo, inclusive)
    contain ONLY values the warmed engine can apply without tracing.
    """

    name: str
    default: object
    kind: str                      # "sweep" | "bo"
    candidates: Tuple = ()
    bounds: Tuple[int, int] = (0, 0)
    direction: str = "max"         # which way better scores move it
    #: scoring windows to discard after applying a new value —
    #: requests admitted under the old setting are still in flight.
    discard_windows: int = 1
    apply_path: str = ""           # human-readable, for /tuning + docs

    def clamp(self, value):
        if self.kind == "bo":
            lo, hi = self.bounds
            return int(min(max(int(round(float(value))), lo), hi))
        return value if value in self.candidates else self.default


class KnobSpace:
    """The online knob set for ONE engine, with apply machinery."""

    def __init__(self, knobs: List[Knob]):
        self.knobs = list(knobs)
        by_name = [k.name for k in knobs]
        if len(set(by_name)) != len(by_name):
            raise ValueError(f"duplicate knob names: {by_name}")

    @property
    def sweep_knobs(self) -> List[Knob]:
        return [k for k in self.knobs if k.kind == "sweep"]

    @property
    def bo_knobs(self) -> List[Knob]:
        return [k for k in self.knobs if k.kind == "bo"]

    def defaults(self) -> Dict[str, object]:
        return {k.name: k.default for k in self.knobs}

    def clamp(self, settings: Dict[str, object]) -> Dict[str, object]:
        """Round/clamp a proposal into the compile-safe set (unknown
        keys dropped — a stale proposal must never reach the engine)."""
        known = {k.name: k for k in self.knobs}
        return {name: known[name].clamp(v)
                for name, v in settings.items() if name in known}

    def describe(self) -> List[Dict]:
        """The /tuning + docs view of the space."""
        out = []
        for k in self.knobs:
            out.append({
                "name": k.name, "kind": k.kind,
                "default": k.default,
                "candidates": list(k.candidates) if k.kind == "sweep"
                else list(range(k.bounds[0], k.bounds[1] + 1)),
                "direction": k.direction,
                "discard_windows": k.discard_windows,
                "apply": k.apply_path,
            })
        return out


def online_knob_space(engine) -> KnobSpace:
    """Derive the compile-safe online space from a WARMED engine.

    Bounds come from the engine's actual warmed state — the prefill
    compile cache and construction-time config — never from what a
    config "could" support: a knob value outside what warmup compiled
    would trace mid-serving.
    """
    cfg = engine.engine_cfg
    knobs: List[Knob] = []

    # Warmup compiles batched prefill for every k in [1, kmax]:
    # any k <= the construction value is a warm shape.
    kmax = min(cfg.max_prefills_per_tick, cfg.n_slots)
    if kmax > 1:
        knobs.append(Knob(
            name="max_prefills_per_tick", default=kmax, kind="bo",
            bounds=(1, kmax),
            apply_path="EngineConfig replace + Scheduler."
                       "max_prefills_per_tick at the tick boundary"))

    # Chunk budget: only within the warmed power-of-two bucket — every
    # value in (B/2, B] pads to the same compile shape.
    chunk = cfg.prefill_chunk_tokens
    if chunk > 0:
        bucket = engine._bucket(chunk)
        lo = bucket // 2 + 1
        if bucket > lo:
            knobs.append(Knob(
                name="prefill_chunk_tokens", default=chunk, kind="bo",
                bounds=(lo, bucket),
                apply_path=f"EngineConfig replace; moves inside the "
                           f"warmed {bucket}-token chunk bucket"))

    if cfg.paged:
        knobs.append(Knob(
            name="page_grant_ahead", default=cfg.page_grant_ahead,
            kind="sweep",
            candidates=tuple(sorted({cfg.page_grant_ahead, 0, 1, 2})),
            apply_path="EngineConfig replace; page-table data only "
                       "(_ensure_write_page grant-ahead span)"))

    if getattr(engine, "_spec", False):
        knobs.append(Knob(
            name="spec_enabled", default=True, kind="sweep",
            candidates=(True, False),
            apply_path="engine._spec_runtime_enabled admission mask "
                       "(both tick executables pre-warmed)"))

    return KnobSpace(knobs)


def apply_settings(engine, settings: Dict[str, object]) -> Dict[str, object]:
    """THE apply path — the serving analogue of
    ``Controller::SynchronizeParameters``: swap knob values into a
    live engine at a tick boundary.  Caller holds the engine step lock
    (the tuner's on-tick hook runs inside :meth:`InferenceEngine.step`)
    or owns the engine exclusively (replay).  Returns what was
    actually applied."""
    applied: Dict[str, object] = {}
    cfg_updates: Dict[str, object] = {}
    for name, value in settings.items():
        if name == "max_prefills_per_tick":
            cfg_updates[name] = int(value)
            engine.scheduler.max_prefills_per_tick = int(value)
        elif name in ("prefill_chunk_tokens", "page_grant_ahead"):
            cfg_updates[name] = int(value)
        elif name == "spec_enabled":
            engine._spec_runtime_enabled = bool(value)
        else:
            continue
        applied[name] = settings[name]
    if cfg_updates:
        # EngineConfig is frozen by design — the swap is a replace +
        # reassign, atomic at the tick boundary the caller guarantees.
        engine.engine_cfg = dataclasses.replace(
            engine.engine_cfg, **cfg_updates)
    return applied
