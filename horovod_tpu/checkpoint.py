"""Checkpoint/resume with the reference's consistency conventions.

The reference has no core checkpoint system — it delegates storage to the
framework and provides the *consistency* primitives (SURVEY.md §5.4):
rank-0-only saving (``examples/keras_imagenet_resnet50.py``), broadcast of
restored state (``BroadcastGlobalVariablesHook``,
``broadcast_optimizer_state``), and Keras ``hvd.load_model`` that rewraps
the optimizer on load.

TPU-native storage: orbax (sharding-aware, async-capable).  This module
packages the conventions over it:

* :func:`save` — rank 0 writes (every process must still call it for
  multi-host orbax arrays; single-controller runs write directly).
* :func:`restore` — load then broadcast, so a checkpoint restored on one
  host starts every worker identically.
* :class:`CheckpointManager` — step-numbered checkpoints with retention,
  the resume-from-latest contract (reference Spark estimator
  ``_has_checkpoint`` behavior).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu import basics
from horovod_tpu import state as S


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _spans_processes(tree: Any) -> bool:
    """True when any leaf is a global jax.Array whose shards live on more
    than one process — the pod/GSPMD regime where every process must
    participate in the (collaborative) orbax write."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return True
        if isinstance(leaf, jax.ShapeDtypeStruct):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and len(sh.device_set) > len(
                list(sh.addressable_devices)
            ):
                return True
    return False


def save(path: str, tree: Any, *, force: bool = True) -> None:
    """Write a pytree checkpoint.

    Two regimes (SURVEY.md §5.4):

    * **replicated/eager** — rank 0's data is authoritative (replicas are
      identical by the DistributedOptimizer contract), so only rank 0
      writes and other ranks return immediately.
    * **global GSPMD arrays** (any leaf spans processes) — EVERY process
      calls into orbax: each writes the shards it addresses and orbax's
      multihost barrier finalizes the checkpoint on the primary.  This is
      the pod save path: a tp/fsdp-sharded model larger than one host
      checkpoints without ever being gathered.
    """
    path = os.path.abspath(path)
    if _spans_processes(tree):
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, tree, force=force)
        return
    if basics.num_processes() > 1 and basics.process_rank() != 0:
        return  # non-writers never touch orbax
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, jax.device_get(tree), force=force)


def _abstract_or_host(t):
    """jax.Array template leaves become abstract targets carrying their
    SHARDING, so orbax places restored shards directly on the right
    devices (no whole-tree bounce through one device — a tp/fsdp model
    bigger than one chip restores sharded); other leaves restore as host
    arrays."""
    if isinstance(t, jax.Array):
        return jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=t.sharding)
    return t


def _to_jax(x):
    """Host-restored leaves become jax.Arrays (numpy cannot be indexed by
    traced values — a restored embedding table would break the first
    jitted ``embed[tokens]``) — EXCEPT when conversion would change the
    dtype (64-bit leaves with jax_enable_x64 off keep their numpy form
    and full precision, the pre-r4 behavior)."""
    if isinstance(x, jax.Array):
        return x
    a = jnp.asarray(x)
    return a if a.dtype == np.asarray(x).dtype else x


def restore(path: str, template: Any, *, root_rank: int = 0,
            broadcast: bool = True) -> Any:
    """Load a checkpoint and (optionally) broadcast it so every process
    resumes from identical state (the reference's restored-state
    broadcast).

    Array leaves come back as ``jax.Array``s placed per the TEMPLATE's
    shardings (pass a tree of sharded arrays — or ``device_put`` the
    result — for multi-chip serving placement, docs/inference.md)."""
    path = os.path.abspath(path)
    if basics.num_processes() == 1 or _spans_processes(template):
        # Single-controller, or pod-mode GSPMD template: every process
        # restores collaboratively — orbax places each shard directly on
        # the devices named by the template's shardings (no broadcast;
        # the shardings ARE the distribution).
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(
                path, jax.tree_util.tree_map(_abstract_or_host, template))
        return jax.tree_util.tree_map(_to_jax, tree)
    if basics.process_rank() == root_rank:
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(path, jax.device_get(template))
    else:
        tree = template
    if broadcast:
        tree = S.broadcast_parameters(tree, root_rank)
    return jax.tree_util.tree_map(_to_jax, tree)


def save_async(path: str, tree: Any):
    """Start a NON-BLOCKING checkpoint write and return a handle with
    ``wait()`` — the training loop keeps stepping while the host
    serializes (orbax async checkpointing; the device→host copy happens
    up front, the file writes on a background thread).  Call ``wait()``
    (or start the next save) before reading the checkpoint back or
    exiting.  Single-controller and pod-collaborative regimes both
    supported (same dispatch as :func:`save`)."""
    ocp = _ocp()
    path = os.path.abspath(path)

    class _Handle:
        def __init__(self, ckptr):
            self._ckptr = ckptr

        def wait(self):
            if self._ckptr is not None:
                self._ckptr.wait_until_finished()
                self._ckptr.close()
                self._ckptr = None

    if _spans_processes(tree):
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        ckptr.save(path, args=ocp.args.StandardSave(tree), force=True)
        return _Handle(ckptr)
    if basics.num_processes() > 1 and basics.process_rank() != 0:
        return _Handle(None)  # non-writers: nothing in flight
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(jax.device_get(tree)),
               force=True)
    return _Handle(ckptr)


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-resume.

    ``save(step, tree)`` on a cadence; ``latest_step()`` / ``restore_latest
    (template)`` on startup — the estimator/elastic resume contract.
    ``async_saves=True`` makes ``save`` non-blocking (each save first
    waits out the previous one, so at most one write is in flight)."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_saves: bool = False) -> None:
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.async_saves = async_saves
        self._inflight = None
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self):
        steps = []
        if os.path.isdir(self.directory):
            for name in os.listdir(self.directory):
                if name.startswith("step_"):
                    try:
                        steps.append(int(name[len("step_"):]))
                    except ValueError:
                        pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, tree: Any) -> None:
        if self.async_saves:
            self.wait()  # at most one write in flight
            self._inflight = save_async(self._step_dir(step), tree)
        else:
            save(self._step_dir(step), tree)
        if basics.num_processes() > 1 and basics.process_rank() != 0:
            return
        # retention (oldest beyond max_to_keep removed; an in-flight
        # async save is never the victim — it is the newest step, and it
        # counts toward the retention budget even though its directory
        # only appears when the background write finalizes)
        steps = self.all_steps()
        if self._inflight is not None and step not in steps:
            steps.append(step)
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            import shutil

            shutil.rmtree(self._step_dir(victim), ignore_errors=True)

    def wait(self) -> None:
        """Block until the in-flight async save (if any) is durable."""
        if self._inflight is not None:
            self._inflight.wait()
            self._inflight = None

    def restore(self, step: int, template: Any) -> Any:
        self.wait()  # never read past an in-flight write
        return restore(self._step_dir(step), template)

    def restore_latest(self, template: Any) -> tuple[Optional[int], Any]:
        """(step, tree) from the newest checkpoint, or (None, template)."""
        self.wait()
        step = self.latest_step()
        if step is None:
            return None, template
        return step, self.restore(step, template)
