"""Checkpoint/resume with the reference's consistency conventions.

The reference has no core checkpoint system — it delegates storage to the
framework and provides the *consistency* primitives (SURVEY.md §5.4):
rank-0-only saving (``examples/keras_imagenet_resnet50.py``), broadcast of
restored state (``BroadcastGlobalVariablesHook``,
``broadcast_optimizer_state``), and Keras ``hvd.load_model`` that rewraps
the optimizer on load.

TPU-native storage: orbax (sharding-aware, async-capable).  This module
packages the conventions over it:

* :func:`save` — rank 0 writes (every process must still call it for
  multi-host orbax arrays; single-controller runs write directly).
* :func:`restore` — load then broadcast, so a checkpoint restored on one
  host starts every worker identically.
* :class:`CheckpointManager` — step-numbered checkpoints with retention,
  the resume-from-latest contract (reference Spark estimator
  ``_has_checkpoint`` behavior).
"""

from __future__ import annotations

import os
import shutil
import warnings
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu import basics
from horovod_tpu import state as S


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _spans_processes(tree: Any) -> bool:
    """True when any leaf is a global jax.Array whose shards live on more
    than one process — the pod/GSPMD regime where every process must
    participate in the (collaborative) orbax write."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return True
        if isinstance(leaf, jax.ShapeDtypeStruct):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and len(sh.device_set) > len(
                list(sh.addressable_devices)
            ):
                return True
    return False


def save(path: str, tree: Any, *, force: bool = True) -> None:
    """Write a pytree checkpoint.

    Two regimes (SURVEY.md §5.4):

    * **replicated/eager** — rank 0's data is authoritative (replicas are
      identical by the DistributedOptimizer contract), so only rank 0
      writes and other ranks return immediately.
    * **global GSPMD arrays** (any leaf spans processes) — EVERY process
      calls into orbax: each writes the shards it addresses and orbax's
      multihost barrier finalizes the checkpoint on the primary.  This is
      the pod save path: a tp/fsdp-sharded model larger than one host
      checkpoints without ever being gathered.
    """
    path = os.path.abspath(path)
    if _spans_processes(tree):
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, tree, force=force)
        return
    if basics.num_processes() > 1 and basics.process_rank() != 0:
        return  # non-writers never touch orbax
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, jax.device_get(tree), force=force)


def _abstract_or_host(t):
    """jax.Array template leaves become abstract targets carrying their
    SHARDING, so orbax places restored shards directly on the right
    devices (no whole-tree bounce through one device — a tp/fsdp model
    bigger than one chip restores sharded); other leaves restore as host
    arrays."""
    if isinstance(t, jax.Array):
        return jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=t.sharding)
    return t


def _to_jax(x):
    """Host-restored leaves become jax.Arrays (numpy cannot be indexed by
    traced values — a restored embedding table would break the first
    jitted ``embed[tokens]``) — EXCEPT when conversion would change the
    dtype (64-bit leaves with jax_enable_x64 off keep their numpy form
    and full precision, the pre-r4 behavior)."""
    if isinstance(x, jax.Array):
        return x
    a = jnp.asarray(x)
    return a if a.dtype == np.asarray(x).dtype else x


def restore(path: str, template: Any, *, root_rank: int = 0,
            broadcast: bool = True) -> Any:
    """Load a checkpoint and (optionally) broadcast it so every process
    resumes from identical state (the reference's restored-state
    broadcast).

    Array leaves come back as ``jax.Array``s placed per the TEMPLATE's
    shardings (pass a tree of sharded arrays — or ``device_put`` the
    result — for multi-chip serving placement, docs/inference.md)."""
    path = os.path.abspath(path)
    if basics.num_processes() == 1 or _spans_processes(template):
        # Single-controller, or pod-mode GSPMD template: every process
        # restores collaboratively — orbax places each shard directly on
        # the devices named by the template's shardings (no broadcast;
        # the shardings ARE the distribution).
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(
                path, jax.tree_util.tree_map(_abstract_or_host, template))
        return jax.tree_util.tree_map(_to_jax, tree)
    if basics.process_rank() == root_rank:
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(path, jax.device_get(template))
    else:
        tree = template
    if broadcast:
        tree = S.broadcast_parameters(tree, root_rank)
    return jax.tree_util.tree_map(_to_jax, tree)


def save_async(path: str, tree: Any):
    """Start a NON-BLOCKING checkpoint write and return a handle with
    ``wait()`` — the training loop keeps stepping while the host
    serializes (orbax async checkpointing; the device→host copy happens
    up front, the file writes on a background thread).  Call ``wait()``
    (or start the next save) before reading the checkpoint back or
    exiting.  Single-controller and pod-collaborative regimes both
    supported (same dispatch as :func:`save`)."""
    ocp = _ocp()
    path = os.path.abspath(path)

    class _Handle:
        def __init__(self, ckptr):
            self._ckptr = ckptr

        def wait(self):
            if self._ckptr is not None:
                self._ckptr.wait_until_finished()
                self._ckptr.close()
                self._ckptr = None

    if _spans_processes(tree):
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        ckptr.save(path, args=ocp.args.StandardSave(tree), force=True)
        return _Handle(ckptr)
    if basics.num_processes() > 1 and basics.process_rank() != 0:
        return _Handle(None)  # non-writers: nothing in flight
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(jax.device_get(tree)),
               force=True)
    return _Handle(ckptr)


def _promote_tmp(tmp: str, final: str) -> None:
    """Atomically promote a completed ``.tmp`` write to its final step
    directory (same filesystem, so ``os.rename`` is the commit point —
    a crash leaves either the old state or the new one, never a
    half-written step under the final name)."""
    if os.path.isdir(final):
        shutil.rmtree(final, ignore_errors=True)
    os.rename(tmp, final)


class _FinalizingHandle:
    """Wrap an async-save handle so ``wait()`` also commits the
    ``.tmp`` -> final rename once the background write is durable."""

    def __init__(self, inner, tmp: str, final: str, promote: bool):
        self._inner = inner
        self._tmp = tmp
        self._final = final
        self._promote = promote

    def wait(self) -> None:
        self._inner.wait()
        if self._promote and os.path.isdir(self._tmp):
            _promote_tmp(self._tmp, self._final)


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-resume.

    ``save(step, tree)`` on a cadence; ``latest_step()`` / ``restore_latest
    (template)`` on startup — the estimator/elastic resume contract.
    ``async_saves=True`` makes ``save`` non-blocking (each save first
    waits out the previous one, so at most one write is in flight).

    Saves are ATOMIC: orbax writes land in ``step_N.tmp`` and are
    committed by a rename — a crash mid-save leaves a stale ``.tmp``
    that :meth:`all_steps` never lists, so a finalized step directory
    is intact by construction.  :meth:`restore_latest` adds a second
    line of defense for corruption after the fact (truncated files,
    torn disks): an unreadable newest step is skipped with a warning
    and the previous intact one restores instead of raising."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_saves: bool = False) -> None:
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.async_saves = async_saves
        self._inflight = None
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self):
        # f"step_N.tmp" names fail the int() parse, so uncommitted and
        # crash-abandoned writes are invisible here by construction.
        steps = []
        if os.path.isdir(self.directory):
            for name in os.listdir(self.directory):
                if name.startswith("step_"):
                    try:
                        steps.append(int(name[len("step_"):]))
                    except ValueError:
                        pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _is_finalizer(self) -> bool:
        """Exactly one process commits the rename: rank 0 (the orbax
        primary in the collaborative regime; the only writer in the
        replicated one)."""
        return basics.num_processes() == 1 or basics.process_rank() == 0

    def save(self, step: int, tree: Any) -> None:
        final = self._step_dir(step)
        tmp = final + ".tmp"
        if self.async_saves:
            self.wait()  # at most one write in flight
            self._inflight = _FinalizingHandle(
                save_async(tmp, tree), tmp, final,
                promote=self._is_finalizer())
        else:
            save(tmp, tree)
            if self._is_finalizer() and os.path.isdir(tmp):
                _promote_tmp(tmp, final)
        if basics.num_processes() > 1 and basics.process_rank() != 0:
            return
        # sweep crash-abandoned .tmp writes from PREVIOUS runs — never
        # the one currently in flight — so each crash doesn't leak a
        # full checkpoint's worth of disk forever
        inflight_tmp = tmp if self._inflight is not None else None
        for name in os.listdir(self.directory):
            if name.endswith(".tmp"):
                stale = os.path.join(self.directory, name)
                if stale != inflight_tmp:
                    shutil.rmtree(stale, ignore_errors=True)
        # retention (oldest beyond max_to_keep removed; an in-flight
        # async save is never the victim — it is the newest step, and it
        # counts toward the retention budget even though its directory
        # only appears when the background write finalizes)
        steps = self.all_steps()
        if self._inflight is not None and step not in steps:
            steps.append(step)
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)

    def wait(self) -> None:
        """Block until the in-flight async save (if any) is durable
        (and, for async saves, committed to its final name)."""
        if self._inflight is not None:
            self._inflight.wait()
            self._inflight = None

    def restore(self, step: int, template: Any) -> Any:
        self.wait()  # never read past an in-flight write
        return restore(self._step_dir(step), template)

    def _warn_unreadable(self, step: int, e: Exception) -> None:
        warnings.warn(
            f"checkpoint step {step} at {self._step_dir(step)} is "
            f"unreadable ({type(e).__name__}: {e}); falling back to the "
            f"previous checkpoint")

    def restore_latest(self, template: Any) -> tuple[Optional[int], Any]:
        """(step, tree) from the newest INTACT checkpoint, or (None,
        template).  A corrupt or partial newest step (truncated files,
        interrupted finalize) is skipped with a warning and the next
        older one is tried — resume never dies on the checkpoint that
        was being written when the previous run crashed."""
        self.wait()
        steps = self.all_steps()
        if _spans_processes(template) and basics.num_processes() > 1:
            # Pod-mode GSPMD: restore is COLLECTIVE (every rank reads
            # its own shards), so a per-rank try/except fallback would
            # let ranks that see local corruption issue different
            # collectives than ranks that don't — a distributed hang.
            # Attempt only the newest step and fail loudly; skipping a
            # torn pod checkpoint needs an out-of-band decision.
            if not steps:
                return None, template
            return steps[-1], self.restore(steps[-1], template)
        if basics.num_processes() > 1:
            # Replicated regime: only rank 0 reads disk, so only it can
            # SEE corruption — if every rank walked the fallback loop
            # independently, non-root ranks would accept the newest step
            # number while rank 0 silently restored an older tree.
            # Rank 0 picks the winning step locally (no broadcast), then
            # step + tree ship together in ONE broadcast so every rank
            # resumes from the same (step, weights) pair.
            chosen, tree = -1, template
            if basics.process_rank() == 0:
                for step in reversed(steps):
                    try:
                        tree = restore(self._step_dir(step), template,
                                       broadcast=False)
                        chosen = step
                        break
                    except Exception as e:
                        self._warn_unreadable(step, e)
            agreed = S.broadcast_parameters(
                {"step": np.asarray(chosen, np.int64), "tree": tree}, 0)
            step = int(np.asarray(agreed["step"]))
            if step < 0:
                return None, template
            return step, agreed["tree"]
        for step in reversed(steps):
            try:
                return step, self.restore(step, template)
            except Exception as e:  # orbax raises various per-format errors
                self._warn_unreadable(step, e)
                continue
        return None, template
