"""Estimator API: fit()/predict() with distributed training handled for
the user.

Reference shape: ``horovod/spark/common/estimator.py:27-110``
(``HorovodEstimator.fit(df)`` materializes data via the Store, launches a
per-rank training fn through the backend, returns a ``HorovodModel``
transformer) with the per-rank fn built as in ``spark/keras/remote.py:
37-195`` (init -> broadcast -> shard reader -> train -> rank-0 checkpoint
to store).  The TPU re-design replaces Spark's DataFrame+Petastorm data
path with numpy shards in the Store and the Spark backend with the
run-func launcher (:mod:`horovod_tpu.runner.run_func`).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from horovod_tpu.estimator.dataframe import DataFrameFitMixin
from horovod_tpu.estimator.store import Store, shard_arrays


@dataclass
class EstimatorParams:
    """Common estimator knobs (reference ``spark/common/params.py``
    EstimatorParams, as a plain dataclass instead of Spark ML Params)."""

    num_proc: int = 2
    batch_size: int = 32
    epochs: int = 1
    shuffle: bool = True
    seed: int = 0
    run_id: Optional[str] = None
    verbose: int = 0
    # Held-out fraction in [0, 1) evaluated each epoch (reference
    # EstimatorParams.validation, ``spark/common/params.py:52-53`` —
    # the float-split flavor; the column-name flavor is DataFrame
    # machinery this numpy data path doesn't have).
    validation: Optional[float] = None
    # Intermediate shard format in the Store: "npz" or "parquet" (the
    # reference's format; interchangeable with external Parquet tools).
    # Readers sniff the magic, so trainers are format-agnostic.
    storage_format: str = "npz"
    # JAX platform pinned in worker ranks.  "auto" (default) trains on
    # TPU when a single worker process can own the visible chips
    # (num_proc == 1) and falls back to CPU otherwise — the launcher does
    # not yet partition chips per process (TPU_VISIBLE_* env plumbing),
    # so several local workers would contend for libtpu's exclusive host
    # lock; "cpu"/"tpu" pin explicitly; None leaves the runtime default
    # untouched.
    jax_platform: Optional[str] = "auto"


def resolve_platform(params: "EstimatorParams") -> str:
    """Resolve ``jax_platform="auto"``: TPU by default when the single
    worker process can own the chips, CPU fallback otherwise (VERDICT r1
    weak #7 — the estimator should touch the TPU without the user
    overriding, but never oversubscribe).  Multi-process runs resolve to
    CPU: nothing in the launcher partitions chips per process yet, so N
    local workers opening the full TPU backend would fight over libtpu's
    exclusive host lock.

    The probe runs in a THROWAWAY subprocess: enumerating TPUs in this
    process would initialize the backend here and hold the exclusive chip
    lock, starving the very worker the answer is for."""
    if params.jax_platform != "auto":
        return params.jax_platform or ""
    if int(params.num_proc) == 1 and _probe_tpu_available():
        return ""  # leave the worker on the runtime default (TPU)
    return "cpu"


_probe_result: Dict[str, bool] = {}


def _probe_tpu_available() -> bool:
    """One-shot subprocess probe for a usable TPU.  Only a probe that RAN
    to completion is cached — a timeout/spawn failure is transient
    machine state, not an answer, and must not pin every later fit() to
    CPU (or TPU) for the life of the process."""
    if "tpu" not in _probe_result:
        import subprocess
        import sys

        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, sys; "
                 "sys.exit(0 if len(jax.devices('tpu')) >= 1 else 1)"],
                capture_output=True, timeout=90,
            )
        except Exception:
            return False
        _probe_result["tpu"] = proc.returncode == 0
    return _probe_result["tpu"]


def _split_validation(x: np.ndarray, y: np.ndarray, validation, seed: int):
    """Deterministic shuffled train/val split (reference
    ``util.py:_train_val_split``); returns (x, y, xv, yv) with the val
    pair None when no validation was requested."""
    if not validation:
        return x, y, None, None
    frac = float(validation)
    if not 0.0 < frac < 1.0:
        raise ValueError(f"validation must be in (0, 1), got {validation}")
    idx = np.random.RandomState(seed).permutation(len(x))
    n_val = max(int(len(x) * frac), 1)
    val, tr = idx[:n_val], idx[n_val:]
    if len(tr) == 0:
        raise ValueError("validation split leaves no training rows")
    return x[tr], y[tr], x[val], y[val]


def _stage_data(remote_store, x, y, p: "EstimatorParams"):
    """Split, shard and materialize train (+ optional validation) data
    through the store — the staging step every estimator flavor shares.
    Returns ``(n_train, n_val)``.

    Guards the lockstep contract: a validation fraction so small that
    some rank's shard would be EMPTY is rejected up front — an empty
    shard would turn that rank's epoch-end val reduction into NaN (mean
    of zero rows) and poison every rank through the allreduce."""
    x, y, xv, yv = _split_validation(
        np.asarray(x), np.asarray(y), p.validation, p.seed)
    if xv is not None and len(xv) < p.num_proc:
        raise ValueError(
            f"validation={p.validation} keeps only {len(xv)} rows — fewer "
            f"than num_proc={p.num_proc}, so some worker would hold an "
            "empty validation shard; raise validation or lower num_proc")
    for r, shard in enumerate(shard_arrays({"x": x, "y": y}, p.num_proc)):
        remote_store.save_arrays(
            remote_store.get_train_data_path(str(r)), shard,
            format=p.storage_format)
    if xv is not None:
        for r, shard in enumerate(shard_arrays({"x": xv, "y": yv},
                                               p.num_proc)):
            remote_store.save_arrays(
                remote_store.get_val_data_path(str(r)), shard,
                format=p.storage_format)
    return len(x), 0 if xv is None else len(xv)


def _steps_per_epoch(n_total: int, num_proc: int, batch_size: int) -> int:
    """Identical on every rank: min over ranks of full batches per shard
    (shard r holds (r+1)*n//P - r*n//P rows)."""
    sizes = [(r + 1) * n_total // num_proc - r * n_total // num_proc
             for r in range(num_proc)]
    steps = min(s // batch_size for s in sizes)
    if steps == 0:
        raise ValueError(
            f"batch_size={batch_size} exceeds the smallest shard "
            f"({min(sizes)} rows from {n_total} over {num_proc} ranks); "
            "reduce batch_size or num_proc")
    return steps


def _jax_train_fn(store, run_id, spec, num_proc):
    """Per-rank training body (role of spark/keras/remote.py:37-195).
    Runs inside a launched rank: init -> broadcast -> local shard ->
    minibatch loop with DistributedOptimizer -> rank-0 checkpoint."""
    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.process_rank()

    shard = store.load_arrays(store.get_train_data_path(str(rank)))
    x, y = shard["x"], shard["y"]

    params = spec["init_params"](jax.random.PRNGKey(spec["seed"]))
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = spec["optimizer"]
    opt_state = opt.init(params)

    loss_fn = spec["loss_fn"]

    import optax

    # Process-level DP: gradients reduce on the EAGER path (negotiated +
    # fused by the native control plane) between two jitted halves — each
    # process drives one device, so there is no in-graph worker axis here.
    @jax.jit
    def grads_fn(params, xb, yb):
        return jax.value_and_grad(loss_fn)(params, xb, yb)

    @jax.jit
    def apply_fn(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def step(params, opt_state, xb, yb):
        loss, grads = grads_fn(params, xb, yb)
        grads = hvd.allreduce(grads, hvd.Average)
        params, opt_state = apply_fn(params, opt_state, grads)
        return params, opt_state, loss

    rng = np.random.RandomState(spec["seed"] + rank)
    bs = spec["batch_size"]
    # Every rank MUST run the same number of steps: shards differ by up to
    # one row, and a rank running an extra step would submit collectives
    # its peers never match (the steady-state ordering contract).  The
    # global min is computable locally from (n_total, num_proc, bs).
    steps = _steps_per_epoch(spec["n_total"], num_proc, bs)
    xv = yv = None
    if spec.get("n_val"):
        vshard = store.load_arrays(store.get_val_data_path(str(rank)))
        xv, yv = vshard["x"], vshard["y"]
        val_loss_fn = jax.jit(lambda p, xb, yb: loss_fn(p, xb, yb))
    history: List[float] = []
    val_history: List[float] = []
    for epoch in range(spec["epochs"]):
        idx = rng.permutation(len(x)) if spec["shuffle"] else np.arange(len(x))
        losses = []
        for s in range(steps):
            b = idx[s * bs:(s + 1) * bs]  # full batch: steps*bs <= shard len
            params, opt_state, loss = step(params, opt_state, x[b], y[b])
            losses.append(float(loss))
        # epoch metric averaged across ranks (MetricAverageCallback role)
        history.append(float(np.mean(hvd.allreduce(
            np.asarray(losses, np.float32), hvd.Average))))
        if spec.get("verbose") and rank == 0:
            print(f"epoch {epoch}: loss {history[-1]:.4f}")
        if xv is not None:
            # row-weighted global mean: shards differ by up to one row.
            # process_sum, not Sum: the payload is PROCESS-level data
            # (this process's shard rows), so the chip-weighted eager Sum
            # would skew the mean when chip counts differ per process.
            part = np.asarray([
                float(val_loss_fn(params, xv, yv)) * len(xv),
                float(len(xv)),
            ], np.float32)
            tot = hvd.process_sum(part, name=f"val.{epoch}")
            val_history.append(float(tot[0] / tot[1]))

    if rank == 0:
        store.save_obj(store.get_checkpoint_path(run_id),
                       {"params": jax.device_get(params),
                        "history": history,
                        "val_history": val_history})
    hvd.barrier()
    return history


class JaxEstimator(DataFrameFitMixin):
    """Distributed-training estimator for a pure-JAX model.

    ``model_fn(params, x)`` is the forward; ``loss_fn(params, x, y)`` the
    training objective; ``init_params(rng)`` builds initial parameters;
    ``optimizer`` is an optax transformation.
    """

    def __init__(self, *, model_fn: Callable, loss_fn: Callable,
                 init_params: Callable, optimizer: Any,
                 store: Store, params: Optional[EstimatorParams] = None):
        self.model_fn = model_fn
        self.loss_fn = loss_fn
        self.init_params = init_params
        self.optimizer = optimizer
        self.store = store
        self.params = params or EstimatorParams()

    def fit(self, x: np.ndarray, y: np.ndarray) -> "JaxModel":
        """Reference fit contract (estimator.py:28-97): materialize data
        through the store, train on num_proc ranks, return a Model."""
        from horovod_tpu.runner import run_func

        p = self.params
        run_id = p.run_id or f"run_{uuid.uuid4().hex[:8]}"
        remote_store = self.store.to_remote()
        n_train, n_val = _stage_data(remote_store, x, y, p)

        spec = {
            "loss_fn": self.loss_fn,
            "init_params": self.init_params,
            "optimizer": self.optimizer,
            "batch_size": p.batch_size,
            "epochs": p.epochs,
            "shuffle": p.shuffle,
            "seed": p.seed,
            "verbose": p.verbose,
            "n_total": n_train,
            "n_val": n_val,
        }
        run_func.run(
            _jax_train_fn, (remote_store, run_id, spec, p.num_proc),
            num_proc=p.num_proc, use_jax_platform=resolve_platform(p),
        )
        ckpt = remote_store.load_obj(remote_store.get_checkpoint_path(run_id))
        return JaxModel(model_fn=self.model_fn, params=ckpt["params"],
                        history=ckpt["history"],
                        val_history=ckpt.get("val_history", []),
                        run_id=run_id)


@dataclass(eq=False)  # auto __eq__ over array fields raises on compare
class JaxModel:
    """Trained-model transformer (reference ``HorovodModel``)."""

    model_fn: Callable
    params: Any
    history: List[float] = field(default_factory=list)
    val_history: List[float] = field(default_factory=list)
    run_id: str = ""

    def predict(self, x: np.ndarray) -> np.ndarray:
        import jax

        if getattr(self, "_jitted", None) is None:
            self._jitted = jax.jit(self.model_fn)
        return np.asarray(self._jitted(self.params, np.asarray(x)))

    def transform(self, x: np.ndarray) -> np.ndarray:  # Spark naming
        return self.predict(x)


# --- torch flavor -------------------------------------------------------------


def _torch_train_fn(store, run_id, spec, num_proc):
    """Per-rank torch training body (role of spark/torch/remote.py)."""
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    rank = hvd.cross_rank()

    shard = store.load_arrays(store.get_train_data_path(str(rank)))
    x = torch.from_numpy(shard["x"]).float()
    y = torch.from_numpy(shard["y"]).float()

    model = spec["model_factory"]()
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        spec["optimizer_factory"](model.parameters()),
        named_parameters=model.named_parameters())
    loss_fn = spec["loss_fn"]

    g = torch.Generator().manual_seed(spec["seed"] + rank)
    bs = spec["batch_size"]
    steps = _steps_per_epoch(spec["n_total"], num_proc, bs)
    xv = yv = None
    if spec.get("n_val"):
        vshard = store.load_arrays(store.get_val_data_path(str(rank)))
        xv = torch.from_numpy(vshard["x"]).float()
        yv = torch.from_numpy(vshard["y"]).float()
    history = []
    val_history = []
    for epoch in range(spec["epochs"]):
        idx = (torch.randperm(len(x), generator=g) if spec["shuffle"]
               else torch.arange(len(x)))
        losses = []
        for s in range(steps):
            b = idx[s * bs:(s + 1) * bs]
            opt.zero_grad()
            loss = loss_fn(model(x[b]), y[b])
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        avg = hvd.allreduce(torch.tensor(np.mean(losses)), op=hvd.Average)
        history.append(float(avg))
        if spec.get("verbose") and rank == 0:
            print(f"epoch {epoch}: loss {history[-1]:.4f}")
        if xv is not None:
            with torch.no_grad():
                vloss = float(loss_fn(model(xv), yv)) * len(xv)
            # Process-level sum: pre-divide by local_size so the
            # chip-weighted eager Sum reduces one contribution per
            # process (see collectives.process_sum).
            part = hvd.allreduce(
                torch.tensor([vloss, float(len(xv))]), op=hvd.Sum,
                name=f"val.{epoch}",
                prescale_factor=1.0 / hvd.local_size())
            val_history.append(float(part[0] / part[1]))

    if rank == 0:
        store.save_obj(store.get_checkpoint_path(run_id),
                       {"state_dict": model.state_dict(),
                        "history": history,
                        "val_history": val_history})
    return history


class TorchEstimator(DataFrameFitMixin):
    """Distributed-training estimator for a torch model (reference
    ``spark/torch/estimator.py`` shape: model + optimizer + loss in,
    Model transformer out)."""

    def __init__(self, *, model_factory: Callable, optimizer_factory: Callable,
                 loss_fn: Callable, store: Store,
                 params: Optional[EstimatorParams] = None):
        self.model_factory = model_factory
        self.optimizer_factory = optimizer_factory
        self.loss_fn = loss_fn
        self.store = store
        self.params = params or EstimatorParams()

    def fit(self, x: np.ndarray, y: np.ndarray) -> "TorchModel":
        from horovod_tpu.runner import run_func

        p = self.params
        run_id = p.run_id or f"run_{uuid.uuid4().hex[:8]}"
        remote_store = self.store.to_remote()
        n_train, n_val = _stage_data(remote_store, x, y, p)
        spec = {
            "model_factory": self.model_factory,
            "optimizer_factory": self.optimizer_factory,
            "loss_fn": self.loss_fn,
            "batch_size": p.batch_size,
            "epochs": p.epochs,
            "shuffle": p.shuffle,
            "seed": p.seed,
            "verbose": p.verbose,
            "n_total": n_train,
            "n_val": n_val,
        }
        run_func.run(
            _torch_train_fn, (remote_store, run_id, spec, p.num_proc),
            num_proc=p.num_proc, use_jax_platform=resolve_platform(p),
        )
        ckpt = remote_store.load_obj(remote_store.get_checkpoint_path(run_id))
        model = self.model_factory()
        model.load_state_dict(ckpt["state_dict"])
        return TorchModel(model=model, history=ckpt["history"],
                          val_history=ckpt.get("val_history", []),
                          run_id=run_id)


@dataclass(eq=False)
class TorchModel:
    model: Any
    history: List[float] = field(default_factory=list)
    val_history: List[float] = field(default_factory=list)
    run_id: str = ""

    def predict(self, x: np.ndarray) -> np.ndarray:
        import torch

        with torch.no_grad():
            return self.model(torch.from_numpy(np.asarray(x)).float()).numpy()

    def transform(self, x: np.ndarray) -> np.ndarray:
        return self.predict(x)
