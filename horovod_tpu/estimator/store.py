"""Store: filesystem abstraction for training data shards and run
artifacts (checkpoints, logs).

Reference: ``horovod/spark/common/store.py:30-246`` — ``Store`` with
``LocalStore``/``HDFSStore`` subclasses giving the estimator stable paths
for intermediate data (``train_data_path``), checkpoints and logs, plus a
serializable remote view.  The TPU re-design drops the Parquet/Petastorm
machinery (numpy shards cover the estimator's data movement on a single
host or shared filesystem) and keeps the path contract.
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Dict, List, Optional

import numpy as np


class Store:
    """Abstract path provider (reference Store)."""

    def __init__(self, prefix_path: str) -> None:
        self.prefix_path = prefix_path

    # -- path contract (reference store.py get_*_path methods) -----------
    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_train_data" + (f".{idx}" if idx else ""))

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_val_data" + (f".{idx}" if idx else ""))

    def get_runs_path(self) -> str:
        return self._join("runs")

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.get_runs_path(), run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint.pkl")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def _join(self, *parts: str) -> str:
        return os.path.join(self.prefix_path, *parts)

    # -- IO (implemented by subclasses) ----------------------------------
    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def to_remote(self) -> "Store":
        """A picklable view usable inside workers (reference
        ``to_remote``); Stores here are already plain-data objects."""
        return self

    # -- convenience on top of bytes IO ----------------------------------
    def save_arrays(self, path: str, arrays: Dict[str, np.ndarray], *,
                    format: str = "npz") -> None:
        """``format``: "npz" (default) or "parquet" (the reference's
        intermediate format).  Readers sniff the file magic, so the two
        formats share paths and no consumer needs to know which was
        chosen."""
        import io

        if format == "parquet":
            self.save_parquet(path, arrays)
            return
        if format != "npz":
            raise ValueError(f"unknown storage format {format!r}")
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        self.write_bytes(path, buf.getvalue())

    def load_arrays(self, path: str) -> Dict[str, np.ndarray]:
        import io

        data = self.read_bytes(path)
        if data[:4] == b"PAR1":  # parquet magic
            return self._parquet_bytes_to_arrays(data)
        with np.load(io.BytesIO(data)) as z:
            return {k: z[k] for k in z.files}

    def save_obj(self, path: str, obj: Any) -> None:
        self.write_bytes(path, pickle.dumps(obj))

    def load_obj(self, path: str) -> Any:
        return pickle.loads(self.read_bytes(path))

    # -- Parquet (the reference's intermediate format; spark/common/
    # util.py materializes DataFrames as Parquet for the trainers) -------
    def save_parquet(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        """Write a dict of equal-length arrays as one Parquet file.
        Multi-dim arrays become fixed-size-list columns (the same shape
        Petastorm round-trips); restored exactly by :meth:`load_parquet`."""
        import io

        import pyarrow as pa
        import pyarrow.parquet as pq

        cols, meta = {}, {}
        for k, v in arrays.items():
            v = np.asarray(v)
            if v.ndim > 1:
                meta[k] = v.shape[1:]
                v2 = v.reshape(len(v), -1)
                cols[k] = pa.FixedSizeListArray.from_arrays(
                    pa.array(v2.ravel()), v2.shape[1])
            else:
                cols[k] = pa.array(v)
        table = pa.table(cols)
        table = table.replace_schema_metadata(
            {b"horovod_tpu.shapes": pickle.dumps(meta)})
        buf = io.BytesIO()
        pq.write_table(table, buf)
        self.write_bytes(path, buf.getvalue())

    def load_parquet(self, path: str) -> Dict[str, np.ndarray]:
        return self._parquet_bytes_to_arrays(self.read_bytes(path))

    @staticmethod
    def _parquet_bytes_to_arrays(data: bytes) -> Dict[str, np.ndarray]:
        import io

        import pyarrow.parquet as pq

        table = pq.read_table(io.BytesIO(data))
        meta = {}
        md = table.schema.metadata or {}
        if b"horovod_tpu.shapes" in md:
            meta = pickle.loads(md[b"horovod_tpu.shapes"])
        out = {}
        for k in table.column_names:
            col = table.column(k).combine_chunks()
            arr = np.asarray(col.flatten() if k in meta else col)
            if k in meta:
                arr = arr.reshape((len(table),) + tuple(meta[k]))
            out[k] = arr
        return out

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Pick a Store for the path (reference ``Store.create``:
        hdfs:// -> HDFSStore, else LocalStore)."""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path)
        return LocalStore(prefix_path)


class LocalStore(Store):
    """Local-filesystem store (reference LocalStore)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


class HDFSStore(Store):
    """HDFS store, gated on pyarrow (reference HDFSStore requires
    pyarrow.hdfs); raises a clear error when unavailable."""

    def __init__(self, prefix_path: str) -> None:
        super().__init__(prefix_path)
        try:
            import pyarrow.fs as pafs  # noqa: F401

            self._fs = pafs.HadoopFileSystem.from_uri(prefix_path)
        except Exception as e:  # pyarrow missing, or no libhdfs/JVM
            raise ImportError(
                "HDFSStore requires pyarrow with a working libhdfs/JVM, "
                "unavailable in this environment; use LocalStore instead "
                f"({e})"
            ) from e

    def exists(self, path: str) -> bool:
        import pyarrow.fs as pafs

        info = self._fs.get_file_info([path])[0]
        return info.type != pafs.FileType.NotFound

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open_input_stream(path) as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        with self._fs.open_output_stream(path) as f:
            f.write(data)


def shard_arrays(arrays: Dict[str, np.ndarray], num_shards: int
                 ) -> List[Dict[str, np.ndarray]]:
    """Row-shard a dict of equal-length arrays into per-rank slices (the
    estimator's stand-in for the reference's DataFrame repartition,
    ``spark/common/util.py`` prepare_data)."""
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        if len(v) != n:
            raise ValueError(f"array {k!r} has length {len(v)} != {n}")
    out = []
    for r in range(num_shards):
        sl = slice(r * n // num_shards, (r + 1) * n // num_shards)
        out.append({k: v[sl] for k, v in arrays.items()})
    return out
