"""DataFrame entry point for the estimators.

Reference: the Spark estimators take a DataFrame plus ``feature_cols`` /
``label_cols`` params and materialize it for the trainers
(``spark/common/util.py:prepare_data``, 608 LoC of DataFrame→Parquet
plumbing).  Here the same user contract — "hand the estimator a
DataFrame and column names" — converts through pandas into the numpy
(x, y) the trainers shard, with list-valued columns (embeddings, images
flattened row-wise) stacked into 2-D blocks and multiple feature columns
concatenated in the order given.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def _col_to_block(col) -> np.ndarray:
    """One column -> (N, k) float block: scalars k=1, list/array values
    stack to their common width."""
    first = col.iloc[0]
    if np.ndim(first) == 0:
        return np.asarray(col, np.float32).reshape(-1, 1)
    block = np.stack([np.asarray(v, np.float32).ravel() for v in col])
    return block


def df_to_arrays(df, feature_cols: Sequence[str],
                 label_cols: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """(x, y) float32 matrices from DataFrame columns (reference
    ``to_petastorm``-style vector assembly, minus Spark)."""
    missing = [c for c in list(feature_cols) + list(label_cols)
               if c not in df.columns]
    if missing:
        raise ValueError(f"columns not in DataFrame: {missing}")
    x = np.concatenate([_col_to_block(df[c]) for c in feature_cols], axis=1)
    y = np.concatenate([_col_to_block(df[c]) for c in label_cols], axis=1)
    return x.astype(np.float32), y.astype(np.float32)


class DataFrameFitMixin:
    """Adds ``fit_df(df, feature_cols, label_cols)`` to an estimator
    whose ``fit(x, y)`` takes numpy matrices."""

    def fit_df(self, df, feature_cols: Sequence[str],
               label_cols: Sequence[str]):
        x, y = df_to_arrays(df, feature_cols, label_cols)
        return self.fit(x, y)
