"""KerasEstimator: fit()/predict() for tf.keras models with distributed
training handled for the user.

Reference: ``horovod/spark/keras/estimator.py:1-513`` (params + model/
optimizer serialization + fit returning a Model transformer) and
``spark/keras/remote.py:37-195`` (the per-worker trainer: hvd.init → pin
device → scale LR by size → shard reader → callbacks (broadcast, metric
average) → fit → rank-0 checkpoint synced to the Store).

TPU re-design: Spark DataFrame/Petastorm data movement becomes numpy
shards through the :class:`~horovod_tpu.estimator.store.Store`, the Spark
backend becomes the run-func launcher, and the trainer's collectives ride
the eager data plane (negotiated by the native control plane).  Training
runs eagerly in the workers (``run_eagerly=True``): the keras
``DistributedOptimizer`` shim reduces gradients on the host path, which
cannot live inside a ``tf.function`` trace — the documented status of the
TF frontend; the compiled-TPU path is the JAX estimator.

Import-gated on tensorflow like :mod:`horovod_tpu.keras`.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import tensorflow  # noqa: F401 — real import gate: this module's surface
# is meaningless without TF, and the package __init__ advertises
# KerasEstimator only when this import succeeds (reference gates its
# spark/keras subpackage the same way).

import numpy as np

from horovod_tpu.estimator.estimator import (
    EstimatorParams, _stage_data, _steps_per_epoch, resolve_platform,
)
from horovod_tpu.estimator.store import Store


def _serialize_keras(model, optimizer, loss, metrics) -> Dict[str, Any]:
    """Capture the compile-time state (reference estimator params
    _get_model_bytes / optimizer serialization, ``spark/keras/
    estimator.py`` + ``spark/keras/optimizer.py``)."""
    import tensorflow as tf

    return {
        "model_json": model.to_json(),
        "weights": [np.asarray(w) for w in model.get_weights()],
        "optimizer": tf.keras.optimizers.serialize(optimizer),
        "loss": tf.keras.losses.serialize(loss) if callable(loss) else loss,
        "metrics": list(metrics or []),
    }


def _keras_train_fn(store, run_id, spec, num_proc):
    """Per-rank trainer (reference ``spark/keras/remote.py:37-195``)."""
    import tensorflow as tf

    import horovod_tpu.keras as hvd_keras

    hvd_keras.init()
    import horovod_tpu as hvd

    rank = hvd.process_rank()
    # Reproducibility: EstimatorParams.seed governs shuffling/dropout;
    # offset per rank so data orders differ across workers but not runs.
    tf.keras.utils.set_random_seed(int(spec["seed"]) + rank)
    shard = store.load_arrays(store.get_train_data_path(str(rank)))
    x, y = shard["x"], shard["y"]

    model = tf.keras.models.model_from_json(
        spec["model_json"], custom_objects=spec["custom_objects"])
    model.set_weights(spec["weights"])

    opt = tf.keras.optimizers.deserialize(spec["optimizer"])
    # Scale LR by worker count (reference remote.py: k.backend.set_value
    # (model.optimizer.lr, lr * hvd.size())).
    try:
        opt.learning_rate.assign(
            float(opt.learning_rate.numpy()) * hvd.num_processes())
    except (AttributeError, TypeError):  # exotic schedules: leave as-is
        pass
    opt = hvd_keras.DistributedOptimizer(opt)

    loss = spec["loss"]
    if isinstance(loss, dict):
        loss = tf.keras.losses.deserialize(loss)
    model.compile(optimizer=opt, loss=loss, metrics=spec["metrics"],
                  run_eagerly=True)

    callbacks = [
        hvd_keras.BroadcastGlobalVariablesCallback(0),
        hvd_keras.MetricAverageCallback(),
    ] + list(spec["callbacks"] or [])

    bs = spec["batch_size"]
    steps = _steps_per_epoch(spec["n_total"], num_proc, bs)
    val_kwargs = {}
    if spec.get("n_val"):
        vshard = store.load_arrays(store.get_val_data_path(str(rank)))
        val_kwargs = {"validation_data": (vshard["x"], vshard["y"])}
    history = model.fit(
        x, y,
        batch_size=bs,
        epochs=spec["epochs"],
        steps_per_epoch=steps,
        shuffle=spec["shuffle"],
        verbose=spec["verbose"],
        callbacks=callbacks,
        **val_kwargs,
    )

    if rank == 0:
        store.save_obj(store.get_checkpoint_path(run_id), {
            "weights": [np.asarray(w) for w in model.get_weights()],
            "history": {k: [float(v) for v in vs]
                        for k, vs in history.history.items()},
        })
    hvd.barrier()
    return True


from horovod_tpu.estimator.dataframe import DataFrameFitMixin


class KerasEstimator(DataFrameFitMixin):
    """Distributed-training estimator for a tf.keras model (reference
    ``KerasEstimator``): pass an (uncompiled) model plus optimizer/loss/
    metrics; ``fit(x, y)`` trains on ``params.num_proc`` ranks and
    returns a :class:`KerasModel` transformer."""

    def __init__(self, *, model, optimizer, loss, metrics=None,
                 callbacks: Optional[List] = None,
                 custom_objects: Optional[Dict] = None,
                 store: Store, params: Optional[EstimatorParams] = None):
        import tensorflow as tf  # noqa: F401 — import gate

        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics
        self.callbacks = callbacks
        self.custom_objects = custom_objects or {}
        self.store = store
        self.params = params or EstimatorParams()

    def fit(self, x: np.ndarray, y: np.ndarray) -> "KerasModel":
        from horovod_tpu.runner import run_func

        p = self.params
        run_id = p.run_id or f"run_{uuid.uuid4().hex[:8]}"
        remote_store = self.store.to_remote()
        n_train, n_val = _stage_data(remote_store, x, y, p)

        spec = _serialize_keras(self.model, self.optimizer, self.loss,
                                self.metrics)
        spec.update({
            "custom_objects": self.custom_objects,
            "callbacks": self.callbacks,
            "batch_size": p.batch_size,
            "epochs": p.epochs,
            "shuffle": p.shuffle,
            "seed": p.seed,
            "verbose": p.verbose,
            "n_total": n_train,
            "n_val": n_val,
        })
        run_func.run(
            _keras_train_fn, (remote_store, run_id, spec, p.num_proc),
            num_proc=p.num_proc, use_jax_platform=resolve_platform(p),
        )
        ckpt = remote_store.load_obj(remote_store.get_checkpoint_path(run_id))
        return KerasModel(
            model_json=spec["model_json"],
            weights=ckpt["weights"],
            custom_objects=self.custom_objects,
            history=ckpt["history"],
            run_id=run_id,
        )


@dataclass(eq=False)  # auto __eq__ over ndarray fields raises on compare
class KerasModel:
    """Trained-model transformer (reference ``KerasModel``,
    ``spark/keras/estimator.py``): self-contained — rebuilds the keras
    model from its serialized architecture + trained weights."""

    model_json: str
    weights: List[np.ndarray]
    custom_objects: Dict = field(default_factory=dict)
    history: Dict[str, List[float]] = field(default_factory=dict)
    run_id: str = ""

    def keras_model(self):
        import tensorflow as tf

        model = tf.keras.models.model_from_json(
            self.model_json, custom_objects=self.custom_objects)
        model.set_weights(self.weights)
        return model

    def predict(self, x: np.ndarray) -> np.ndarray:
        if getattr(self, "_model", None) is None:
            self._model = self.keras_model()
        return np.asarray(self._model.predict(np.asarray(x), verbose=0))

    def transform(self, x: np.ndarray) -> np.ndarray:  # Spark naming
        return self.predict(x)
