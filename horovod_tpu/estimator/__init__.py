"""Estimator layer: Store + fit()/predict() estimators (the reference's
Spark Estimator framework, ``horovod/spark/common/*`` — SURVEY.md §2.5 —
re-designed over the run-func launcher instead of Spark)."""

from horovod_tpu.estimator.estimator import (  # noqa: F401
    EstimatorParams,
    JaxEstimator,
    JaxModel,
    TorchEstimator,
    TorchModel,
)
from horovod_tpu.estimator.store import (  # noqa: F401
    HDFSStore,
    LocalStore,
    Store,
    shard_arrays,
)

# KerasEstimator is import-gated on tensorflow (reference: the Keras
# estimator lives under spark/keras/ and imports keras lazily).
try:
    from horovod_tpu.estimator.keras import (  # noqa: F401
        KerasEstimator,
        KerasModel,
    )
except ImportError:  # pragma: no cover - TF absent
    pass
