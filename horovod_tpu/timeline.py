"""Chrome-tracing timeline, host-side spans.

Reference: ``horovod/common/timeline.{h,cc}`` — per-tensor lifecycle events
written as Chrome trace JSON by a dedicated writer thread fed from a
lock-free queue (SURVEY.md §5.1).

TPU re-design: inside a compiled step there is no negotiation to trace (the
schedule is static) — device-side detail comes from the XLA/TPU profiler
(``jax.profiler.trace``), which :func:`Timeline.profile` wraps.  What this
module traces is the host side the profiler can't see: eager collectives,
step boundaries, data loading, checkpointing.  Events flow through a
plain queue to a writer thread so the hot path never touches file IO —
the same decoupling as the reference's SPSC queue (``timeline.h:68-70``).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import queue
import threading
import time
from typing import Optional

import jax


def expand_rank_path(path: str, rank: Optional[int] = None) -> str:
    """Substitute ``%r`` in a trace-file path with this process's rank
    (``HOROVOD_RANK``, else the initialized context's process rank,
    else 0) — so every rank of a multi-process run writes its own file
    instead of all clobbering one (merge them afterwards with
    ``python -m horovod_tpu.obs.merge``)."""
    if "%r" not in path:
        return path
    if rank is None:
        env = os.environ.get("HOROVOD_RANK")
        if env not in (None, ""):
            rank = int(env)
        else:
            from horovod_tpu import basics

            rank = basics.process_rank() if basics.is_initialized() else 0
    return path.replace("%r", str(rank))


def _dropped_events_counter():
    """Create-or-fetch the process-wide dropped-events counter (shared
    by every Timeline instance; also seeded at init so /metrics exposes
    the family before any timeline exists)."""
    from horovod_tpu.obs.registry import default_registry

    return default_registry().counter(
        "timeline_dropped_events_total",
        "Timeline events dropped on a full writer queue "
        "(the trace file has gaps)", exist_ok=True)


class Timeline:
    def __init__(self, path: str, *, pid: Optional[int] = None,
                 queue_size: int = 1 << 20) -> None:
        path = expand_rank_path(path)
        self.path = path
        self.pid = pid if pid is not None else os.getpid()
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        # Dropped-event accounting: _emit sheds load on queue.Full to
        # protect the hot path, but silent loss would make a sparse
        # trace look like a quiet system — count every drop (here and
        # in the process registry) and flush the total as a trailing
        # event on close() so the trace file discloses its own gaps.
        self.dropped_events = 0
        try:
            self._dropped_counter = _dropped_events_counter()
        except Exception:  # pragma: no cover - registry must not gate IO
            self._dropped_counter = None
        self._writer = threading.Thread(target=self._drain, daemon=True)
        self._writer.start()
        atexit.register(self.close)

    # -- event emission (microsecond timestamps, Chrome trace format) -------

    def _emit(self, ev: dict) -> None:
        if self._closed:
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:  # drop rather than stall the hot path
            self.dropped_events += 1
            if self._dropped_counter is not None:
                self._dropped_counter.inc()

    def emit_batch(self, evs: list) -> None:
        """Enqueue a pre-built group of events as ONE queue item (one
        writer wakeup) — the hot-emitter path (engine tick phases)."""
        if self._closed or not evs:
            return
        try:
            self._q.put_nowait(evs)
        except queue.Full:
            self.dropped_events += len(evs)
            if self._dropped_counter is not None:
                self._dropped_counter.inc(len(evs))

    def begin(self, name: str, category: str = "host", tid: int = 0) -> None:
        self._emit(
            {
                "name": name,
                "cat": category,
                "ph": "B",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": tid,
            }
        )

    def end(self, name: str, tid: int = 0) -> None:
        self._emit(
            {
                "name": name,
                "ph": "E",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": tid,
            }
        )

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "p",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": 0,
                "args": args or {},
            }
        )

    def complete(self, name: str, start_s: float, dur_s: float,
                 category: str = "host", tid: int = 0,
                 args: Optional[dict] = None) -> None:
        """A complete span (Chrome ``X`` event) with an explicit start
        and duration in ``time.monotonic()`` SECONDS — for spans whose
        boundaries were stamped elsewhere (the request tracer resolves
        a span only once the request retires)."""
        ev = {
            "name": name,
            "cat": category,
            "ph": "X",
            "ts": start_s * 1e6,
            "dur": max(dur_s, 0.0) * 1e6,
            "pid": self.pid,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        self._emit(ev)

    def thread_name(self, tid: int, name: str) -> None:
        """Label a synthetic thread row (Chrome ``M``/thread_name
        metadata) — Perfetto shows the label instead of a bare tid."""
        self._emit({
            "name": "thread_name",
            "ph": "M",
            "pid": self.pid,
            "tid": tid,
            "args": {"name": name},
        })

    def mark_cycle(self) -> None:
        """Cycle marker (``HOROVOD_TIMELINE_MARK_CYCLES``,
        ``operations.cc:392-405``) — on TPU, one per train step."""
        self.instant("CYCLE")

    @contextlib.contextmanager
    def activity(self, name: str, category: str = "host", tid: int = 0):
        """Span context manager (the reference's ActivityStart/End pairs,
        ``common.h:31-59``)."""
        self.begin(name, category, tid)
        try:
            yield
        finally:
            self.end(name, tid)

    @contextlib.contextmanager
    def profile(self, logdir: str):
        """Bracket a region with the XLA/TPU profiler — the device-side
        complement of the host timeline."""
        with jax.profiler.trace(logdir):
            yield

    # -- writer thread -------------------------------------------------------

    def _drain(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                return
            # A list is a pre-batched group (Tracer.tick_phase): one
            # queue wakeup carries many events, so a hot emitter costs
            # one writer context switch per BATCH instead of per event.
            for e in (ev if isinstance(ev, list) else (ev,)):
                if not self._first:
                    self._file.write(",\n")
                self._first = False
                json.dump(e, self._file)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=5)
        if self._writer.is_alive():
            # The writer is still draining a huge backlog: the file is
            # NOT ours — appending the trailer or closing would
            # interleave with (and crash) the writer.  Leave the trace
            # truncated (no closing bracket) rather than corrupted; the
            # daemon writer exits at the None sentinel it already has.
            return
        if self.dropped_events:
            # Trailing disclosure: the writer thread is done, so the
            # file (and the _first separator state) is ours to append
            # the drop count as one final instant event.
            if not self._first:
                self._file.write(",\n")
            self._first = False
            json.dump({
                "name": "TIMELINE_DROPPED_EVENTS",
                "ph": "i",
                "s": "g",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": 0,
                "args": {"dropped_events": self.dropped_events},
            }, self._file)
        self._file.write("\n]\n")
        self._file.close()


_timeline: Optional[Timeline] = None


def start_timeline(path: str, mark_cycles: bool = False) -> Timeline:
    """``hvd.start_timeline`` parity (``common/basics.py``).

    ``mark_cycles`` exports ``HOROVOD_TIMELINE_MARK_CYCLES`` so the
    native control plane (which owns the negotiation cycles) emits a
    cycle tick per background iteration.  The native runtime latches the
    flag at ``hvd.init()`` — when it is already running, the export only
    reaches FUTURE inits, so warn rather than silently no-op (the
    launcher's ``--timeline-mark-cycles`` flag sets the env before
    workers init and is the reliable path)."""
    global _timeline
    if _timeline is not None:
        raise ValueError("timeline already started")
    if mark_cycles:
        os.environ["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
        from horovod_tpu import basics

        if basics.is_initialized():
            import logging

            logging.getLogger("horovod_tpu").warning(
                "start_timeline(mark_cycles=True) after init(): the "
                "native runtime latched the flag at init, so cycle "
                "ticks start at the NEXT init; set "
                "HOROVOD_TIMELINE_MARK_CYCLES=1 (or use horovodrun "
                "--timeline-mark-cycles) before init() instead")
    _timeline = Timeline(path)
    return _timeline


def stop_timeline() -> None:
    global _timeline
    if _timeline is not None:
        _timeline.close()
        _timeline = None
    # don't leak the cycle-marker request into a later, unrelated init
    os.environ.pop("HOROVOD_TIMELINE_MARK_CYCLES", None)


def get() -> Optional[Timeline]:
    from horovod_tpu import basics

    if _timeline is not None:
        return _timeline
    if basics.is_initialized():
        return basics._ctx().timeline
    return None


@contextlib.contextmanager
def trace(name: str, category: str = "host"):
    """Nest a user-named span into the active timeline; no-op (zero
    overhead beyond the lookup) when no timeline is recording — safe to
    leave in production training loops."""
    tl = get()
    if tl is None:
        yield
        return
    with tl.activity(name, category):
        yield
