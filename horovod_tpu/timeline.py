"""Chrome-tracing timeline, host-side spans.

Reference: ``horovod/common/timeline.{h,cc}`` — per-tensor lifecycle events
written as Chrome trace JSON by a dedicated writer thread fed from a
lock-free queue (SURVEY.md §5.1).

TPU re-design: inside a compiled step there is no negotiation to trace (the
schedule is static) — device-side detail comes from the XLA/TPU profiler
(``jax.profiler.trace``), which :func:`Timeline.profile` wraps.  What this
module traces is the host side the profiler can't see: eager collectives,
step boundaries, data loading, checkpointing.  Events flow through a
plain queue to a writer thread so the hot path never touches file IO —
the same decoupling as the reference's SPSC queue (``timeline.h:68-70``).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import queue
import threading
import time
from typing import Optional

import jax


class Timeline:
    def __init__(self, path: str, *, pid: Optional[int] = None) -> None:
        self.path = path
        self.pid = pid if pid is not None else os.getpid()
        self._q: "queue.Queue" = queue.Queue(maxsize=1 << 20)
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._writer = threading.Thread(target=self._drain, daemon=True)
        self._writer.start()
        atexit.register(self.close)

    # -- event emission (microsecond timestamps, Chrome trace format) -------

    def _emit(self, ev: dict) -> None:
        if self._closed:
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:  # drop rather than stall the hot path
            pass

    def begin(self, name: str, category: str = "host", tid: int = 0) -> None:
        self._emit(
            {
                "name": name,
                "cat": category,
                "ph": "B",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": tid,
            }
        )

    def end(self, name: str, tid: int = 0) -> None:
        self._emit(
            {
                "name": name,
                "ph": "E",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": tid,
            }
        )

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "p",
                "ts": time.monotonic_ns() / 1e3,
                "pid": self.pid,
                "tid": 0,
                "args": args or {},
            }
        )

    def mark_cycle(self) -> None:
        """Cycle marker (``HOROVOD_TIMELINE_MARK_CYCLES``,
        ``operations.cc:392-405``) — on TPU, one per train step."""
        self.instant("CYCLE")

    @contextlib.contextmanager
    def activity(self, name: str, category: str = "host", tid: int = 0):
        """Span context manager (the reference's ActivityStart/End pairs,
        ``common.h:31-59``)."""
        self.begin(name, category, tid)
        try:
            yield
        finally:
            self.end(name, tid)

    @contextlib.contextmanager
    def profile(self, logdir: str):
        """Bracket a region with the XLA/TPU profiler — the device-side
        complement of the host timeline."""
        with jax.profiler.trace(logdir):
            yield

    # -- writer thread -------------------------------------------------------

    def _drain(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                return
            if not self._first:
                self._file.write(",\n")
            self._first = False
            json.dump(ev, self._file)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=5)
        self._file.write("\n]\n")
        self._file.close()


_timeline: Optional[Timeline] = None


def start_timeline(path: str, mark_cycles: bool = False) -> Timeline:
    """``hvd.start_timeline`` parity (``common/basics.py``).

    ``mark_cycles`` exports ``HOROVOD_TIMELINE_MARK_CYCLES`` so the
    native control plane (which owns the negotiation cycles) emits a
    cycle tick per background iteration.  The native runtime latches the
    flag at ``hvd.init()`` — when it is already running, the export only
    reaches FUTURE inits, so warn rather than silently no-op (the
    launcher's ``--timeline-mark-cycles`` flag sets the env before
    workers init and is the reliable path)."""
    global _timeline
    if _timeline is not None:
        raise ValueError("timeline already started")
    if mark_cycles:
        os.environ["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
        from horovod_tpu import basics

        if basics.is_initialized():
            import logging

            logging.getLogger("horovod_tpu").warning(
                "start_timeline(mark_cycles=True) after init(): the "
                "native runtime latched the flag at init, so cycle "
                "ticks start at the NEXT init; set "
                "HOROVOD_TIMELINE_MARK_CYCLES=1 (or use horovodrun "
                "--timeline-mark-cycles) before init() instead")
    _timeline = Timeline(path)
    return _timeline


def stop_timeline() -> None:
    global _timeline
    if _timeline is not None:
        _timeline.close()
        _timeline = None
    # don't leak the cycle-marker request into a later, unrelated init
    os.environ.pop("HOROVOD_TIMELINE_MARK_CYCLES", None)


def get() -> Optional[Timeline]:
    from horovod_tpu import basics

    if _timeline is not None:
        return _timeline
    if basics.is_initialized():
        return basics._ctx().timeline
    return None


@contextlib.contextmanager
def trace(name: str, category: str = "host"):
    """Nest a user-named span into the active timeline; no-op (zero
    overhead beyond the lookup) when no timeline is recording — safe to
    leave in production training loops."""
    tl = get()
    if tl is None:
        yield
        return
    with tl.activity(name, category):
        yield
