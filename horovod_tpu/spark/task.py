"""Spark task service: the executor-side half of ``horovod_tpu.spark.run``.

Reference: ``horovod/spark/task/task_service.py`` + the task half of
``spark/__init__.py:39-71`` — each Spark task registers with the driver,
ring-probes the next task's addresses to find routable NICs, then
executes the per-rank entry (``mpirun_exec_fn``).

TPU re-design: the task talks to the driver through the signed rendezvous
KV, reuses the launcher's ring NIC probe (:mod:`horovod_tpu.runner.
discovery`), and runs ``fn`` IN the Spark task process with the standard
``HOROVOD_*`` env contract — no orted tunnel; JAX distributed init does
the wire-up when ``fn`` calls ``horovod_tpu.init()``.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import time
from typing import Any

import cloudpickle

from horovod_tpu.runner import discovery
from horovod_tpu.runner import secret as _secret
from horovod_tpu.runner.rendezvous import KVClient

from horovod_tpu.spark.driver import SCOPE


def host_hash() -> str:
    """Stable identifier of the machine a task runs on (reference
    ``run/common/util/host_hash.py``: hostname-derived hash used to group
    task indices into hosts).  Overridable via ``HOROVOD_HOST_HASH`` for
    tests and containerized setups where hostnames lie."""
    override = os.environ.get("HOROVOD_HOST_HASH")
    if override:
        return override
    return hashlib.md5(socket.gethostname().encode()).hexdigest()[:16]


def _wait(kv: KVClient, key: str, timeout: float) -> bytes:
    """kv.wait that also aborts promptly if the driver flagged failure
    (reference notify_spark_job_failed → tasks stop blocking)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if kv.get(SCOPE, "failed") is not None:
            raise RuntimeError(
                "Spark driver reported job failure; aborting task")
        v = kv.get(SCOPE, key)
        if v is not None:
            return v
        time.sleep(0.1)
    raise TimeoutError(f"rendezvous key {SCOPE}/{key} not published")


def task_main(index: int, driver_addr: str, driver_port: int,
              secret_key: str = "", timeout: float = 600.0) -> Any:
    """Body of one Spark task (reference ``_task_fn``,
    ``spark/__init__.py:39-71``):

    1. register index + host hash + candidate addresses with the driver;
    2. ring-probe the next task's listener for routable NICs
       (``runner.discovery``);
    3. receive the rank assignment and coordinator address;
    4. export the standard ``HOROVOD_*`` env and execute ``fn``.

    ``secret_key`` is the driver's per-job HMAC key, shipped INSIDE the
    Spark task closure (the reference ships its secret the same way,
    inside the pickled task fn): an executor on another machine has a
    fresh environment, and without the key it could not even read the
    signed KV entry that carries the job's env.

    Returns ``(rank, fn result)`` so the driver can order the collected
    results by rank, matching the reference's return contract.
    """
    if secret_key:
        os.environ[_secret.ENV_KEY] = secret_key
    kv = KVClient(driver_addr, driver_port)
    num_proc = int(_wait(kv, "num_proc", timeout))

    kv.put(SCOPE, f"task.{index}", json.dumps({
        "index": index,
        "host_hash": host_hash(),
        "addrs": discovery.local_addresses(),
    }).encode())

    # Ring NIC probe: same handshake the launcher uses (reference tasks
    # probe next_task_client with match_intf=True).
    discovery.run_task_discovery(kv, index, num_proc, timeout=timeout)

    ranks = json.loads(_wait(kv, "ranks", timeout))
    rank = int(ranks["index_to_rank"][str(index)])
    my_host = ranks["host_hash_by_index"][str(index)]
    local_size = int(ranks["local_size_by_host"][my_host])
    peers_on_host = sorted(
        int(i) for i, h in ranks["host_hash_by_index"].items()
        if h == my_host
    )
    local_rank = peers_on_host.index(index)
    coord = json.loads(_wait(kv, "coordinator", timeout))

    fn, args, kwargs, extra_env = cloudpickle.loads(
        _wait(kv, "fn", timeout))

    # User env first, the computed HOROVOD_* contract ON TOP — a user
    # propagating their shell env (which may carry stale HOROVOD_RANK /
    # coordinator exports) must not clobber the task's wiring.
    env = dict(extra_env)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_NUM_PROC": str(num_proc),
        "HOROVOD_SIZE": str(num_proc),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_COORDINATOR_ADDR": coord["addr"],
        "HOROVOD_JAX_PORT": str(coord["jax_port"]),
        "HOROVOD_NATIVE_PORT": str(coord["native_port"]),
    })
    os.environ.update(env)

    result = fn(*args, **kwargs)
    return rank, result
