"""Spark integration (reference: ``horovod/spark/__init__.py:39-239``).

``horovod_tpu.spark.run(fn)`` mirrors ``horovod.spark.run``: execute
``fn`` as ``num_proc`` tasks of a Spark job with full Horovod rank/
rendezvous wiring.  PySpark is not part of this image, so the module
degrades gracefully: with pyspark importable the Spark path runs; without
it, ``run`` falls back to the local run-func launcher (same fn contract)
and the Estimators are importable from :mod:`horovod_tpu.estimator`,
which carries the Store/fit/transform API the reference implements over
Spark DataFrames (SURVEY.md §2.5).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.estimator import (  # noqa: F401 — estimator parity surface
    EstimatorParams,
    HDFSStore,
    JaxEstimator,
    JaxModel,
    LocalStore,
    Store,
    TorchEstimator,
    TorchModel,
)

try:  # TF-gated, like the reference's spark/keras subpackage
    from horovod_tpu.estimator import KerasEstimator, KerasModel  # noqa: F401
except ImportError:  # pragma: no cover - TF absent
    pass


def _pyspark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def run(fn: Callable, args: tuple = (), kwargs: Optional[Dict] = None,
        num_proc: Optional[int] = None, env: Optional[Dict] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` parallel workers with Horovod wiring.

    Reference contract (``spark/__init__.py:104-239``): returns the list
    of each worker's return value.  On a machine with pyspark + an active
    SparkContext the workers are Spark tasks; otherwise they are local
    launcher processes (the capability the reference's Spark layer
    ultimately provides — N coordinated fn executions).
    """
    from horovod_tpu.runner import run_func

    nproc = num_proc or 2
    if _pyspark_available():
        from pyspark import SparkContext

        sc = SparkContext._active_spark_context
        if sc is not None:
            return _spark_run(sc, fn, args, kwargs or {}, num_proc, env,
                              verbose)
    if verbose:
        print(f"[horovod_tpu.spark] no active SparkContext; running "
              f"{nproc} local launcher processes")
    return run_func.run(fn, args, kwargs, num_proc=nproc, env=env)


def _spark_run(sc, fn, args, kwargs, num_proc, env, verbose,
               start_timeout: float = 600.0):
    """Spark task path (reference ``spark/__init__.py:104-239``): the
    driver hosts the job's signed rendezvous KV
    (:class:`horovod_tpu.spark.driver.SparkDriverService`), Spark tasks
    run :func:`horovod_tpu.spark.task.task_main` — register, ring NIC
    probe, rank assignment, env wiring, fn execution — and results come
    back rank-ordered through the RDD collect.

    The Spark job runs in a side thread (reference _make_spark_thread) so
    the driver can coordinate registration while ``collect()`` blocks; a
    task failure cancels the job group and flags the KV so blocked tasks
    abort instead of hanging.
    """
    import queue
    import socket
    import threading
    import time

    from horovod_tpu.runner import discovery
    from horovod_tpu.runner import secret as secret_mod
    from horovod_tpu.spark import task as task_mod
    from horovod_tpu.spark.driver import SCOPE, SparkDriverService

    num = num_proc or sc.defaultParallelism
    # The per-job HMAC key travels INSIDE the task closure (Spark's own
    # serialized-closure channel): executors on other machines have fresh
    # environments, and without the key they could not read a single
    # signed KV entry — including the one carrying the job env.  A key is
    # GENERATED when none is exported (same as launch_job): the driver's
    # KV listens on an open port and tasks cloudpickle what they read
    # from it, so an unsigned KV would be remote code execution for
    # anyone who can reach the port.  Exported before the driver starts
    # so its server verifies from the first request.
    secret_key = (secret_mod.get_key() or b"").decode() \
        or secret_mod.make_secret_key()
    os.environ[secret_mod.ENV_KEY] = secret_key
    driver = SparkDriverService(num, fn, args, kwargs, env)
    driver_host = os.environ.get("HOROVOD_HOSTNAME") or socket.gethostbyname(
        socket.gethostname())
    driver_port = driver.port
    job_group = f"horovod_tpu.spark.{driver_port}"

    if verbose:
        print(f"[horovod_tpu.spark] running {num} Spark tasks; rendezvous "
              f"at {driver_host}:{driver_port}")

    result_q: "queue.Queue" = queue.Queue()

    def _run_job():
        try:
            sc.setJobGroup(job_group, "horovod_tpu.spark.run",
                           interruptOnCancel=True)
            res = (
                sc.parallelize(range(num), num)
                .mapPartitionsWithIndex(
                    lambda i, _it: [task_mod.task_main(
                        i, driver_host, driver_port, secret_key,
                        timeout=start_timeout)])
                .collect()
            )
            result_q.put(("ok", res))
        except BaseException as e:  # noqa: BLE001 - propagate to caller
            driver.notify_job_failed()
            result_q.put(("error", e))

    job_thread = threading.Thread(target=_run_job, daemon=True)
    job_thread.start()

    def _discover_with_abort(deadline: float):
        """discovery.discover, but re-checked every few seconds so a task
        crash mid-probe aborts the driver promptly (via the failed flag
        _run_job sets) instead of blocking out the full start_timeout.
        discover() only reads published reach-reports, so retrying it is
        idempotent."""
        while True:
            if driver.failed or driver.kv.get(SCOPE, "failed") is not None:
                raise RuntimeError("Spark job failed during NIC discovery")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"NIC discovery did not complete within {start_timeout}s")
            try:
                return discovery.discover(driver.kv, num,
                                          timeout=min(remaining, 3.0))
            except TimeoutError:
                continue

    try:
        tasks = driver.wait_for_task_registration(timeout=start_timeout)
        mapping = SparkDriverService.assign_ranks(tasks)
        driver.publish_ranks(mapping, tasks)
        # Ring NIC probe reports land in the same KV; pick rank 0's
        # verified-routable address as the coordinator.
        routable = _discover_with_abort(time.monotonic() + start_timeout)
        rank0_index = next(i for i, r in mapping.items() if r == 0)
        driver.publish_coordinator(
            routable.get(rank0_index, tasks[rank0_index]["addrs"][0]),
            jax_port=9373, native_port=9374)
    except BaseException as startup_err:
        driver.notify_job_failed()
        try:
            sc.cancelJobGroup(job_group)
        except Exception:
            pass
        driver.shutdown()
        # A task may have crashed first: surface ITS error (queued by
        # _run_job) instead of the driver-side timeout that masked it.
        try:
            kind, payload = result_q.get_nowait()
        except queue.Empty:
            raise startup_err
        if kind == "error":
            raise RuntimeError(
                "horovod_tpu.spark.run: Spark job failed during "
                "startup") from payload
        raise startup_err

    try:
        # start_timeout bounds STARTUP (registration/probe, above) only;
        # fn may train for hours — wait for collect() indefinitely.
        kind, payload = result_q.get()
        if kind == "error":
            raise RuntimeError(
                "horovod_tpu.spark.run: Spark job failed") from payload
        # task_main returns (rank, result); order by rank like the
        # reference's ranks_to_indices-mapped results.
        return [r for _, r in sorted(payload, key=lambda p: p[0])]
    finally:
        job_thread.join(timeout=10)
        driver.shutdown()
