"""Spark integration (reference: ``horovod/spark/__init__.py:39-239``).

``horovod_tpu.spark.run(fn)`` mirrors ``horovod.spark.run``: execute
``fn`` as ``num_proc`` tasks of a Spark job with full Horovod rank/
rendezvous wiring.  PySpark is not part of this image, so the module
degrades gracefully: with pyspark importable the Spark path runs; without
it, ``run`` falls back to the local run-func launcher (same fn contract)
and the Estimators are importable from :mod:`horovod_tpu.estimator`,
which carries the Store/fit/transform API the reference implements over
Spark DataFrames (SURVEY.md §2.5).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.estimator import (  # noqa: F401 — estimator parity surface
    EstimatorParams,
    HDFSStore,
    JaxEstimator,
    JaxModel,
    LocalStore,
    Store,
    TorchEstimator,
    TorchModel,
)


def _pyspark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def run(fn: Callable, args: tuple = (), kwargs: Optional[Dict] = None,
        num_proc: Optional[int] = None, env: Optional[Dict] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` parallel workers with Horovod wiring.

    Reference contract (``spark/__init__.py:104-239``): returns the list
    of each worker's return value.  On a machine with pyspark + an active
    SparkContext the workers are Spark tasks; otherwise they are local
    launcher processes (the capability the reference's Spark layer
    ultimately provides — N coordinated fn executions).
    """
    from horovod_tpu.runner import run_func

    nproc = num_proc or 2
    if _pyspark_available():
        from pyspark import SparkContext

        sc = SparkContext._active_spark_context
        if sc is not None:
            return _spark_run(sc, fn, args, kwargs or {}, num_proc, env,
                              verbose)
    if verbose:
        print(f"[horovod_tpu.spark] no active SparkContext; running "
              f"{nproc} local launcher processes")
    return run_func.run(fn, args, kwargs, num_proc=nproc, env=env)


def _spark_run(sc, fn, args, kwargs, num_proc, env, verbose):
    """Spark task path (reference ``spark/__init__.py:104-239``): the
    driver hosts the rendezvous KV server; tasks register their host,
    learn rank 0's address, export the coordinator env, then run fn."""
    import socket

    from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer

    num = num_proc or sc.defaultParallelism
    server = RendezvousServer(0)
    port = server.start()
    driver_host = os.environ.get("HOROVOD_HOSTNAME") or socket.gethostbyname(
        socket.gethostname())
    jax_port = 9373
    native_port = 9374
    extra_env = dict(env or {})

    def _task(index):
        import os as _os
        import socket as _socket

        kv = KVClient(driver_host, port)
        my_host = _socket.gethostbyname(_socket.gethostname())
        kv.put("hosts", str(index), my_host.encode())
        rank0_host = kv.wait("hosts", "0", timeout=120).decode()
        _os.environ.update(extra_env)
        _os.environ.update({
            "HOROVOD_RANK": str(index),
            "HOROVOD_NUM_PROC": str(num),
            "HOROVOD_COORDINATOR_ADDR": rank0_host,
            "HOROVOD_JAX_PORT": str(jax_port),
            "HOROVOD_NATIVE_PORT": str(native_port),
        })
        return [fn(*(args or ()), **kwargs)]

    if verbose:
        print(f"[horovod_tpu.spark] running {num} Spark tasks; rendezvous "
              f"at {driver_host}:{port}")
    try:
        return (
            sc.parallelize(range(num), num)
            .mapPartitionsWithIndex(lambda i, _: _task(i))
            .collect()
        )
    finally:
        server.stop()
