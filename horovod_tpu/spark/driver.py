"""Spark driver service: the launcher-side coordinator of a Spark job.

Reference: ``horovod/spark/driver/driver_service.py`` (SparkDriverService /
SparkDriverClient) and the driver half of ``spark/__init__.py:104-239`` —
the driver holds the pickled ``fn``, collects task registrations and host
hashes, assigns ranks host-contiguously, and distributes the coordination
addresses.

TPU re-design: instead of a pickled-RPC BasicService, the driver hosts the
job's signed rendezvous KV server (:mod:`horovod_tpu.runner.rendezvous`)
and all driver↔task traffic is KV puts/waits — the same transport the
launcher already uses, so Spark tasks bootstrap exactly like
``horovodrun``-spawned ranks.  The orted/mpirun_rsh tunnel disappears:
tasks run ``fn`` in-process and JAX's distributed runtime (rank 0 =
coordinator) replaces the MPI wire-up.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from horovod_tpu.runner.rendezvous import KVClient, RendezvousServer

SCOPE = "spark"


class SparkDriverService:
    """Drives one ``horovod_tpu.spark.run`` job over the rendezvous KV."""

    def __init__(self, num_proc: int, fn, args: tuple, kwargs: Dict,
                 env: Optional[Dict[str, str]] = None) -> None:
        self.num_proc = num_proc
        self._server = RendezvousServer(0)
        self.port = self._server.start()
        self._kv: Optional[KVClient] = None
        self._failed = False
        payload = cloudpickle.dumps((fn, args, kwargs, dict(env or {})))
        self.kv.put(SCOPE, "fn", payload)
        self.kv.put(SCOPE, "num_proc", str(num_proc).encode())

    @property
    def kv(self) -> KVClient:
        if self._kv is None:
            self._kv = KVClient("127.0.0.1", self.port)
        return self._kv

    # -- registration (reference wait_for_initial_registration) ------------

    def wait_for_task_registration(self, timeout: float = 600.0
                                   ) -> List[Dict[str, Any]]:
        """Block until all ``num_proc`` tasks registered; returns their
        records ``{"index", "host_hash", "addrs"}`` in index order."""
        deadline = time.monotonic() + timeout
        tasks = []
        for i in range(self.num_proc):
            while True:
                if self._failed or self.kv.get(SCOPE, "failed") is not None:
                    raise RuntimeError(
                        "Spark job failed before all tasks registered")
                rec = self.kv.get(SCOPE, f"task.{i}")
                if rec is not None:
                    break
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"Spark tasks did not register within {timeout}s "
                        f"(got {i}/{self.num_proc}); cluster may lack free "
                        "executors — the reference raises the same way")
                time.sleep(0.1)
            tasks.append(json.loads(rec))
        return tasks

    # -- rank assignment (reference ranks_to_indices / host grouping) ------

    @staticmethod
    def assign_ranks(tasks: List[Dict[str, Any]]) -> Dict[int, int]:
        """task index → rank, host-contiguous: tasks sharing a host hash
        get consecutive ranks so ``local_rank`` is meaningful (the
        reference builds its hosts string the same way,
        ``spark/__init__.py:193-205``)."""
        by_host: Dict[str, List[int]] = {}
        for t in tasks:
            by_host.setdefault(t["host_hash"], []).append(t["index"])
        rank = 0
        mapping: Dict[int, int] = {}
        for host in sorted(by_host):
            for idx in sorted(by_host[host]):
                mapping[idx] = rank
                rank += 1
        return mapping

    def publish_ranks(self, mapping: Dict[int, int],
                      tasks: List[Dict[str, Any]]) -> None:
        local_sizes: Dict[str, int] = {}
        for t in tasks:
            local_sizes[t["host_hash"]] = local_sizes.get(t["host_hash"], 0) + 1
        payload = {
            "index_to_rank": {str(k): v for k, v in mapping.items()},
            "host_hash_by_index": {str(t["index"]): t["host_hash"]
                                   for t in tasks},
            "local_size_by_host": local_sizes,
        }
        self.kv.put(SCOPE, "ranks", json.dumps(payload).encode())

    def publish_coordinator(self, addr: str, jax_port: int,
                            native_port: int) -> None:
        """Publish rank 0's routable address (from the ring NIC probe) —
        the value the reference distributes as the mpirun host/interface
        selection."""
        self.kv.put(SCOPE, "coordinator", json.dumps(
            {"addr": addr, "jax_port": jax_port,
             "native_port": native_port}).encode())

    def notify_job_failed(self) -> None:
        """Mark the job failed so blocked tasks abort rather than hang
        (reference notify_spark_job_failed)."""
        self._failed = True
        try:
            self.kv.put(SCOPE, "failed", b"1")
        except Exception:
            pass

    @property
    def failed(self) -> bool:
        return self._failed

    def shutdown(self) -> None:
        self._server.stop()
