"""Keras frontend (reference: ``horovod/keras/__init__.py`` +
``horovod/_keras/__init__.py``).  Import-gated on tensorflow like
:mod:`horovod_tpu.tensorflow`; the framework-agnostic callback semantics
(BroadcastGlobalVariables, MetricAverage, LR warmup/schedule) live in
:mod:`horovod_tpu.callbacks` and work for JAX training loops too.
"""

from __future__ import annotations

try:
    import tensorflow as tf  # noqa: F401
except ImportError as _e:  # pragma: no cover - TF absent in this image
    raise ImportError(
        "horovod_tpu.keras requires tensorflow; the callback semantics "
        "are available framework-agnostically in horovod_tpu.callbacks."
    ) from _e

from horovod_tpu.basics import (  # noqa: F401
    init, is_initialized, local_rank, local_size, rank, shutdown, size,
)
from horovod_tpu.tensorflow import (  # noqa: F401
    DistributedOptimizer,
    allgather,
    allreduce,
    broadcast,
    broadcast_variables,
)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast initial model/optimizer variables from root at train
    start (reference _keras/callbacks.py:20-43)."""

    def __init__(self, root_rank=0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        if not self._done:
            broadcast_variables(self.model.variables, self.root_rank)
            self._done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over workers (reference
    _keras/callbacks.py:46-84)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            import numpy as np

            for k in sorted(logs):
                v = logs[k]
                if isinstance(v, (int, float)):
                    from horovod_tpu.ops import collectives as C

                    logs[k] = float(C.allreduce(
                        np.asarray(v, np.float32), C.Average,
                        name=f"metric.{k}.{epoch}"))


def load_model(filepath, custom_objects=None, compression=None):
    """Load a keras model and re-wrap its optimizer (reference
    keras/__init__.py:117-150)."""
    model = tf.keras.models.load_model(
        filepath, custom_objects=custom_objects)
    if model.optimizer is not None:
        model.optimizer = DistributedOptimizer(model.optimizer)
    return model
