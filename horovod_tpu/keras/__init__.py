"""Keras frontend (reference: ``horovod/keras/__init__.py`` +
``horovod/_keras/__init__.py``).  Import-gated on tensorflow like
:mod:`horovod_tpu.tensorflow`; the framework-agnostic callback semantics
(BroadcastGlobalVariables, MetricAverage, LR warmup/schedule) live in
:mod:`horovod_tpu.callbacks` and work for JAX training loops too.
"""

from __future__ import annotations

try:
    import tensorflow as tf  # noqa: F401
except ImportError as _e:  # pragma: no cover - TF absent in this image
    raise ImportError(
        "horovod_tpu.keras requires tensorflow; the callback semantics "
        "are available framework-agnostically in horovod_tpu.callbacks."
    ) from _e

from horovod_tpu.basics import (  # noqa: F401
    init, is_initialized, local_rank, local_size, rank, shutdown, size,
)
from horovod_tpu.tensorflow import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    allgather,
    allreduce,
    broadcast,
    broadcast_variables,
)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast initial model/optimizer variables from root at train
    start (reference _keras/callbacks.py:20-43)."""

    def __init__(self, root_rank=0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        if not self._done:
            broadcast_variables(self.model.variables, self.root_rank)
            self._done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over workers (reference
    _keras/callbacks.py:46-84)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            import numpy as np

            for k in sorted(logs):
                v = logs[k]
                if isinstance(v, (int, float)):
                    from horovod_tpu.ops import collectives as C

                    logs[k] = float(C.allreduce(
                        np.asarray(v, np.float32), C.Average,
                        name=f"metric.{k}.{epoch}"))


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Multiply the optimizer's base LR by ``multiplier(epoch)`` within
    ``[start_epoch, end_epoch)`` (reference ``_keras/callbacks.py:87-150``).

    The base LR is read from the optimizer at train start, like the
    reference.  ``staircase=False`` evaluates the multiplier per batch at
    fractional epochs.  With ``momentum_correction`` (and a
    momentum-carrying optimizer), the momentum is rescaled by
    ``new_lr / old_lr`` for the batch where the LR changes, so the
    accumulated velocity doesn't over/under-shoot at the new scale —
    the reference's restore-momentum dance."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None, initial_lr=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = initial_lr
        self.current_epoch = 0
        self.restore_momentum = None
        self.multiplier = (multiplier if callable(multiplier)
                           else (lambda epoch: multiplier))

    def _lr_var(self):
        return self.model.optimizer.learning_rate

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = float(
                tf.keras.backend.get_value(self._lr_var()))

    def _in_range(self, epoch):
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _adjust(self, epoch):
        if not self._in_range(epoch):
            return
        old_lr = float(tf.keras.backend.get_value(self._lr_var()))
        new_lr = self.initial_lr * float(self.multiplier(epoch))
        self._lr_var().assign(new_lr)
        opt = self.model.optimizer
        if (self.momentum_correction and old_lr > 0
                and hasattr(opt, "momentum")
                and self.restore_momentum is None):
            m = float(tf.keras.backend.get_value(opt.momentum)) \
                if not isinstance(opt.momentum, float) else opt.momentum
            if m:
                self.restore_momentum = m
                self._set_momentum(m * new_lr / old_lr)

    def _set_momentum(self, value):
        opt = self.model.optimizer
        if isinstance(opt.momentum, float):
            opt.momentum = value
        else:
            opt.momentum.assign(value)

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase:
            if self.steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required when staircase=False")
            self._adjust(self.current_epoch + batch / self.steps_per_epoch)

    def on_batch_end(self, batch, logs=None):
        if self.restore_momentum is not None:
            self._set_momentum(self.restore_momentum)
            self.restore_momentum = None

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(tf.keras.backend.get_value(self._lr_var()))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup from base LR to base LR × workers over
    ``warmup_epochs`` (reference ``_keras/callbacks.py`` warmup; Goyal et
    al. 2017 recipe cited there)."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0, initial_lr=None):
        from horovod_tpu import basics

        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def mult(epoch):
            n = basics.size()
            return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)

        super().__init__(multiplier=mult, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch,
                         initial_lr=initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose:
            from horovod_tpu import basics

            if basics.rank() == 0:
                print(f"Epoch {epoch + 1}: finished gradual learning rate "
                      f"warmup to {logs.get('lr') if logs else None}.")


def load_model(filepath, custom_objects=None, compression=None):
    """Load a keras model and re-wrap its optimizer (reference
    keras/__init__.py:117-150 + _keras/__init__.py:103-115).

    Models saved while training carry the Distributed-wrapped optimizer
    in their config (same class NAME as the base optimizer, our module
    path); keras can only deserialize it if handed a matching class, so
    every standard optimizer name maps to its wrapped subclass in
    custom_objects — the reference's ``__subclasses__`` sweep."""
    from horovod_tpu.tensorflow import distributed_optimizer_class

    objs = dict(custom_objects or {})
    for name in dir(tf.keras.optimizers):
        cls = getattr(tf.keras.optimizers, name)
        if (isinstance(cls, type)
                and issubclass(cls, tf.keras.optimizers.Optimizer)
                and cls is not tf.keras.optimizers.Optimizer):
            objs.setdefault(name, distributed_optimizer_class(
                cls, compression=compression))
    model = tf.keras.models.load_model(filepath, custom_objects=objs)
    if model.optimizer is not None and not getattr(
            model.optimizer, "_hvd_wrapped", False):
        # saved from an unwrapped optimizer: wrap it now
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model
