"""Elastic-ish state synchronization.

The reference (v0.19) predates Horovod Elastic; its fault-tolerance
primitive is Join (SURVEY.md §5.3) plus the convention that rank 0
checkpoints and broadcasts restored state (§5.4).  This module packages that
convention: a :class:`State` object holding params/optimizer state that can
``sync()`` (broadcast from rank 0 after a restart or membership change),
``save()``/``restore()`` to disk, and ``commit()`` periodically.

On TPU a membership change means a new mesh and recompilation — the driver
of that (re-running ``init()`` with the surviving hosts) lives above this
layer in the launcher; this object guarantees the surviving state is
consistent when training resumes.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu import state as S


class State:
    """Synchronizable training state (params, opt_state, epoch, step...)."""

    def __init__(self, **kwargs: Any) -> None:
        self._keys = sorted(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def sync(self, root_rank: int = 0) -> None:
        """Broadcast every field from ``root_rank`` (restart consistency)."""
        for k in self._keys:
            v = getattr(self, k)
            leaves = jax.tree_util.tree_leaves(v)
            if leaves and all(
                isinstance(l, (jax.Array, np.ndarray, float, int)) for l in leaves
            ):
                setattr(self, k, S.broadcast_parameters(v, root_rank))
            else:
                setattr(self, k, S.broadcast_object(v, root_rank))

    def save(self, path: str) -> None:
        """Rank-0 checkpoint (host pytree pickle; for large models prefer
        orbax — this covers the reference's convention, not a storage
        format)."""
        if basics.rank() == 0:
            tmp = path + ".tmp"
            host = {
                k: jax.tree_util.tree_map(
                    lambda l: np.asarray(l)
                    if isinstance(l, (jax.Array, np.ndarray))
                    else l,
                    getattr(self, k),
                )
                for k in self._keys
            }
            with open(tmp, "wb") as f:
                pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)

    def restore(self, path: str, root_rank: int = 0) -> bool:
        """Rank 0 loads, then broadcast to all.  Returns False if absent."""
        exists = os.path.exists(path) if basics.rank() == 0 else False
        exists = bool(S.broadcast_object(exists, root_rank))
        if not exists:
            return False
        if basics.rank() == 0:
            with open(path, "rb") as f:
                host = pickle.load(f)
        else:
            host = None
        host = S.broadcast_object(host, root_rank)
        for k in self._keys:
            if k in host:
                setattr(self, k, host[k])
        return True

    def commit(self, path: Optional[str] = None) -> None:
        if path is not None:
            self.save(path)


def run(train_fn):
    """Decorator: sync state before the first invocation, mirroring
    ``horovod.elastic.run``'s contract at v0.19 scope (initial broadcast)."""

    def wrapped(state: State, *args, **kwargs):
        state.sync()
        return train_fn(state, *args, **kwargs)

    return wrapped
