"""Adasum: adaptive, scale-insensitive gradient reduction.

Reference: ``horovod/common/ops/adasum/adasum.h`` — the pairwise combination

    a' = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

(coefficient math at ``adasum.h:387-397``) applied over a recursive
distance-doubling hierarchy (``FusedAllreduce``, ``adasum.h:194-338``), and
the hybrid GPU variant (``ops/adasum_gpu_operations.cc``): reduce-scatter
within the node, Adasum across nodes, allgather back.

TPU re-design: the recursion is expressed in-graph with ``lax.ppermute``
partner exchanges over the mesh axis, so XLA schedules the log2(P) rounds on
ICI directly; the hierarchical variant maps reference LOCAL→``local`` axis
(plain psum, ICI) and CROSS→``cross`` axis (Adasum rounds, DCN).  Dot
products accumulate in float32 — the reference does its coefficient math in
host float64 (``adasum.h:355-372``), unavailable in-graph on TPU; float32 is
the documented deviation and the tests bound its error.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu import basics


def _pairwise(a, b, dot, asq, bsq):
    """One Adasum combination with the reference's coefficient formula and
    zero-norm guards (``adasum.h:387-397``)."""
    one = jnp.ones((), jnp.float32)
    acoef = jnp.where(asq > 0, one - dot / (2.0 * asq), one)
    bcoef = jnp.where(bsq > 0, one - dot / (2.0 * bsq), one)
    return (
        acoef.astype(a.dtype) * a + bcoef.astype(b.dtype) * b
    )


def _leaf_dots(a, b):
    a32 = a.astype(jnp.float32).ravel()
    b32 = b.astype(jnp.float32).ravel()
    return jnp.vdot(a32, b32), jnp.vdot(a32, a32), jnp.vdot(b32, b32)


def adasum_allreduce(tree, *, axis_name=None):
    """In-graph Adasum allreduce over the worker axis (or hierarchical over
    ``(cross, local)``: local sum + mean, Adasum across hosts — the
    ``AdasumGpuAllreduce`` structure)."""
    axes = axis_name
    if axes is None:
        axes = (basics.axis_name() if basics.is_initialized() else basics.AXIS,)
    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(axes)
    if len(axes) == 2:
        cross_ax, local_ax = axes
        nloc = lax.axis_size(local_ax)
        tree = jax.tree_util.tree_map(
            lambda t: lax.psum(t, local_ax) / jnp.asarray(nloc, t.dtype), tree
        )
        return _adasum_over_axis(tree, cross_ax)
    if len(axes) != 1:
        raise ValueError("adasum_allreduce takes one axis or (cross, local)")
    return _adasum_over_axis(tree, axes[0])


def _adasum_over_axis(tree, ax: str):
    n = lax.axis_size(ax)
    if n & (n - 1) != 0:
        raise ValueError(
            f"Adasum requires a power-of-two axis size (got {n}); the "
            "reference has the same restriction (adasum_gpu_operations.cc)"
        )
    if n == 1:
        return tree
    idx = lax.axis_index(ax)
    levels = int(np.log2(n))
    for k in range(levels):
        stride = 1 << k
        perm = [(i, i ^ stride) for i in range(n)]

        def _exchange(t):
            return lax.ppermute(t, ax, perm)

        partner_tree = jax.tree_util.tree_map(_exchange, tree)
        # Orientation: the lower rank of the pair is "a".
        is_lower = (idx & stride) == 0

        def _combine(t, p):
            a = jnp.where(is_lower, t, p)
            b = jnp.where(is_lower, p, t)
            dot, asq, bsq = _leaf_dots(a, b)
            return _pairwise(a, b, dot, asq, bsq)

        tree = jax.tree_util.tree_map(_combine, tree, partner_tree)
    return tree


def adasum_reduce_stack(stacked):
    """Serial ground-truth: Adasum-reduce a stacked ``(P, ...)`` array with
    the same pairing order as the distributed recursion.  Used by the eager
    path and as the oracle in tests (role of the reference's
    ``test_adasum_*`` closed-form checks)."""
    x = jnp.asarray(stacked)
    while x.shape[0] > 1:
        a = x[0::2]
        b = x[1::2]
        a32 = a.astype(jnp.float32).reshape(a.shape[0], -1)
        b32 = b.astype(jnp.float32).reshape(b.shape[0], -1)
        dot = jnp.sum(a32 * b32, axis=1)
        asq = jnp.sum(a32 * a32, axis=1)
        bsq = jnp.sum(b32 * b32, axis=1)
        shape = (a.shape[0],) + (1,) * (a.ndim - 1)
        one = jnp.ones_like(dot)
        acoef = jnp.where(asq > 0, one - dot / (2 * asq), one).reshape(shape)
        bcoef = jnp.where(bsq > 0, one - dot / (2 * bsq), one).reshape(shape)
        x = acoef.astype(a.dtype) * a + bcoef.astype(b.dtype) * b
    return x[0]


def vhdd_program(mesh, axis: str):
    """Compiled distributed VHDD over ``axis`` of ``mesh``: each device
    holds one contribution; log2(P) ``ppermute`` partner-exchange rounds
    (the in-graph recursion of :func:`_adasum_over_axis`) produce the
    combined result everywhere.  Per-round traffic is one tensor per link —
    the reference's ``FusedAllreduce`` communication pattern
    (``adasum.h:194-338``) — instead of an O(P) gather."""
    from horovod_tpu import spmd

    spec = jax.sharding.PartitionSpec(axis)

    def fn(block):  # per-shard: (1, ...)
        t = jnp.squeeze(block, 0)
        out = _adasum_over_axis(t, axis)
        return out[None]

    return jax.jit(spmd.shard(fn, in_specs=spec, out_specs=spec, mesh=mesh))


def vhdd_program_group(mesh, axis: str, n: int):
    """Compiled distributed VHDD over a GROUP of ``n`` tensors: the same
    log2(P) ``ppermute`` rounds as :func:`vhdd_program` with all tensors
    exchanged together (shared communication), but coefficient math done
    PER TENSOR — ``_adasum_over_axis`` maps the dot products over the
    pytree leaves.  This is the reference's fused-buffer semantics
    (``adasum.h:194-338`` FusedAllreduce loops per-tensor for the dots
    while the buffer rides the wire as one message)."""
    from horovod_tpu import spmd

    spec = jax.sharding.PartitionSpec(axis)

    def fn(*blocks):  # per-shard: n arrays of (1, ...)
        tree = [jnp.squeeze(b, 0) for b in blocks]
        out = _adasum_over_axis(tree, axis)
        return tuple(o[None] for o in out)

    return jax.jit(spmd.shard(fn, in_specs=(spec,) * n,
                              out_specs=(spec,) * n, mesh=mesh))


@functools.lru_cache(maxsize=1)
def _compiled_eager_vhdd():
    from horovod_tpu.ops import collectives as C

    return vhdd_program(C._process_mesh(), "proc")


@functools.lru_cache(maxsize=64)
def _compiled_eager_vhdd_group(n: int):
    from horovod_tpu.ops import collectives as C

    return vhdd_program_group(C._process_mesh(), "proc", n)


def eager_adasum_group(arrays):
    """Eager Adasum of a FUSED tensor group with per-tensor coefficients.

    Used by the native executor when the controller fused several Adasum
    requests into one response: concatenating and running a single dot
    would change the math (one global coefficient instead of one per
    layer, diverging from reference ``adasum.h`` FusedAllreduce), so the
    group shares the communication rounds while each tensor keeps its own
    pairwise coefficients."""
    from horovod_tpu.ops import collectives as C

    arrays = [np.asarray(a) for a in arrays]
    P = basics.cross_size()
    if P == 1:
        return [a.copy() for a in arrays]
    if P & (P - 1) == 0:
        outs = _compiled_eager_vhdd_group(len(arrays))(
            *[C._to_global(a) for a in arrays])
        return [C._local_shard_to_host(o)[0] for o in outs]
    # Non-power-of-2 fallback: gather + serial oracle per tensor.
    return [
        np.asarray(adasum_reduce_stack(C._replicated_to_host(
            C._compiled_identity_replicated()(C._to_global(a)))))
        for a in arrays
    ]


def eager_adasum(x: np.ndarray) -> np.ndarray:
    """Eager (host/process-level) Adasum across processes.

    Power-of-two process counts run the distributed log2(P)-round VHDD
    program; other counts fall back to gather + the serial oracle (the
    reference has the same power-of-2 restriction on its hierarchy,
    ``adasum_mpi.cc:52-67``, and errors instead of falling back)."""
    from horovod_tpu.ops import collectives as C

    P = basics.cross_size()
    if P == 1:
        return np.asarray(x).copy()
    if P & (P - 1) == 0:
        out = C._local_shard_to_host(
            _compiled_eager_vhdd()(C._to_global(np.asarray(x)))
        )
        return out[0]
    stacked = C._replicated_to_host(
        C._compiled_identity_replicated()(C._to_global(np.asarray(x)))
    )
    return np.asarray(adasum_reduce_stack(stacked))
