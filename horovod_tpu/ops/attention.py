"""Attention kernels: Pallas flash attention + ring attention (sequence
parallelism over the mesh).

The reference framework has no attention ops at all — sequence length is
invisible to it (SURVEY.md §5.7: tensors are opaque byte buffers, and the
op set is allreduce/allgather/broadcast/join).  These are the TPU-native
extensions the rebuild is required to treat as first-class: long-context
attention as a fused-VMEM Pallas kernel, and context parallelism as
``lax.ppermute`` rotations of K/V shards over the ICI ring — the
collective pattern the reference could only have expressed as NCCL
point-to-points.

Layout convention: ``(batch, heads, seq, head_dim)`` f32/bf16.

* :func:`flash_attention` — online-softmax tiled attention, one Pallas
  kernel; O(block) VMEM, saves the logsumexp for the backward.  The
  backward is a pair of fused Pallas kernels (dk/dv with Q innermost,
  dq with K innermost) computing the analytic flash gradients from the
  saved LSE — no (S, block) score materialization in HBM; untileable
  shapes fall back to the same math expressed blockwise in XLA.
  :func:`flash_attention_with_lse` additionally exposes the LSE as a
  differentiable output (dlse folds in as ``delta -= dlse``).
* :func:`flash_attention_shifted` — the same kernels with the mask as a
  RUNTIME scalar: allowed iff ``col + shift <= row``, ``shift`` an int32
  operand staged into SMEM.  ``shift = 0`` is ordinary causal,
  ``shift <= -T`` is unmasked, ``shift >= S`` masks everything (the
  kernel then yields o=0, lse=-inf, which vanishes in a logsumexp
  merge).  This is what lets ring attention call ONE kernel per chunk
  instead of dispatching through ``lax.switch`` (whose pallas-in-switch-
  in-scan nesting trips a jax lowering-cache bug, see ``ring_attention``).
* :func:`ring_attention` — each device holds a contiguous sequence shard;
  K/V shards rotate around the ring with ``lax.ppermute`` while the local
  Q accumulates partial attention, merged by logsumexp weighting.  Each
  chunk runs the Pallas flash kernel with ``shift = (src - me) * S_kv``:
  earlier shards come out fully attended, the diagonal shard causally,
  later shards fully masked — one code path, no per-kind dispatch.
* :func:`ulysses_attention` — the all-to-all flavor of sequence
  parallelism (DeepSpeed-Ulysses pattern): one ``lax.all_to_all``
  reshards from sequence-sharded to head-sharded, every device computes
  FULL-sequence attention for its head subset (so the flash kernel and
  plain causal masking apply unchanged), and a second all-to-all reshards
  back.  Two collectives per attention instead of P ppermute rounds —
  cheaper when heads divide evenly over the axis and the ICI all-to-all
  bandwidth is good; ring wins when S_local is huge and overlap matters.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30  # finite mask value: exp(NEG_INF - anything_real) == 0


def _sm_scale(q, sm_scale):
    return 1.0 / np.sqrt(q.shape[-1]) if sm_scale is None else sm_scale


def expand_kv(kv, n_heads: int):
    """Grouped-query attention: repeat K/V heads up to ``n_heads``.

    The kernels are MHA; GQA expands at the call site with
    ``jnp.repeat`` — whose VJP is exactly the per-group sum, so
    gradients w.r.t. the shared KV heads are exact under autodiff.  The
    bandwidth win is preserved where it matters: ring attention rotates
    the UNEXPANDED (B, H_kv, S, D) shards around the ICI ring and
    expands per chunk, so ppermute traffic shrinks by H/H_kv."""
    H_kv = kv.shape[1]
    if H_kv == n_heads:
        return kv
    if n_heads % H_kv != 0:
        raise ValueError(
            f"n_heads ({n_heads}) must be a multiple of kv heads ({H_kv})")
    return jnp.repeat(kv, n_heads // H_kv, axis=1)


def _float0_like(x):
    """Cotangent for an integer-dtype primal (custom_vjp convention)."""
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


# --- reference (oracle) -------------------------------------------------------


def _reference_attention_lse(q, k, v, shift, scale):
    """One O(S^2) score computation -> (output, logsumexp).

    ``shift``: None for unmasked, else a (traced or static) int scalar —
    position (row, col) is attended iff ``col + shift <= row``.  shift=0
    is standard causal."""
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if shift is not None:
        S, T = scores.shape[-2], scores.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (S, T), 0)
        cols = lax.broadcasted_iota(jnp.int32, (S, T), 1)
        scores = jnp.where(cols + shift <= rows, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # fully-masked rows: stay finite
    p = jnp.where(scores > NEG_INF * 0.5, jnp.exp(scores - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    o = jnp.einsum("bhst,bhtd->bhsd", (p / l_safe).astype(v.dtype), v)
    lse = jnp.where(l[..., 0] > 0, m[..., 0] + jnp.log(l_safe[..., 0]),
                    NEG_INF)
    return o, lse


def reference_attention(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None):
    """O(S^2)-memory oracle used by tests and as the small-shape fallback."""
    o, _ = _reference_attention_lse(q, k, v, 0 if causal else None,
                                    _sm_scale(q, sm_scale))
    return o


# --- Pallas forward kernel ----------------------------------------------------


def _flash_fwd_kernel(shift_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref,
                      *, block_q: int, block_k: int, masked: bool,
                      scale: float, num_k: int):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks); K innermost, so the
    (acc, m, l) scratch carries the online softmax across K steps.

    ``shift_ref`` is a (1,) int32 in SMEM: position (row, col) attends iff
    ``col + shift <= row`` (only read when ``masked``)."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # K blocks entirely above the shifted diagonal contribute nothing.
    run = True
    if masked:
        run = ik * block_k + shift_ref[0] <= iq * block_q + block_q - 1

    @pl.when(run)
    def _step():
        # Keep inputs in their native dtype (bf16 rides the MXU at full
        # rate) and accumulate in f32 via preferred_element_type.
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if masked:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols + shift_ref[0] <= rows, s, NEG_INF)
        m_prev = m_ref[:, :1]                               # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        if masked:
            # Rows fully masked so far have m_new == NEG_INF; exp(s-m_new)
            # would be exp(0)=1 garbage — zero those lanes explicitly.
            p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - m_new), 0.0)
        else:
            p = jnp.exp(s - m_new)                          # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)                     # (block_q, 1)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        # P·V in the value dtype (bf16 MXU) with f32 accumulation; exact
        # for f32 inputs, standard flash practice for bf16.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # LSE layout (BH, 8, S): 8 replicated sublanes satisfy the TPU
        # (÷8, ÷128) tile constraint; caller reads sublane 0.  Fully
        # masked rows (l == 0) report -inf so they vanish in merges.
        lse = jnp.where(l[:, 0] > 0, m_ref[:, 0] + jnp.log(l_safe[:, 0]),
                        NEG_INF)  # (block_q,)
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


# Shared Pallas plumbing (ops/_pallas_util.py): the guarded import,
# interpreter fallback, vma-inheriting out shapes, and the SMEM scalar
# spec are shared with the fused paged-attention decode kernel
# (ops/paged_attention.py) so the conventions cannot fork.
from horovod_tpu.ops._pallas_util import (  # noqa: E402
    PALLAS_AVAILABLE as _PALLAS,
    out_sds as _out_sds,
    pl,
    pltpu,
    scalar_operand as _shift_operand,
    smem_spec as _smem_spec,
    use_interpret as _use_interpret,
)


def _flash_fwd(q, k, v, shift, sm_scale, block_q: int, block_k: int):
    """shift: None (no mask) or int scalar (traced ok) — shifted causal."""
    B, H, S, D = q.shape
    T = k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    scale = _sm_scale(q, sm_scale)
    if (not _PALLAS or S % block_q or T % block_k
            or D % 8):  # fall back for shapes the kernel can't tile
        return _reference_attention_lse(q, k, v, shift, scale)
    nq, nk = S // block_q, T // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        masked=shift is not None, scale=scale, num_k=nk)
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            _out_sds((B * H, S, D), q.dtype, q),
            _out_sds((B * H, 8, S), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(_shift_operand(shift, q), qr, kr, vr)
    return o.reshape(B, H, S, D), lse[:, 0, :].reshape(B, H, S)


def _flash_bwd_dkdv_kernel(shift_ref, q_ref, do_ref, lse_ref, delta_ref,
                           k_ref, v_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                           *, block_q: int, block_k: int, masked: bool,
                           scale: float, num_q: int):
    """Grid: (BH, num_k_blocks, num_q_blocks); Q innermost so the dk/dv
    scratch accumulates across Q steps for one K block."""
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if masked:  # Q blocks entirely above the shifted diagonal: nothing
        run = iq * block_q + block_q - 1 >= ik * block_k + shift_ref[0]

    @pl.when(run)
    def _step():
        q = q_ref[0]                      # (block_q, d) native dtype
        do = do_ref[0]                    # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        lse = lse_ref[0, 0, :]            # (block_q,) f32
        delta = delta_ref[0, 0, :]        # (block_q,) f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if masked:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols + shift_ref[0] <= rows, s, NEG_INF)
            # exp(NEG_INF - NEG_INF) == 1 for rows whose lse is -inf
            # (fully masked): their cotangents are exactly zero, but keep
            # p finite-clean anyway.
            p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - lse[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])  # (block_q, block_k) f32
        # dv_j += p^T do_i
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        # dk_j += ds^T q_i
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(shift_ref, q_ref, do_ref, lse_ref, delta_ref,
                         k_ref, v_ref, dq_ref, dq_acc,
                         *, block_q: int, block_k: int, masked: bool,
                         scale: float, num_k: int):
    """Grid: (BH, num_q_blocks, num_k_blocks); K innermost, dq scratch
    accumulates across K steps for one Q block."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = True
    if masked:
        run = ik * block_k + shift_ref[0] <= iq * block_q + block_q - 1

    @pl.when(run)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if masked:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols + shift_ref[0] <= rows, s, NEG_INF)
            p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - lse[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(shift, scale, block_q, block_k, q, k, v, o, lse, do,
                      dlse=None):
    """Fused Pallas backward: two tiled kernels (dk/dv then dq), O(block)
    VMEM, no (S, block_k) f32 materialization in HBM.

    ``dlse``: optional cotangent of the LSE output (when the caller
    differentiates through the logsumexp too, e.g. ring attention's
    merge).  ∂lse_i/∂s_ij = p_ij, so it folds into the kernels as
    ``delta_i -= dlse_i`` — the same place the o-path's rowsum(do·o)
    enters."""
    B, H, S, D = q.shape
    T = k.shape[2]
    nq, nk = S // block_q, T // block_k
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    dor = do.reshape(B * H, S, D).astype(q.dtype)
    # delta_i = rowsum(do * o); same (BH, 8, S) sublane-replicated layout
    # as the forward's LSE output.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(
        delta.reshape(B * H, 1, S), (B * H, 8, S)).astype(jnp.float32)
    lse_t = jnp.broadcast_to(
        lse.reshape(B * H, 1, S), (B * H, 8, S)).astype(jnp.float32)

    q_spec_by_q = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    q_spec_by_k = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    k_spec_by_q = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    k_spec_by_k = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    row_by_q = pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i))
    row_by_k = pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i))

    masked = shift is not None
    sh = _shift_operand(shift, q)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, block_q=block_q,
                          block_k=block_k, masked=masked, scale=scale,
                          num_q=nq),
        grid=(B * H, nk, nq),
        in_specs=[_smem_spec(), q_spec_by_k, q_spec_by_k, row_by_k, row_by_k,
                  k_spec_by_k, k_spec_by_k],
        out_specs=[k_spec_by_k, k_spec_by_k],
        out_shape=[_out_sds((B * H, T, D), k.dtype, q),
                   _out_sds((B * H, T, D), v.dtype, q)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=_use_interpret(),
    )(sh, qr, dor, lse_t, delta, kr, vr)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, masked=masked, scale=scale,
                          num_k=nk),
        grid=(B * H, nq, nk),
        in_specs=[_smem_spec(), q_spec_by_q, q_spec_by_q, row_by_q, row_by_q,
                  k_spec_by_q, k_spec_by_q],
        out_specs=q_spec_by_q,
        out_shape=_out_sds((B * H, S, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_use_interpret(),
    )(sh, qr, dor, lse_t, delta, kr, vr)

    return (dq.reshape(B, H, S, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _flash_bwd(shift, sm_scale, block_q, block_k, res, do, dlse=None):
    """Flash backward from the saved LSE.

    Tileable shapes run the fused Pallas kernels (above): O(block) VMEM,
    no (S, block) f32 score materialization in HBM.  Untileable shapes
    fall back to the analytic XLA form scanned over K blocks:

        p_ij = exp(q_i k_j^T * scale - lse_i)
        dv_j = p^T do ;  dp = do v^T ;  ds = p * (dp - rowsum(do * o))
        dq_i += ds k_j * scale ;  dk_j = ds^T q_i * scale

    ``dlse`` (cotangent of the LSE output) folds in as delta -= dlse.
    ``shift``: None for unmasked, else the shifted-causal int scalar.
    """
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    T = k.shape[2]
    scale = _sm_scale(q, sm_scale)
    bq = min(block_q, S)
    bk = min(block_k, T)
    if _PALLAS and S % bq == 0 and T % bk == 0 and D % 8 == 0:
        return _flash_bwd_pallas(shift, scale, bq, bk, q, k, v, o, lse, do,
                                 dlse=dlse)
    if T % bk:  # analytic fallback: widen to one K block
        bk = T
    nk = T // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (B,H,S)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    rows = lax.broadcasted_iota(jnp.int32, (S, bk), 0)

    def kblock(carry, jb):
        dq = carry
        ks = lax.dynamic_slice_in_dim(k, jb * bk, bk, axis=2).astype(jnp.float32)
        vs = lax.dynamic_slice_in_dim(v, jb * bk, bk, axis=2).astype(jnp.float32)
        s = jnp.einsum("bhsd,bhtd->bhst", qf, ks) * scale  # (B,H,S,bk)
        if shift is not None:
            cols = jb * bk + lax.broadcasted_iota(jnp.int32, (S, bk), 1)
            s = jnp.where(cols + shift <= rows, s, NEG_INF)
            p = jnp.where(s > NEG_INF * 0.5,
                          jnp.exp(s - lse[..., None]), 0.0)
        else:
            p = jnp.exp(s - lse[..., None])                 # (B,H,S,bk)
        dv = jnp.einsum("bhst,bhsd->bhtd", p, dof)
        dp = jnp.einsum("bhsd,bhtd->bhst", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhst,bhtd->bhsd", ds, ks)
        dk = jnp.einsum("bhst,bhsd->bhtd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = lax.scan(kblock, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, T, D)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, T, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 512):
    """Fused tiled attention.  ``(B, H, S, D) x (B, H, T, D) -> (B, H, S, D)``.

    Forward runs as one Pallas TPU kernel (online softmax, O(block) VMEM);
    on CPU it runs the same kernel under the Pallas interpreter.  Shapes
    that can't tile (S % block, D % 8) silently use the XLA reference.
    """
    o, _ = _flash_fwd(q, k, v, 0 if causal else None, sm_scale,
                      block_q, block_k)
    return o


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, 0 if causal else None, sm_scale,
                        block_q, block_k)
    return o, (q, k, v, o, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, do):
    return _flash_bwd(0 if causal else None, sm_scale, block_q, block_k,
                      res, do)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             sm_scale: Optional[float] = None,
                             block_q: int = 1024, block_k: int = 512):
    """:func:`flash_attention` that also returns the per-row logsumexp as
    a DIFFERENTIABLE output ``(o, lse)`` — the building block for merge-
    based compositions (ring attention) whose gradients flow through the
    lse weights; the backward folds the lse cotangent in as
    ``delta -= dlse``."""
    return _flash_fwd(q, k, v, 0 if causal else None, sm_scale,
                      block_q, block_k)


def _fal_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, 0 if causal else None, sm_scale,
                        block_q, block_k)
    return (o, lse), (q, k, v, o, lse)


def _fal_bwd(causal, sm_scale, block_q, block_k, res, ct):
    do, dlse = ct
    return _flash_bwd(0 if causal else None, sm_scale, block_q, block_k,
                      res, do, dlse=dlse)


flash_attention_with_lse.defvjp(_fal_fwd, _fal_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention_shifted(q, k, v, shift,
                            sm_scale: Optional[float] = None,
                            block_q: int = 1024, block_k: int = 512):
    """Flash attention with a RUNTIME shifted-causal mask -> ``(o, lse)``.

    ``shift`` is an int32 scalar (traced values welcome): position
    (row, col) attends iff ``col + shift <= row``.  shift=0 is ordinary
    causal; shift <= -T allows everything; shift >= S masks everything
    and yields o=0, lse=NEG_INF (a no-op under logsumexp merging).  The
    scalar rides to the kernel through SMEM, so ONE compiled kernel
    serves every chunk kind of ring attention — full, diagonal, and dead
    — with no ``lax.switch`` wrapper (pallas-in-switch-in-scan trips a
    jax lowering-cache bug; a data-dependent mask sidesteps it).  Both
    outputs are differentiable; dlse folds in as ``delta -= dlse``.
    """
    return _flash_fwd(q, k, v, shift, sm_scale, block_q, block_k)


def _fas_fwd(q, k, v, shift, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, shift, sm_scale, block_q, block_k)
    return (o, lse), (q, k, v, o, lse, shift)


def _fas_bwd(sm_scale, block_q, block_k, res, ct):
    q, k, v, o, lse, shift = res
    do, dlse = ct
    dq, dk, dv = _flash_bwd(shift, sm_scale, block_q, block_k,
                            (q, k, v, o, lse), do, dlse=dlse)
    return dq, dk, dv, _float0_like(shift)


flash_attention_shifted.defvjp(_fas_fwd, _fas_bwd)


# --- chunk attention with LSE (building block for ring) -----------------------


def _chunk_attn(q, k, v, mask, scale):
    """Attention of local q over one K/V chunk with an additive bool mask
    (True = allowed); returns per-chunk normalized output + LSE."""
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # fully-masked rows stay at NEG_INF
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    o = jnp.einsum("bhst,bhtd->bhsd", p / l_safe, v.astype(jnp.float32))
    lse = (m + jnp.log(l_safe))[..., 0]  # (B,H,S)
    return o, lse


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   impl: str = "flash",
                   block_q: int = 1024, block_k: int = 512):
    """Sequence-parallel attention inside ``shard_map``: every device holds
    a contiguous sequence shard of q/k/v ``(B, H, S_local, D)``; K/V rotate
    around the mesh-axis ring via ``lax.ppermute`` (ICI neighbor exchange)
    while partial attention accumulates with logsumexp merging.

    Each chunk is computed by the Pallas flash kernel
    (:func:`flash_attention_shifted`) with ``shift = (src - me) * S_kv``:
    the globally-causal mask restricted to the (me, src) shard pair IS a
    shifted-causal mask, so earlier shards come out fully attended, the
    diagonal shard causally, and later shards fully masked (o=0,
    lse=-inf, which the merge annihilates) — one kernel call per step,
    no ``lax.switch`` chunk dispatch (whose pallas-in-switch-in-scan
    nesting trips a jax lowering-cache bug, the r2 blocker).  Dead-chunk
    blocks are still skipped inside the kernel: the ``pl.when`` grid
    predicate compares against the runtime shift.

    ``impl="reference"`` keeps the masked-XLA chunk path (used by tests
    as a second oracle and by shapes that can't tile — though the flash
    path falls back internally too).  Differentiable end-to-end; the VJP
    rides the transposed ``ppermute``s back around the ring.

    GQA: pass k/v with ``H_kv < H`` heads (``H % H_kv == 0``) — the ring
    rotates the small shards (ICI traffic ÷ H/H_kv) and each chunk
    expands to full heads before the kernel.
    """
    P = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    scale = _sm_scale(q, sm_scale)
    B, H, S, D = q.shape
    T = k.shape[2]
    perm = [(i, (i + 1) % P) for i in range(P)]
    use_flash = impl == "flash"

    def step(carry, s_idx):
        o, lse, ks_kv, vs_kv = carry
        src = (me - s_idx) % P  # which shard's K/V we hold this step
        last = s_idx == P - 1
        # GQA: the carry rotates the small (B, H_kv, T, D) shards; the
        # chunk compute expands to full heads (jnp.repeat — VJP is the
        # group-sum, so the transposed ring carries exact KV grads).
        ks = expand_kv(ks_kv, H)
        vs = expand_kv(vs_kv, H)
        if use_flash:
            if causal:
                shift = ((src - me) * T).astype(jnp.int32)
                o_c, lse_c = flash_attention_shifted(
                    q, ks, vs, shift, scale, block_q, block_k)
            else:
                o_c, lse_c = flash_attention_with_lse(
                    q, ks, vs, False, scale, block_q, block_k)
            o_c = o_c.astype(jnp.float32)
            lse_c = lse_c.astype(jnp.float32)
        elif causal:
            shift = (src - me) * T
            rows = lax.broadcasted_iota(jnp.int32, (S, T), 0)
            cols = lax.broadcasted_iota(jnp.int32, (S, T), 1)
            o_c, lse_c = _chunk_attn(
                q, ks, vs, (cols + shift <= rows)[None, None], scale)
        else:
            o_c, lse_c = _chunk_attn(q, ks, vs, None, scale)
        lse_new = jnp.logaddexp(lse, lse_c)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_c * jnp.exp(lse_c - lse_new)[..., None])
        if not last:  # the final rotation's result is never read
            ks_kv = lax.ppermute(ks_kv, axis_name, perm)
            vs_kv = lax.ppermute(vs_kv, axis_name, perm)
        return o, lse_new, ks_kv, vs_kv

    # Derive the initial carry from q so it inherits q's varying-over-axis
    # type under shard_map (a plain literal would mismatch the carry-out).
    o0 = jnp.zeros_like(q, jnp.float32) * 0.0
    lse0 = q[..., 0].astype(jnp.float32) * 0.0 + NEG_INF
    # The ring loop is UNROLLED (P is the static mesh-axis size): each
    # step is one kernel call + a ppermute, so XLA can overlap step i's
    # neighbor exchange with step i-1's compute — a lax.scan would
    # serialize them behind the carry.  Unrolling also keeps the Pallas
    # call out of scan-in-scan nesting, which the interpret-mode
    # lowering used on CPU can't cache correctly (KeyError: closed_call).
    carry = (o0, lse0, k, v)
    for s_idx in range(P):
        carry = step(carry, s_idx)
    o = carry[0]
    return o.astype(q.dtype)


def zigzag_perm(S: int, P: int):
    """Column permutation mapping a CONTIGUOUS global sequence to the
    zigzag layout: device i holds global chunks ``(i, 2P-1-i)`` of size
    ``S/(2P)`` — pairing an early and a late chunk so every device owns
    the same amount of causal work.  Returns (perm, inv): permute data
    columns by ``perm`` before sharding contiguously over the axis;
    ``inv`` restores original order."""
    if S % (2 * P):
        raise ValueError(f"sequence {S} must divide into 2*{P} chunks")
    Sc = S // (2 * P)
    idx = np.arange(S).reshape(2 * P, Sc)
    perm = np.concatenate(
        [np.concatenate([idx[i], idx[2 * P - 1 - i]]) for i in range(P)])
    inv = np.argsort(perm)
    return perm, inv


def zigzag_positions(S_local: int, axis_name: str):
    """Global position ids for this device's zigzag rows (feed to RoPE):
    ``[me*Sc + 0..Sc-1, (2P-1-me)*Sc + 0..Sc-1]``."""
    P = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    Sc = S_local // 2
    ar = jnp.arange(Sc, dtype=jnp.float32)
    return jnp.concatenate([me * Sc + ar, (2 * P - 1 - me) * Sc + ar])


def zigzag_ring_attention(q, k, v, *, axis_name: str,
                          sm_scale: Optional[float] = None,
                          impl: str = "flash",
                          block_q: int = 1024, block_k: int = 512):
    """CAUSAL ring attention with the ZIGZAG chunk layout — the causal
    load-balance fix for sequence parallelism.

    Plain ring + causal is imbalanced: device i's rows attend i+1 of the
    P shard-pairs, so early devices idle while the last device computes
    every step — the lockstep ring pays the max every rotation.  Zigzag
    pairs chunk ``i`` with chunk ``2P-1-i`` on device i (q/k/v rows in
    zigzag layout — :func:`zigzag_perm`; RoPE positions from
    :func:`zigzag_positions`), which makes the alive work EXACTLY half
    the block pairs on every device at every step:

      step with kv from src = chunks (src, 2P-1-src); my q = (me, 2P-1-me)
        q_early × k_early : alive iff src <= me   (shift-causal kernel)
        q_early × k_late  : ALWAYS dead           (never issued)
        q_late  × k_early : always fully alive
        q_late  × k_late  : alive iff src >= me   (shift-causal kernel)

    Exactly 2 of 4 quarter-blocks compute per device per step — ~2×
    the causal ring's steady-state throughput at large P.  Dead blocks
    in the two conditional calls are skipped inside the shifted flash
    kernel (the ``pl.when`` grid predicate against the runtime shift).
    ``impl="reference"`` uses one masked-XLA chunk attention over the
    exact global-position causal mask (the oracle).  Differentiable
    end-to-end (the VJP rides the transposed ppermutes); GQA supported
    like :func:`ring_attention`.
    """
    P = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    scale = _sm_scale(q, sm_scale)
    B, H, S, D = q.shape
    if S % 2:
        raise ValueError("zigzag shard length must be even (two chunks)")
    Sc = S // 2
    perm = [(i, (i + 1) % P) for i in range(P)]
    use_flash = impl == "flash"

    qa, qb = q[:, :, :Sc], q[:, :, Sc:]

    # Global chunk ids of my q rows.
    my_a = me           # early chunk
    my_b = 2 * P - 1 - me  # late chunk

    def merge(o, lse, o_c, lse_c):
        lse_new = jnp.logaddexp(lse, lse_c)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_c * jnp.exp(lse_c - lse_new)[..., None])
        return o, lse_new

    def block(qx, my_chunk, ks, vs, src_chunk):
        """(o, lse) of one q-half over one kv-half chunk, with the
        global-causal relation expressed as a shifted-causal mask."""
        if use_flash:
            shift = ((src_chunk - my_chunk) * Sc).astype(jnp.int32)
            o_c, lse_c = flash_attention_shifted(
                qx, ks, vs, shift, scale, block_q, block_k)
            return o_c.astype(jnp.float32), lse_c.astype(jnp.float32)
        shift = (src_chunk - my_chunk) * Sc
        rows = lax.broadcasted_iota(jnp.int32, (Sc, Sc), 0)
        cols = lax.broadcasted_iota(jnp.int32, (Sc, Sc), 1)
        return _chunk_attn(qx, ks, vs,
                           (cols + shift <= rows)[None, None], scale)

    def step(carry, s_idx):
        oa, lsea, ob, lseb, ks_kv, vs_kv = carry
        src = (me - s_idx) % P
        last = s_idx == P - 1
        ks = expand_kv(ks_kv, H)
        vs = expand_kv(vs_kv, H)
        ka, va = ks[:, :, :Sc], vs[:, :, :Sc]   # src's early chunk
        kb, vb = ks[:, :, Sc:], vs[:, :, Sc:]   # src's late chunk
        src_a = src
        src_b = 2 * P - 1 - src
        # q_early x k_early (alive iff src <= me; dead blocks kernel-skip)
        o_c, l_c = block(qa, my_a, ka, va, src_a)
        oa, lsea = merge(oa, lsea, o_c, l_c)
        # q_late x k_early (always fully alive)
        o_c, l_c = block(qb, my_b, ka, va, src_a)
        ob, lseb = merge(ob, lseb, o_c, l_c)
        # q_late x k_late (alive iff src >= me)
        o_c, l_c = block(qb, my_b, kb, vb, src_b)
        ob, lseb = merge(ob, lseb, o_c, l_c)
        # q_early x k_late: provably dead for every (me, src) — not issued.
        if not last:
            ks_kv = lax.ppermute(ks_kv, axis_name, perm)
            vs_kv = lax.ppermute(vs_kv, axis_name, perm)
        return oa, lsea, ob, lseb, ks_kv, vs_kv

    def zeros_like_half(qx):
        o0 = jnp.zeros_like(qx, jnp.float32) * 0.0
        lse0 = qx[..., 0].astype(jnp.float32) * 0.0 + NEG_INF
        return o0, lse0

    oa, lsea = zeros_like_half(qa)
    ob, lseb = zeros_like_half(qb)
    carry = (oa, lsea, ob, lseb, k, v)
    for s_idx in range(P):  # unrolled like ring_attention (see note there)
        carry = step(carry, s_idx)
    out = jnp.concatenate([carry[0], carry[2]], axis=2)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      impl: str = "flash"):
    """All-to-all sequence parallelism inside ``shard_map`` (the
    DeepSpeed-Ulysses pattern; SURVEY.md §5.7 lists it as the alltoall
    resharding flavor of context parallelism).

    Every device holds a sequence shard ``(B, H, S_local, D)``.  One
    ``lax.all_to_all`` redistributes to ``(B, H/P, S_global, D)`` — full
    sequence, head subset — so local attention (including the Pallas
    flash kernel via the default ``impl="flash"``, and ordinary causal
    masking) runs unchanged; the inverse all_to_all restores sequence
    sharding.  Requires ``H %% axis_size == 0``.  Differentiable
    end-to-end: the VJP of ``all_to_all`` is the transposed all_to_all.
    """
    P = lax.axis_size(axis_name)
    B, H, S, D = q.shape
    if H % P != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the "
            f"'{axis_name}' axis size ({P}); use ring_attention otherwise")

    def seq_to_heads(x):  # (B,h,S_local,D) -> (B,h/P,S_global,D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    # GQA: reshard K/V at their small head count when it still divides
    # the axis (all_to_all moves H_kv/P heads per link), expanding to
    # full heads only after the reshard; otherwise expand first.
    if k.shape[1] % P == 0:
        kh = expand_kv(seq_to_heads(k), H // P)
        vh = expand_kv(seq_to_heads(v), H // P)
    else:
        kh = seq_to_heads(expand_kv(k, H))
        vh = seq_to_heads(expand_kv(v, H))
    qh = seq_to_heads(q)
    if impl == "flash":
        oh = flash_attention(qh, kh, vh, causal, sm_scale=sm_scale)
    else:
        oh = reference_attention(qh, kh, vh, causal=causal,
                                 sm_scale=sm_scale)
    # (B,H/P,S_global,D) -> (B,H,S_local,D)
    return lax.all_to_all(oh, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
