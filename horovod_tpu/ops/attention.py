"""Attention kernels: Pallas flash attention + ring attention (sequence
parallelism over the mesh).

The reference framework has no attention ops at all — sequence length is
invisible to it (SURVEY.md §5.7: tensors are opaque byte buffers, and the
op set is allreduce/allgather/broadcast/join).  These are the TPU-native
extensions the rebuild is required to treat as first-class: long-context
attention as a fused-VMEM Pallas kernel, and context parallelism as
``lax.ppermute`` rotations of K/V shards over the ICI ring — the
collective pattern the reference could only have expressed as NCCL
point-to-points.

Layout convention: ``(batch, heads, seq, head_dim)`` f32/bf16.

* :func:`flash_attention` — online-softmax tiled attention, one Pallas
  kernel; O(block) VMEM, saves the logsumexp for the backward.  The
  backward is a pair of fused Pallas kernels (dk/dv with Q innermost,
  dq with K innermost) computing the analytic flash gradients from the
  saved LSE — no (S, block) score materialization in HBM; untileable
  shapes fall back to the same math expressed blockwise in XLA.
  :func:`flash_attention_with_lse` additionally exposes the LSE as a
  differentiable output (dlse folds in as ``delta -= dlse``).
* :func:`ring_attention` — each device holds a contiguous sequence shard;
  K/V shards rotate around the ring with ``lax.ppermute`` while the local
  Q accumulates partial attention, merged by logsumexp weighting.  Causal
  masking degrades gracefully: a fully-masked chunk contributes weight
  exp(-1e30 - lse) == 0.
* :func:`ulysses_attention` — the all-to-all flavor of sequence
  parallelism (DeepSpeed-Ulysses pattern): one ``lax.all_to_all``
  reshards from sequence-sharded to head-sharded, every device computes
  FULL-sequence attention for its head subset (so the flash kernel and
  plain causal masking apply unchanged), and a second all-to-all reshards
  back.  Two collectives per attention instead of P ppermute rounds —
  cheaper when heads divide evenly over the axis and the ICI all-to-all
  bandwidth is good; ring wins when S_local is huge and overlap matters.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30  # finite mask value: exp(NEG_INF - anything_real) == 0


def _sm_scale(q, sm_scale):
    return 1.0 / np.sqrt(q.shape[-1]) if sm_scale is None else sm_scale


# --- reference (oracle) -------------------------------------------------------


def _reference_attention_lse(q, k, v, causal, scale):
    """One O(S^2) score computation -> (output, logsumexp)."""
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        S, T = scores.shape[-2], scores.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (S, T), 0)
        cols = lax.broadcasted_iota(jnp.int32, (S, T), 1)
        scores = jnp.where(cols <= rows, scores, NEG_INF)
    lse = jax.nn.logsumexp(scores, axis=-1)
    w = jnp.exp(scores - lse[..., None])
    o = jnp.einsum("bhst,bhtd->bhsd", w.astype(v.dtype), v)
    return o, lse


def reference_attention(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None):
    """O(S^2)-memory oracle used by tests and as the small-shape fallback."""
    o, _ = _reference_attention_lse(q, k, v, causal, _sm_scale(q, sm_scale))
    return o


# --- Pallas forward kernel ----------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref,
                      *, block_q: int, block_k: int, causal: bool,
                      scale: float, num_k: int):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks); K innermost, so the
    (acc, m, l) scratch carries the online softmax across K steps."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: K blocks strictly above the diagonal contribute nothing.
    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _step():
        # Keep inputs in their native dtype (bf16 rides the MXU at full
        # rate) and accumulate in f32 via preferred_element_type.
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        m_prev = m_ref[:, :1]                               # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)          # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                              # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)                     # (block_q, 1)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        # P·V in the value dtype (bf16 MXU) with f32 accumulation; exact
        # for f32 inputs, standard flash practice for bf16.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # LSE layout (BH, 8, S): 8 replicated sublanes satisfy the TPU
        # (÷8, ÷128) tile constraint; caller reads sublane 0.
        lse = m_ref[:, 0] + jnp.log(l_safe[:, 0])  # (block_q,)
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


try:  # pallas is TPU/GPU-oriented; keep import failure non-fatal on CPU
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _PALLAS = True
except Exception:  # pragma: no cover
    _PALLAS = False


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _out_sds(shape, dtype, like):
    """ShapeDtypeStruct that inherits ``like``'s varying-over-mesh-axes
    type, so the pallas_call type-checks inside ``shard_map`` (ring
    attention runs the kernel per sequence shard)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_fwd(q, k, v, causal: bool, sm_scale, block_q: int, block_k: int):
    B, H, S, D = q.shape
    T = k.shape[2]
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    scale = _sm_scale(q, sm_scale)
    if (not _PALLAS or S % block_q or T % block_k
            or D % 8):  # fall back for shapes the kernel can't tile
        return _reference_attention_lse(q, k, v, causal, scale)
    nq, nk = S // block_q, T // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        causal=causal, scale=scale, num_k=nk)
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            _out_sds((B * H, S, D), q.dtype, q),
            _out_sds((B * H, 8, S), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qr, kr, vr)
    return o.reshape(B, H, S, D), lse[:, 0, :].reshape(B, H, S)


def _flash_bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc,
                           *, block_q: int, block_k: int, causal: bool,
                           scale: float, num_q: int):
    """Grid: (BH, num_k_blocks, num_q_blocks); Q innermost so the dk/dv
    scratch accumulates across Q steps for one K block."""
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if causal:  # Q blocks strictly above the diagonal contribute nothing
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0]                      # (block_q, d) native dtype
        do = do_ref[0]                    # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        lse = lse_ref[0, 0, :]            # (block_q,) f32
        delta = delta_ref[0, 0, :]        # (block_q,) f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])     # (block_q, block_k) f32
        # dv_j += p^T do_i
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        # dk_j += ds^T q_i
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                         dq_ref, dq_acc,
                         *, block_q: int, block_k: int, causal: bool,
                         scale: float, num_k: int):
    """Grid: (BH, num_q_blocks, num_k_blocks); K innermost, dq scratch
    accumulates across K steps for one Q block."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(causal, scale, block_q, block_k, q, k, v, o, lse, do,
                      dlse=None):
    """Fused Pallas backward: two tiled kernels (dk/dv then dq), O(block)
    VMEM, no (S, block_k) f32 materialization in HBM.

    ``dlse``: optional cotangent of the LSE output (when the caller
    differentiates through the logsumexp too, e.g. ring attention's
    merge).  ∂lse_i/∂s_ij = p_ij, so it folds into the kernels as
    ``delta_i -= dlse_i`` — the same place the o-path's rowsum(do·o)
    enters."""
    B, H, S, D = q.shape
    T = k.shape[2]
    nq, nk = S // block_q, T // block_k
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    dor = do.reshape(B * H, S, D).astype(q.dtype)
    # delta_i = rowsum(do * o); same (BH, 8, S) sublane-replicated layout
    # as the forward's LSE output.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(
        delta.reshape(B * H, 1, S), (B * H, 8, S)).astype(jnp.float32)
    lse_t = jnp.broadcast_to(
        lse.reshape(B * H, 1, S), (B * H, 8, S)).astype(jnp.float32)

    q_spec_by_q = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    q_spec_by_k = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    k_spec_by_q = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    k_spec_by_k = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    row_by_q = pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i))
    row_by_k = pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i))

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          num_q=nq),
        grid=(B * H, nk, nq),
        in_specs=[q_spec_by_k, q_spec_by_k, row_by_k, row_by_k,
                  k_spec_by_k, k_spec_by_k],
        out_specs=[k_spec_by_k, k_spec_by_k],
        out_shape=[_out_sds((B * H, T, D), k.dtype, q),
                   _out_sds((B * H, T, D), v.dtype, q)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=_use_interpret(),
    )(qr, dor, lse_t, delta, kr, vr)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          num_k=nk),
        grid=(B * H, nq, nk),
        in_specs=[q_spec_by_q, q_spec_by_q, row_by_q, row_by_q,
                  k_spec_by_q, k_spec_by_q],
        out_specs=q_spec_by_q,
        out_shape=_out_sds((B * H, S, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_use_interpret(),
    )(qr, dor, lse_t, delta, kr, vr)

    return (dq.reshape(B, H, S, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _flash_bwd(causal, sm_scale, block_q, block_k, res, do, dlse=None):
    """Flash backward from the saved LSE.

    Tileable shapes run the fused Pallas kernels (above): O(block) VMEM,
    no (S, block) f32 score materialization in HBM.  Untileable shapes
    fall back to the analytic XLA form scanned over K blocks:

        p_ij = exp(q_i k_j^T * scale - lse_i)
        dv_j = p^T do ;  dp = do v^T ;  ds = p * (dp - rowsum(do * o))
        dq_i += ds k_j * scale ;  dk_j = ds^T q_i * scale

    ``dlse`` (cotangent of the LSE output) folds in as delta -= dlse.
    """
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    T = k.shape[2]
    scale = _sm_scale(q, sm_scale)
    bq = min(block_q, S)
    bk = min(block_k, T)
    if _PALLAS and S % bq == 0 and T % bk == 0 and D % 8 == 0:
        return _flash_bwd_pallas(causal, scale, bq, bk, q, k, v, o, lse, do,
                                 dlse=dlse)
    if T % bk:  # analytic fallback: widen to one K block
        bk = T
    nk = T // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (B,H,S)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    rows = lax.broadcasted_iota(jnp.int32, (S, bk), 0)

    def kblock(carry, jb):
        dq = carry
        ks = lax.dynamic_slice_in_dim(k, jb * bk, bk, axis=2).astype(jnp.float32)
        vs = lax.dynamic_slice_in_dim(v, jb * bk, bk, axis=2).astype(jnp.float32)
        s = jnp.einsum("bhsd,bhtd->bhst", qf, ks) * scale  # (B,H,S,bk)
        if causal:
            cols = jb * bk + lax.broadcasted_iota(jnp.int32, (S, bk), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                     # (B,H,S,bk)
        dv = jnp.einsum("bhst,bhsd->bhtd", p, dof)
        dp = jnp.einsum("bhsd,bhtd->bhst", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhst,bhtd->bhsd", ds, ks)
        dk = jnp.einsum("bhst,bhsd->bhtd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = lax.scan(kblock, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, T, D)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, T, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 512):
    """Fused tiled attention.  ``(B, H, S, D) x (B, H, T, D) -> (B, H, S, D)``.

    Forward runs as one Pallas TPU kernel (online softmax, O(block) VMEM);
    on CPU it runs the same kernel under the Pallas interpreter.  Shapes
    that can't tile (S % block, D % 8) silently use the XLA reference.
    """
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, do):
    return _flash_bwd(causal, sm_scale, block_q, block_k, res, do)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             sm_scale: Optional[float] = None,
                             block_q: int = 1024, block_k: int = 512):
    """:func:`flash_attention` that also returns the per-row logsumexp as
    a DIFFERENTIABLE output ``(o, lse)`` — the building block for merge-
    based compositions (ring attention) whose gradients flow through the
    lse weights; the backward folds the lse cotangent in as
    ``delta -= dlse``."""
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _fal_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return (o, lse), (q, k, v, o, lse)


def _fal_bwd(causal, sm_scale, block_q, block_k, res, ct):
    do, dlse = ct
    return _flash_bwd(causal, sm_scale, block_q, block_k, res, do,
                      dlse=dlse)


flash_attention_with_lse.defvjp(_fal_fwd, _fal_bwd)


# --- chunk attention with LSE (building block for ring) -----------------------


def _chunk_attn(q, k, v, mask, scale):
    """Attention of local q over one K/V chunk with an additive bool mask
    (True = allowed); returns per-chunk normalized output + LSE."""
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # fully-masked rows stay at NEG_INF
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    o = jnp.einsum("bhst,bhtd->bhsd", p / l_safe, v.astype(jnp.float32))
    lse = (m + jnp.log(l_safe))[..., 0]  # (B,H,S)
    return o, lse


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Sequence-parallel attention inside ``shard_map``: every device holds
    a contiguous sequence shard of q/k/v ``(B, H, S_local, D)``; K/V rotate
    around the mesh-axis ring via ``lax.ppermute`` (ICI neighbor exchange)
    while partial attention accumulates with logsumexp merging.

    With ``causal=True``, shard ``r`` attends fully to shards ``< r``,
    causally to itself, and not at all to shards ``> r`` (those chunks are
    masked to NEG_INF and vanish in the merge).  Differentiable end-to-end;
    the VJP rides the transposed ``ppermute``s back around the ring.
    """
    P = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    scale = _sm_scale(q, sm_scale)
    B, H, S, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]

    rows = lax.broadcasted_iota(jnp.int32, (S, S), 0)
    cols = lax.broadcasted_iota(jnp.int32, (S, S), 1)

    # Chunk attention is the masked XLA form (_chunk_attn), not the
    # Pallas kernel: a pallas_call inside the switch inside this scan
    # inside a MODEL's layer scan trips a jax lowering-cache bug in the
    # interpreter (KeyError: closed_call), so the kernelized chunk —
    # flash_attention_with_lse exists for it, dlse-correct — waits on a
    # jax fix.  XLA still fuses the masked form well.
    def step(carry, s_idx):
        o, lse, ks, vs = carry
        src = (me - s_idx) % P  # which shard's K/V we hold this step
        if causal:
            # Three chunk kinds by shard order — full attention to
            # earlier shards, causal to self, and NOTHING from later
            # shards: the dead branch skips the attention compute
            # entirely (for a causal ring that's ~half of all
            # (shard, step) pairs) instead of computing and discarding
            # through the -inf merge.  Differentiable: the skipped
            # branch is constant, and those chunks contribute exactly
            # nothing to the merged output either way.
            def full(qq, kk, vv):
                return _chunk_attn(qq, kk, vv, None, scale)

            def self_causal(qq, kk, vv):
                return _chunk_attn(qq, kk, vv,
                                   (cols <= rows)[None, None], scale)

            def dead(qq, kk, vv):
                # derive from qq so the outputs are varying-over-axis
                # like the live branches' (shard_map vma typing)
                z = qq.astype(jnp.float32) * 0.0
                return z, z[..., 0] + NEG_INF

            idx = jnp.where(src < me, 2, jnp.where(src == me, 1, 0))
            o_c, lse_c = lax.switch(idx, (dead, self_causal, full),
                                    q, ks, vs)
        else:
            o_c, lse_c = _chunk_attn(q, ks, vs, None, scale)
        lse_new = jnp.logaddexp(lse, lse_c)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_c * jnp.exp(lse_c - lse_new)[..., None])
        ks = lax.ppermute(ks, axis_name, perm)
        vs = lax.ppermute(vs, axis_name, perm)
        return (o, lse_new, ks, vs), None

    # Derive the initial carry from q so it inherits q's varying-over-axis
    # type under shard_map (a plain literal would mismatch the carry-out).
    o0 = jnp.zeros_like(q, jnp.float32) * 0.0
    lse0 = q[..., 0].astype(jnp.float32) * 0.0 + NEG_INF
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(P))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      impl: str = "reference"):
    """All-to-all sequence parallelism inside ``shard_map`` (the
    DeepSpeed-Ulysses pattern; SURVEY.md §5.7 lists it as the alltoall
    resharding flavor of context parallelism).

    Every device holds a sequence shard ``(B, H, S_local, D)``.  One
    ``lax.all_to_all`` redistributes to ``(B, H/P, S_global, D)`` — full
    sequence, head subset — so local attention (including the Pallas
    flash kernel via ``impl="flash"``, and ordinary causal masking) runs
    unchanged; the inverse all_to_all restores sequence sharding.
    Requires ``H %% axis_size == 0``.  Differentiable end-to-end: the VJP
    of ``all_to_all`` is the transposed all_to_all.
    """
    P = lax.axis_size(axis_name)
    B, H, S, D = q.shape
    if H % P != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the "
            f"'{axis_name}' axis size ({P}); use ring_attention otherwise")

    def seq_to_heads(x):  # (B,H,S_local,D) -> (B,H/P,S_global,D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if impl == "flash":
        oh = flash_attention(qh, kh, vh, causal, sm_scale=sm_scale)
    else:
        oh = reference_attention(qh, kh, vh, causal=causal,
                                 sm_scale=sm_scale)
    # (B,H/P,S_global,D) -> (B,H,S_local,D)
    return lax.all_to_all(oh, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
