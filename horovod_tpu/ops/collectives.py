"""Collective operations: allreduce / allgather / broadcast / alltoall /
reducescatter, synchronous and handle-based async.

Re-design of the reference's op layer (``horovod/common/ops/*``,
``horovod/torch/mpi_ops.py:72-508``, ``horovod/tensorflow/mpi_ops.py``) for
TPU.  Two execution paths replace the reference's seven backends:

* **In-graph (compiled) path** — when called under a trace with the worker
  axis bound (``shard_map``/``pmap`` over the horovod mesh), each op lowers
  directly to the XLA collective (``psum`` / ``all_gather`` / ``ppermute`` /
  ``all_to_all`` / ``psum_scatter``) over ICI/DCN.  Negotiation
  (``controller.cc:55-347``), tensor fusion (``controller.cc:631-752``) and
  the response cache (``response_cache.h``) are unnecessary here: SPMD
  compilation gives every process an identical collective schedule, and XLA's
  combiner does the batching the fusion buffer did.
* **Eager path** — concrete arrays outside any trace.  Ops run as tiny cached
  compiled programs over a one-device-per-process mesh (the CROSS
  communicator), i.e. the replacement for the reference's CPU backends
  (MPI/Gloo/CCL ops).  Multiple eager ops issued back-to-back are fused by
  the bucketing layer in :mod:`horovod_tpu.ops.fusion`.

All processes must issue eager collectives in the same order — the same
contract the reference enforces dynamically via its coordinator; here it is a
documented SPMD requirement, with the native runtime's stall inspector
(``native/src/stall_inspector.cc``) flagging violations when it is active.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics

# --- Reduce ops (reference: horovod_reduce_op_* in common/operations.cc and
# the Average/Sum/Adasum constants re-exported per framework) ----------------

Average = "Average"
Sum = "Sum"
Adasum = "Adasum"
Min = "Min"      # TPU extension (reference v0.19 has only the three above)
Max = "Max"
Product = "Product"

_REDUCE_OPS = (Average, Sum, Adasum, Min, Max, Product)


def _check_op(op: str) -> None:
    if op not in _REDUCE_OPS:
        raise ValueError(f"Unknown reduce op {op!r}; expected one of {_REDUCE_OPS}")


def _is_traced(tree: Any) -> bool:
    return any(
        isinstance(leaf, jax.core.Tracer) for leaf in jax.tree_util.tree_leaves(tree)
    )


def _axis_names(axis_name) -> tuple:
    if axis_name is None:
        axis_name = basics.axis_name() if basics.is_initialized() else basics.AXIS
        if isinstance(axis_name, str):
            # Probe the trace's axis environment: a step built over the
            # hierarchical (cross, local) mesh binds those axes instead of
            # the flat worker axis, and collectives called with
            # axis_name=None should resolve to whichever is live.
            try:
                lax.axis_size(axis_name)
            except NameError:
                try:
                    lax.axis_size(basics.CROSS_AXIS)
                    lax.axis_size(basics.LOCAL_AXIS)
                    return (basics.CROSS_AXIS, basics.LOCAL_AXIS)
                except NameError:
                    pass
    if isinstance(axis_name, (tuple, list)):
        return tuple(axis_name)
    return (axis_name,)


# --- hierarchical-collective config (reference knobs: common/common.h:76-77,
# HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_HIERARCHICAL_ALLGATHER; exported
# by the launcher's --hierarchical-* flags via runner/config_parser.py) ------

import os as _os


def _env_flag(name: str) -> bool:
    return _os.environ.get(name, "0").lower() not in ("", "0", "false")


def hierarchical_allreduce_enabled() -> bool:
    """True when HOROVOD_HIERARCHICAL_ALLREDUCE requests the two-level
    reduce (psum_scatter over `local`/ICI → psum over `cross`/DCN →
    all_gather over `local`) instead of a flat psum over both axes."""
    return _env_flag("HOROVOD_HIERARCHICAL_ALLREDUCE")


def hierarchical_allgather_enabled() -> bool:
    """True when HOROVOD_HIERARCHICAL_ALLGATHER requests staged gathers
    (local axis first, then cross) instead of one joint-axis all_gather."""
    return _env_flag("HOROVOD_HIERARCHICAL_ALLGATHER")


def _axis_size(axes: tuple) -> int:
    try:
        n = 1
        for a in axes:
            n *= lax.axis_size(a)
        return n
    except (NameError, AttributeError):
        # Fallback: psum of ones — XLA constant-folds this for a static mesh.
        return lax.psum(jnp.ones((), jnp.int32), axes)


def _reraise_unbound(err: NameError) -> None:
    raise RuntimeError(
        "horovod_tpu collective called inside jit without the worker axis "
        "bound. Wrap the computation in jax.shard_map over horovod_tpu.mesh() "
        "(or use horovod_tpu.spmd.run_step), or call the op eagerly."
    ) from err


# --- in-graph implementations ----------------------------------------------


def _hier_psum(t, axes: tuple):
    """Two-level allreduce over the (cross, local) mesh — the compiled
    re-design of ``NCCLHierarchicalAllreduce``
    (``ops/nccl_operations.cc:162-354``): reduce-scatter within the node,
    allreduce of the scattered shard across nodes, allgather within the
    node.  Here `local` rides ICI and `cross` rides DCN, so the cross-host
    hop moves 1/local_size of the tensor per chip."""
    cross, local = axes
    n_local = lax.axis_size(local)
    flat = t.reshape(-1)
    pad = (-flat.shape[0]) % n_local
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, local, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross)
    full = lax.all_gather(shard, local, axis=0, tiled=True)
    if pad:
        full = full[: t.size]
    return full.reshape(t.shape)


def _injit_allreduce(tensor, op: str, axes: tuple, prescale, postscale):
    if op == Adasum:
        from horovod_tpu.ops import adasum as _adasum

        return _adasum.adasum_allreduce(tensor, axis_name=axes)
    if prescale is not None and prescale != 1.0:
        tensor = jax.tree_util.tree_map(lambda t: t * prescale, tensor)
    if op in (Average, Sum):
        if len(axes) == 2 and hierarchical_allreduce_enabled():
            out = jax.tree_util.tree_map(lambda t: _hier_psum(t, axes), tensor)
        else:
            out = jax.tree_util.tree_map(lambda t: lax.psum(t, axes), tensor)
        if op == Average:
            n = _axis_size(axes)
            out = jax.tree_util.tree_map(lambda t: t / jnp.asarray(n, t.dtype), out)
    elif op == Min:
        out = jax.tree_util.tree_map(lambda t: lax.pmin(t, axes), tensor)
    elif op == Max:
        out = jax.tree_util.tree_map(lambda t: lax.pmax(t, axes), tensor)
    elif op == Product:
        # XLA has no pprod; take it through logs? No — all_gather+reduce is
        # exact for small worker counts and rare use.  Reference lacks
        # Product entirely, so the simple form is acceptable.
        def _prod(t):
            g = lax.all_gather(t, axes[-1])
            for a in axes[:-1]:
                g = lax.all_gather(g, a)
            return jnp.prod(g.reshape((-1,) + t.shape), axis=0)

        out = jax.tree_util.tree_map(_prod, tensor)
    else:  # pragma: no cover
        raise AssertionError(op)
    if postscale is not None and postscale != 1.0:
        out = jax.tree_util.tree_map(lambda t: t * postscale, out)
    return out


def _injit_broadcast(tensor, root_rank: int, axes: tuple):
    """Broadcast by masked psum: select(rank==root, x, 0) then sum.

    One allreduce on ICI — the compiled replacement for
    ``NCCLBroadcast::Execute`` (``ops/nccl_operations.cc:366-396``).
    """
    if len(axes) == 1:
        idx = lax.axis_index(axes[0])
    else:
        idx = jnp.zeros((), jnp.int32)
        for a in axes:
            idx = idx * lax.axis_size(a) + lax.axis_index(a)

    def _bc(t):
        masked = jnp.where(idx == root_rank, t, jnp.zeros_like(t))
        return lax.psum(masked, axes)

    return jax.tree_util.tree_map(_bc, tensor)


def _injit_allgather(tensor, axes: tuple):
    def _ag(t):
        if len(axes) == 2 and hierarchical_allgather_enabled():
            # MPIHierarchicalAllgather analogue (ops/mpi_operations.cc):
            # gather within the node first (ICI), then gather node blocks
            # across hosts (DCN).  Worker order is (cross, local)-major on
            # both paths.
            g = lax.all_gather(t, axes[1], axis=0, tiled=True)
            return lax.all_gather(g, axes[0], axis=0, tiled=True)
        # Flat path: ONE gather over the (possibly joint) axis — XLA emits a
        # single all-gather over the full device set.
        return lax.all_gather(t, axes if len(axes) > 1 else axes[0],
                              axis=0, tiled=True)

    return jax.tree_util.tree_map(_ag, tensor)


def _injit_alltoall(tensor, axes: tuple):
    if len(axes) != 1:
        raise ValueError("alltoall supports a single mesh axis")

    def _a2a(t):
        return lax.all_to_all(t, axes[0], split_axis=0, concat_axis=0, tiled=True)

    return jax.tree_util.tree_map(_a2a, tensor)


def _injit_reducescatter(tensor, op: str, axes: tuple):
    if len(axes) != 1:
        raise ValueError("reducescatter supports a single mesh axis")
    if op not in (Average, Sum):
        raise ValueError("reducescatter supports Sum/Average")

    def _rs(t):
        out = lax.psum_scatter(t, axes[0], scatter_dimension=0, tiled=True)
        if op == Average:
            n = _axis_size(axes)
            out = out / jnp.asarray(n, out.dtype)
        return out

    return jax.tree_util.tree_map(_rs, tensor)


# --- eager implementations --------------------------------------------------
#
# The eager data plane: one device per process forms the CROSS mesh; local
# host values are stitched into a global array and a cached compiled program
# performs the reduction with replicated output.  With a single process all
# ops are local identities (sum over one contributor), matching reference
# semantics where size()==1.

_eager_lock = threading.Lock()


@functools.lru_cache(maxsize=1)
def _process_mesh() -> jax.sharding.Mesh:
    devs = {}
    for d in basics.mesh().devices.flat:
        devs.setdefault(d.process_index, d)
    ordered = [devs[p] for p in sorted(devs)]
    return jax.sharding.Mesh(np.array(ordered, dtype=object), axis_names=("proc",))


def _to_global(x: np.ndarray):
    """Stitch per-process host values into one global array with leading
    axis = process, sharded over the process mesh."""
    pm = _process_mesh()
    sharding = jax.sharding.NamedSharding(pm, jax.sharding.PartitionSpec("proc"))
    local_dev = [d for d in pm.devices.flat if d.process_index == jax.process_index()]
    shard = jax.device_put(np.asarray(x)[None], local_dev[0])
    nproc = pm.devices.size
    return jax.make_array_from_single_device_arrays(
        (nproc,) + tuple(np.asarray(x).shape), sharding, [shard]
    )


@functools.lru_cache(maxsize=1)
def _process_local_counts() -> tuple:
    """Chips per process, ordered by process index.

    This is the weight each process's eager contribution carries: the
    API's worker count is CHIPS (``basics.size()``), so with
    ``local_size > 1`` (one process driving several chips) an eager
    submission stands for every local chip — Sum multiplies by the local
    count and Average divides by ``size()``, keeping eager and in-graph
    reductions consistent (the reference has no such seam because a
    process is exactly one GPU; ``common/basics.py:22-211`` contract)."""
    counts: dict = {}
    for d in basics.mesh().devices.flat:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return tuple(counts[p] for p in sorted(counts))


@functools.lru_cache(maxsize=4096)
def _compiled_reduce(op: str, counts: tuple):
    pm = _process_mesh()
    repl = jax.sharding.NamedSharding(pm, jax.sharding.PartitionSpec())
    nchips = int(sum(counts))
    weighted = any(c != 1 for c in counts)

    def fn(a):
        if weighted and op in (Sum, Average, Product):
            w = jnp.asarray(np.asarray(counts), a.dtype).reshape(
                (-1,) + (1,) * (a.ndim - 1))
        if op == Sum:
            return (a * w).sum(axis=0) if weighted else a.sum(axis=0)
        if op == Average:
            # Promote like jnp.mean (ints divide to float).
            s = (a * w).sum(axis=0) if weighted else a.sum(axis=0)
            return s / nchips
        if op == Min:
            return a.min(axis=0)  # duplicates don't change min/max
        if op == Max:
            return a.max(axis=0)
        if op == Product:
            return (a ** w).prod(axis=0) if weighted else a.prod(axis=0)
        raise AssertionError(op)

    return jax.jit(fn, out_shardings=repl)


@functools.lru_cache(maxsize=4096)
def _compiled_identity_replicated():
    pm = _process_mesh()
    repl = jax.sharding.NamedSharding(pm, jax.sharding.PartitionSpec())
    return jax.jit(lambda a: a, out_shardings=repl)


# --- traffic-shaped eager programs -------------------------------------------
#
# Builders are parameterized by (mesh, axis) so tests can compile them over a
# virtual multi-device mesh and assert on the emitted collectives (the
# "bytes proportional to tensor, not P x tensor" contract).  The eager path
# instantiates them over the process mesh via the cached wrappers below.


def _pick_program(mesh, axis: str, src: int):
    """Rooted broadcast: replicate ONE shard of a dim-0-sharded array.

    The owner's block is statically sliced out, so the partitioner moves only
    that tensor (select + all-reduce or collective-broadcast) — never an
    all-gather of every rank's buffer.  Replaces the reference's
    ``MPIBroadcast``/``NCCLBroadcast`` on the eager path."""
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.jit(
        lambda a: lax.index_in_dim(a, src, axis=0, keepdims=False),
        out_shardings=repl,
    )


def _reducescatter_program(mesh, axis: str, op: str, counts: tuple = None):
    """Eager reduce-scatter as a true ``lax.psum_scatter`` (each process
    receives only its reduced 1/P slice and each link carries (P-1)/P of
    one tensor — half the all-reduce cost; reference
    ``ops/nccl_operations.cc:162-354`` intra-node phase).

    ``counts``: chips per process (see :func:`_process_local_counts`) —
    contributions are chip-weighted so Sum/Average match the in-graph
    (worker-axis) semantics when ``local_size > 1``."""
    from horovod_tpu import spmd

    spec = jax.sharding.PartitionSpec(axis)
    weighted = counts is not None and any(c != 1 for c in counts)
    denom = int(sum(counts)) if counts else None

    def fn(block):  # per-shard: (1, d0, ...)
        t = jnp.squeeze(block, 0)
        if weighted:
            w = jnp.asarray(np.asarray(counts), t.dtype)[
                lax.axis_index(axis)]
            t = t * w
        out = lax.psum_scatter(t, axis, scatter_dimension=0, tiled=True)
        if op == Average:
            n = denom if denom is not None else lax.axis_size(axis)
            out = out / jnp.asarray(n, out.dtype)
        return out[None]

    return jax.jit(spmd.shard(fn, in_specs=spec, out_specs=spec, mesh=mesh))


def _alltoall_program(mesh, axis: str):
    """Eager all-to-all as a true ``lax.all_to_all`` over the process axis
    (traffic: each link carries one peer-slice, not the whole tensor)."""
    from horovod_tpu import spmd

    spec = jax.sharding.PartitionSpec(axis)

    def fn(block):  # per-shard: (1, rows, ...)
        t = jnp.squeeze(block, 0)
        t = lax.all_to_all(t, axis, split_axis=0, concat_axis=0, tiled=True)
        return t[None]

    return jax.jit(spmd.shard(fn, in_specs=spec, out_specs=spec, mesh=mesh))


@functools.lru_cache(maxsize=4096)
def _compiled_pick(src: int):
    return _pick_program(_process_mesh(), "proc", src)


@functools.lru_cache(maxsize=16)
def _compiled_reducescatter(op: str):
    return _reducescatter_program(_process_mesh(), "proc", op,
                                  _process_local_counts())


@functools.lru_cache(maxsize=1)
def _compiled_alltoall():
    return _alltoall_program(_process_mesh(), "proc")


def _replicated_to_host(arr) -> np.ndarray:
    return np.asarray(jax.device_get(arr))


def _local_shard_to_host(arr) -> np.ndarray:
    """Fetch this process's (single) addressable shard of a global array."""
    shards = arr.addressable_shards
    assert len(shards) == 1, len(shards)
    return np.asarray(shards[0].data)


def _eager_allreduce(x, op: str, prescale, postscale) -> np.ndarray:
    xh = np.asarray(x)
    if prescale is not None and prescale != 1.0:
        xh = xh * np.asarray(prescale, xh.dtype)
    if basics.cross_size() == 1:
        # Same chip-weighted semantics as the multi-process path: one
        # process driving N chips submits a value that stands for every
        # local chip, so Sum is N*x (== the in-graph worker-axis psum)
        # and Average is N*x/size() == x.  Min/Max/Adasum(identical
        # contributions) are duplicate-insensitive.
        ls = basics.local_size()
        if ls > 1 and op == Sum:
            out = xh * np.asarray(ls, xh.dtype)
        elif ls > 1 and op == Product:
            out = xh ** ls
        else:
            out = xh.copy()
    elif op == Adasum:
        from horovod_tpu.ops import adasum as _adasum

        out = _adasum.eager_adasum(xh)
    else:
        out = _replicated_to_host(
            _compiled_reduce(op, _process_local_counts())(_to_global(xh))
        )
    if postscale is not None and postscale != 1.0:
        out = out * np.asarray(postscale, out.dtype)
    return out


def _eager_allgather(x) -> np.ndarray:
    xh = np.asarray(x)
    if basics.cross_size() == 1:
        return xh.copy()
    # Variable first-dim support (reference: allgather recvcounts /
    # displacements, ops/collective_operations.cc:120-196): gather sizes,
    # pad to max, gather, slice.
    n0 = np.zeros((), np.int64) + xh.shape[0]
    sizes = _replicated_to_host(
        _compiled_identity_replicated()(_to_global(n0))
    ).astype(int)
    m = int(sizes.max())
    pad = np.zeros((m,) + xh.shape[1:], xh.dtype)
    pad[: xh.shape[0]] = xh
    gathered = _replicated_to_host(_compiled_identity_replicated()(_to_global(pad)))
    return np.concatenate([gathered[i, : sizes[i]] for i in range(len(sizes))], axis=0)


def _eager_broadcast(x, root_rank: int) -> np.ndarray:
    xh = np.asarray(x)
    if basics.cross_size() == 1:
        return xh.copy()
    # root_rank is a worker rank; owning process = root // local_size.
    proc = root_rank // max(basics.local_size(), 1)
    return _replicated_to_host(_compiled_pick(proc)(_to_global(xh)))


def _eager_reducescatter(x, op: str) -> np.ndarray:
    if op not in (Average, Sum):
        raise ValueError("reducescatter supports Sum/Average")
    xh = np.asarray(x)
    P = basics.cross_size()
    if xh.shape[0] % P != 0:
        raise ValueError(
            f"reducescatter requires dim0 ({xh.shape[0]}) divisible by the "
            f"process count ({P}) on the eager path"
        )
    if P == 1:
        # Chip-weighted like _eager_allreduce: Sum over N local chips is
        # N*x; Average is N*x/size() == x.
        ls = basics.local_size()
        if ls > 1 and op == Sum:
            return xh * np.asarray(ls, xh.dtype)
        return xh.copy()
    return _local_shard_to_host(_compiled_reducescatter(op)(_to_global(xh)))[0]


def _eager_alltoall(x, splits) -> np.ndarray:
    xh = np.asarray(x)
    P = basics.cross_size()
    if splits is None and xh.shape[0] % P != 0:
        raise ValueError("alltoall without splits requires dim0 % size == 0")
    if splits is not None:
        splits = np.asarray(splits, np.int64)
        if splits.shape != (P,) or splits.sum() != xh.shape[0]:
            raise ValueError(f"splits must be ({P},) summing to dim0")
    if P == 1:
        return xh.copy()
    if splits is None:
        # Even splits: one true all_to_all — each link carries one
        # tensor/P slice.
        out = _local_shard_to_host(_compiled_alltoall()(_to_global(xh)))
        return out[0]
    # Uneven splits: pad each destination piece to the global max split and
    # run the same all_to_all over (P, max_split) blocks — traffic is
    # P x max_split (~ tensor size), not P x whole-tensor (reference covers
    # uneven recvcounts via MPI_Alltoallv; XLA all_to_all is regular, so
    # padding buys regularity).
    gathered_splits = _replicated_to_host(
        _compiled_identity_replicated()(_to_global(splits))
    ).astype(int)
    m = int(gathered_splits.max())
    send = np.zeros((P, m) + xh.shape[1:], xh.dtype)
    offs = np.concatenate([[0], np.cumsum(splits)])
    for p in range(P):
        send[p, : splits[p]] = xh[offs[p] : offs[p + 1]]
    out = _local_shard_to_host(_compiled_alltoall()(_to_global(send)))[0]
    me = jax.process_index()
    return np.concatenate(
        [out[p, : gathered_splits[p, me]] for p in range(P)], axis=0
    )


# --- native-runtime routing ---------------------------------------------------
#
# When the native control plane (horovod_tpu.native — the C++ re-design of
# the reference's background thread/controller/fusion/cache) is running,
# every eager op is enqueued as a named request and executed only once the
# coordinator declares it globally ready; requests submitted in the same
# cycle fuse into one collective.  Without it (library unavailable or
# HOROVOD_NATIVE=0), ops run directly in program order.


def _native_rt():
    from horovod_tpu import eager_runtime

    return eager_runtime.get()


def _native_kind_and_args(kind: str):
    from horovod_tpu import native

    return {
        "allreduce": native.ALLREDUCE,
        "allgather": native.ALLGATHER,
        "broadcast": native.BROADCAST,
        "alltoall": native.ALLTOALL,
        "reducescatter": native.REDUCESCATTER,
    }[kind]


def _native_submit_tree(rt, kind: str, tree, name, **kw):
    """Submit every leaf as its own named request; returns (treedef,
    [(handle, name)]).  All leaves go in before any wait, so one
    negotiation cycle sees — and fuses — the whole pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    op_type = _native_kind_and_args(kind)
    pairs = []
    for i, leaf in enumerate(leaves):
        lname = rt.auto_name(kind, f"{name}.{i}" if name and len(leaves) > 1
                             else name)
        arr = np.asarray(leaf)
        h = rt.submit(lname, op_type, arr, **kw)
        pairs.append((h, lname))
    return treedef, pairs


def _native_wait_tree(rt, treedef, pairs):
    return jax.tree_util.tree_unflatten(
        treedef, [rt.wait(h, n) for h, n in pairs]
    )


def _native_reduce_op(op: str) -> int:
    from horovod_tpu import eager_runtime

    to_native, _ = eager_runtime._op_maps()
    return to_native[op]


# --- public API --------------------------------------------------------------


def allreduce(
    tensor,
    op: str = Average,
    *,
    axis_name=None,
    compression=None,
    name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Allreduce a tensor (or pytree) across all workers.

    Reference: ``hvd.allreduce`` (``tensorflow/__init__.py:43-118``,
    ``torch/mpi_ops.py:94-180``).  ``op=Average`` divides by worker count in
    the compiled graph (the reference divides in the completion callback,
    ``torch/mpi_ops_v2.cc:69-74``).
    """
    _check_op(op)
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    if _is_traced(tensor):
        try:
            out = _injit_allreduce(
                tensor, op, _axis_names(axis_name), prescale_factor, postscale_factor
            )
        except NameError as e:
            _reraise_unbound(e)
    else:
        basics._ctx()
        rt = _native_rt()
        if rt is not None:
            treedef, pairs = _native_submit_tree(
                rt, "allreduce", tensor, name,
                reduce_op=_native_reduce_op(op),
                prescale=1.0 if prescale_factor is None else prescale_factor,
                postscale=1.0 if postscale_factor is None else postscale_factor,
            )
            out = _native_wait_tree(rt, treedef, pairs)
        else:
            out = jax.tree_util.tree_map(
                lambda t: _eager_allreduce(
                    t, op, prescale_factor, postscale_factor
                ),
                tensor,
            )
    if compression is not None:
        out = compression.decompress(out, ctx)
    return out


def process_sum(tensor, *, name: Optional[str] = None):
    """Sum one contribution PER PROCESS (eager path).

    The eager ``Sum`` is chip-weighted — each process's submission stands
    for all ``local_size()`` chips it drives (see docs/concepts.md).  Use
    this instead when the payload is process-level data (a shard's row
    count, a per-process aggregate): the pre-division by the local chip
    count makes the chip weighting cancel exactly, also with
    heterogeneous chip counts (Σ ls_p · x_p/ls_p = Σ x_p)."""
    if _is_traced(tensor):
        raise ValueError(
            "process_sum is an eager (host-side) op; in-graph code sums "
            "per chip with allreduce(op=Sum)")
    ls = float(basics.local_size()) if basics.is_initialized() else 1.0
    return allreduce(tensor, Sum, name=name, prescale_factor=1.0 / ls)


def grouped_allreduce(tensors: Sequence, op: str = Average, *, axis_name=None, **kw):
    """Allreduce a list of tensors as one logical fused operation
    (reference: grouped allreduce / the fusion buffer).  In-graph, XLA's
    collective combiner fuses adjacent psums; eagerly we bucket explicitly
    via :mod:`horovod_tpu.ops.fusion`."""
    tensors = list(tensors)
    if _is_traced(tensors):
        return [allreduce(t, op, axis_name=axis_name, **kw) for t in tensors]
    basics._ctx()
    # Parse the kwargs the eager paths support; anything else raises LOUDLY
    # rather than silently returning unscaled results (r4 advisor finding).
    name = kw.pop("name", None)
    prescale = kw.pop("prescale_factor", None)
    postscale = kw.pop("postscale_factor", None)
    if kw:
        raise TypeError(
            f"unsupported kwargs for eager grouped allreduce: {sorted(kw)}")
    rt = _native_rt()
    if rt is not None:
        # Submit the whole group before waiting: one negotiation cycle sees
        # all of it and fuses (routing through the native queue also keeps
        # collective launch order globally consistent with concurrent
        # async ops).
        treedef, pairs = _native_submit_tree(
            rt, "allreduce", tensors, name,
            reduce_op=_native_reduce_op(op),
            prescale=1.0 if prescale is None else prescale,
            postscale=1.0 if postscale is None else postscale,
        )
        return _native_wait_tree(rt, treedef, pairs)
    if prescale is not None:
        tensors = [np.asarray(t) * np.asarray(prescale, np.asarray(t).dtype)
                   for t in tensors]

    def _post(out):
        if postscale is None:
            return out
        return [o * np.asarray(postscale, np.asarray(o).dtype) for o in out]

    if op == Adasum:
        # Concatenating a bucket and running one Adasum would change the
        # math (one global pairwise coefficient instead of one per
        # tensor); the group kernel shares the log2(P) communication
        # rounds while keeping per-tensor coefficients (the reference's
        # FusedAllreduce semantics, adasum.h:194-338).
        from horovod_tpu.ops import adasum as _adasum

        return _post(_adasum.eager_adasum_group(
            [np.asarray(t) for t in tensors]))
    from horovod_tpu.ops import fusion

    return _post(fusion.fused_eager_allreduce(tensors, op))


def allgather(tensor, *, axis_name=None, name: Optional[str] = None):
    """Concatenate tensors from all workers along dim 0
    (``MPI_Allgatherv`` analogue; variable first-dim supported eagerly)."""
    if _is_traced(tensor):
        try:
            return _injit_allgather(tensor, _axis_names(axis_name))
        except NameError as e:
            _reraise_unbound(e)
    basics._ctx()
    rt = _native_rt()
    if rt is not None:
        treedef, pairs = _native_submit_tree(rt, "allgather", tensor, name)
        return _native_wait_tree(rt, treedef, pairs)
    return jax.tree_util.tree_map(_eager_allgather, tensor)


def broadcast(tensor, root_rank: int = 0, *, axis_name=None, name=None):
    """Broadcast from worker ``root_rank`` to all workers."""
    if _is_traced(tensor):
        try:
            return _injit_broadcast(tensor, root_rank, _axis_names(axis_name))
        except NameError as e:
            _reraise_unbound(e)
    basics._ctx()
    rt = _native_rt()
    if rt is not None:
        treedef, pairs = _native_submit_tree(
            rt, "broadcast", tensor, name, root_rank=root_rank
        )
        return _native_wait_tree(rt, treedef, pairs)
    return jax.tree_util.tree_map(lambda t: _eager_broadcast(t, root_rank), tensor)


def alltoall(tensor, splits=None, *, axis_name=None, name=None):
    """Exchange dim-0 slices between all workers (TPU extension over the
    reference's op set — added to Horovod post-0.19; here it rides
    ``lax.all_to_all`` / ICI natively)."""
    if _is_traced(tensor):
        if splits is not None:
            raise ValueError("uneven splits only supported eagerly")
        try:
            return _injit_alltoall(tensor, _axis_names(axis_name))
        except NameError as e:
            _reraise_unbound(e)
    basics._ctx()
    rt = _native_rt()
    if rt is not None:
        if splits is None:
            treedef, pairs = _native_submit_tree(rt, "alltoall", tensor, name)
            return _native_wait_tree(rt, treedef, pairs)
        # Uneven splits can't ride the native queue (the wire Request has no
        # splits field, matching the reference v0.19 op set which predates
        # alltoallv), so they run on the direct path.  Flush with a native
        # BARRIER first: under the SPMD ordering contract every op submitted
        # before this point (on any rank) completes before the barrier does,
        # so no negotiated launch can interleave with the direct collective
        # (protocol invariant #4).  A local pending check would NOT work —
        # ranks can disagree on local pending state and then only some of
        # them would enter the global collective.
        rt.barrier()
    return jax.tree_util.tree_map(lambda t: _eager_alltoall(t, splits), tensor)


def reducescatter(tensor, op: str = Average, *, axis_name=None, name=None):
    """Reduce-scatter along dim 0 (the primitive underlying hierarchical
    allreduce, ``ops/nccl_operations.cc:162-354``).  In-graph it lowers to
    ``lax.psum_scatter``; eagerly each worker receives its reduced 1/P
    slice through the same negotiated runtime as the other ops."""
    if _is_traced(tensor):
        try:
            return _injit_reducescatter(tensor, op, _axis_names(axis_name))
        except NameError as e:
            _reraise_unbound(e)
    _validate_reducescatter(tensor, op)
    basics._ctx()
    rt = _native_rt()
    if rt is not None:
        treedef, pairs = _native_submit_tree(
            rt, "reducescatter", tensor, name, reduce_op=_native_reduce_op(op)
        )
        return _native_wait_tree(rt, treedef, pairs)
    return jax.tree_util.tree_map(lambda t: _eager_reducescatter(t, op), tensor)


def _validate_reducescatter(tensor, op: str) -> None:
    """Fail fast with a local ValueError (identically on every rank, since
    shapes match by contract) instead of letting the background executor
    surface an opaque cross-rank NativeError after a negotiation round."""
    if op not in (Average, Sum):
        raise ValueError("reducescatter supports Sum/Average")
    P = basics.cross_size() if basics.is_initialized() else 1
    for leaf in jax.tree_util.tree_leaves(tensor):
        a = np.asarray(leaf)
        if a.ndim == 0:
            raise ValueError("reducescatter requires tensors with >= 1 dim")
        if a.shape[0] % max(P, 1) != 0:
            raise ValueError(
                f"reducescatter requires dim0 ({a.shape[0]}) divisible by "
                f"the worker count ({P})"
            )


def barrier() -> None:
    """Block until all processes arrive (eager, process-level).  With the
    native runtime this is a true BARRIER request through the coordinator;
    otherwise a zero-byte allreduce."""
    basics._ctx()
    rt = _native_rt()
    if rt is not None:
        rt.barrier()
        return
    _eager_allreduce(np.zeros((), np.float32), Sum, None, None)


# --- handle-based async API --------------------------------------------------
#
# Mirrors torch/mpi_ops.py:72-508 + handle_manager.cc:21-55.  Eager jax
# dispatch is already asynchronous, so a handle wraps the in-flight arrays;
# ``synchronize`` materializes them.


class _HandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._results: dict[int, Any] = {}

    def allocate(self, value) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = value
            return h

    def take(self, handle: int):
        with self._lock:
            if handle not in self._results:
                raise ValueError(f"Unknown or already-synchronized handle {handle}")
            return self._results.pop(handle)

    def peek(self, handle: int):
        with self._lock:
            return self._results.get(handle)


_handles = _HandleManager()


class _NativeInFlight:
    """An op pending in the native runtime's negotiation queue (the
    reference's handle, ``torch/mpi_ops_v2.cc`` + ``handle_manager.cc:
    21-55``).  Carries the compression context so ``synchronize``
    decompresses, matching the synchronous path."""

    def __init__(self, rt, treedef, pairs, compression=None, ctx=None):
        self.rt = rt
        self.treedef = treedef
        self.pairs = pairs
        self.compression = compression
        self.ctx = ctx

    def done(self) -> bool:
        return all(self.rt.poll(h) for h, _ in self.pairs)

    def resolve(self):
        out = _native_wait_tree(self.rt, self.treedef, self.pairs)
        if self.compression is not None:
            out = self.compression.decompress(out, self.ctx)
        return out


def _async(fn, *args, **kw) -> int:
    return _handles.allocate(fn(*args, **kw))


def allreduce_async(tensor, op: str = Average, name=None, **kw) -> int:
    _check_op(op)
    rt = None if _is_traced(tensor) else _native_rt()
    if rt is not None:
        basics._ctx()
        compression = kw.get("compression")
        ctx = None
        if compression is not None:
            tensor, ctx = compression.compress(tensor)
        pre = kw.get("prescale_factor")
        post = kw.get("postscale_factor")
        treedef, pairs = _native_submit_tree(
            rt, "allreduce", tensor, name,
            reduce_op=_native_reduce_op(op),
            prescale=1.0 if pre is None else pre,
            postscale=1.0 if post is None else post,
        )
        return _handles.allocate(
            _NativeInFlight(rt, treedef, pairs, compression, ctx)
        )
    return _async(allreduce, tensor, op, name=name, **kw)


def allgather_async(tensor, name=None, **kw) -> int:
    rt = None if _is_traced(tensor) else _native_rt()
    if rt is not None:
        basics._ctx()
        treedef, pairs = _native_submit_tree(rt, "allgather", tensor, name)
        return _handles.allocate(_NativeInFlight(rt, treedef, pairs))
    return _async(allgather, tensor, name=name, **kw)


def broadcast_async(tensor, root_rank: int = 0, name=None, **kw) -> int:
    rt = None if _is_traced(tensor) else _native_rt()
    if rt is not None:
        basics._ctx()
        treedef, pairs = _native_submit_tree(
            rt, "broadcast", tensor, name, root_rank=root_rank
        )
        return _handles.allocate(_NativeInFlight(rt, treedef, pairs))
    return _async(broadcast, tensor, root_rank, name=name, **kw)


def reducescatter_async(tensor, op: str = Average, name=None, **kw) -> int:
    rt = None if _is_traced(tensor) else _native_rt()
    if rt is not None:
        _validate_reducescatter(tensor, op)
        basics._ctx()
        treedef, pairs = _native_submit_tree(
            rt, "reducescatter", tensor, name, reduce_op=_native_reduce_op(op)
        )
        return _handles.allocate(_NativeInFlight(rt, treedef, pairs))
    return _async(reducescatter, tensor, op, name=name, **kw)


def alltoall_async(tensor, splits=None, name=None, **kw) -> int:
    rt = None if _is_traced(tensor) else _native_rt()
    if rt is not None and splits is None:
        basics._ctx()
        treedef, pairs = _native_submit_tree(rt, "alltoall", tensor, name)
        return _handles.allocate(_NativeInFlight(rt, treedef, pairs))
    return _async(alltoall, tensor, splits, name=name, **kw)


# In-place variants: JAX arrays are immutable; these are aliases kept for
# API parity with allreduce_async_ / broadcast_async_ (torch/mpi_ops.py).
allreduce_async_ = allreduce_async
broadcast_async_ = broadcast_async


def poll(handle: int) -> bool:
    """True if the op behind ``handle`` has completed
    (``horovod_torch_poll``, ``handle_manager.cc:34-41``)."""
    val = _handles.peek(handle)
    if val is None:
        return True
    if isinstance(val, _NativeInFlight):
        return val.done()
    done = True
    for leaf in jax.tree_util.tree_leaves(val):
        if isinstance(leaf, jax.Array):
            try:
                done = done and leaf.is_ready()
            except AttributeError:  # older jax
                pass
    return done


def synchronize(handle: int):
    """Wait for and return the result of an async op
    (``torch/mpi_ops.py`` ``synchronize``)."""
    val = _handles.take(handle)
    if isinstance(val, _NativeInFlight):
        return val.resolve()
    return jax.tree_util.tree_map(
        lambda l: jax.block_until_ready(l) if isinstance(l, jax.Array) else l, val
    )
