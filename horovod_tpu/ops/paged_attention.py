"""Fused Pallas paged-attention decode kernel (flash-decoding over the
page table, int8 dequant in the load).

The unfused paged decode path (:func:`horovod_tpu.models.transformer.
_attention_decode_paged`) runs gather-pages -> ``kv_dequantize`` ->
attend as separate XLA ops, materializing every active slot's FULL
logical K/V view (``(S, H_kv, max_pages * page, Dh)`` at compute dtype)
each tick.  Decode is cache-bandwidth-bound, so that materialization is
pure overhead — the paper's fusion-buffer insight applied to serving:
collapse the many small memory-bound steps into one resident pass.

This kernel performs the whole resolve-dequant-attend in one Pallas
program per ``(slot, kv-head)``:

* the grid walks ``(slot, kv_head, page_block)`` with the PAGE BLOCK
  innermost, so the online-softmax scratch carries across a slot's
  pages;
* the page table row lives in SMEM via scalar prefetch
  (``PrefetchScalarGridSpec``) — each K/V BlockSpec's index_map reads
  ``table[s, b]`` to stream the REFERENCED physical page straight from
  the pool, so the gather never materializes;
* int8 dequant is fused into the load: the page's int8 payload and its
  per-vector scales are combined in-register (f32 compute, then cast to
  the compute dtype — the exact :func:`~horovod_tpu.models.transformer.
  kv_dequantize` contract, see :data:`DEQUANT_COMPUTE`);
* masking is by LOGICAL position against a per-slot ``limit``
  (positions ``< limit[s]`` attend) — partial last pages, page-tail
  junk, NULL-page trash, and inactive slots (``limit == 0``) all fall
  out of the same comparison;
* cross-block combination is the standard flash-decoding online
  softmax (running max / sum / accumulator with rescale), and the
  kernel emits per-row ``logsumexp`` so a caller can merge the result
  with attention over OTHER sources (the speculative VERIFY path
  combines committed-page attention with in-window attention by LSE).

Conventions shared with :mod:`~horovod_tpu.ops.attention` via
:mod:`~horovod_tpu.ops._pallas_util`: non-fatal Pallas import, CPU
interpreter fallback (tier-1 CPU tests exercise the REAL kernel body),
and a pure-JAX reference path (:func:`paged_attend_reference`) for
shapes the TPU tiling cannot serve.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.ops._pallas_util import (
    NEG_INF,
    PALLAS_AVAILABLE,
    pl,
    pltpu,
    use_interpret,
)

__all__ = ["DEQUANT_COMPUTE", "paged_attend", "paged_attend_reference",
           "kernel_supported"]


# The pinned dequant compute dtype.  ``kv_dequantize`` promotes int8
# payloads and their scales through f32 — even when the compute dtype is
# bf16 — and only THEN casts to the target dtype.  The fused kernel
# mirrors the same f32-multiply-then-cast in its load so the unfused
# fallback and the fused path round identically; any change here must
# change both (tests/test_paged.py pins the contract).
DEQUANT_COMPUTE = jnp.float32


def _dequant(q, scale, dtype):
    """The in-kernel mirror of ``kv_dequantize``: f32 multiply, then a
    single cast to ``dtype`` (see :data:`DEQUANT_COMPUTE`)."""
    return (q.astype(DEQUANT_COMPUTE)
            * scale[..., None].astype(DEQUANT_COMPUTE)).astype(dtype)


# Minimum sublane tile (second-to-last dim) per dtype on TPU.  The
# interpreter is layout-agnostic, so this gates only the real-TPU path.
_MIN_SUBLANE = {"float32": 8, "bfloat16": 16, "int8": 32}


def kernel_supported(k_pool, page_size: int, head_dim: int) -> bool:
    """Whether the Pallas kernel can serve this pool's layout.

    Under the interpreter (any non-TPU backend) every shape works; on a
    real TPU the page must fill whole dtype tiles — ``head_dim`` a lane
    multiple (128) and ``page_size`` a sublane multiple of the STORED
    dtype (8 f32 / 16 bf16 / 32 int8).  Otherwise the caller gets the
    pure-JAX :func:`paged_attend_reference` with identical semantics.
    """
    if not PALLAS_AVAILABLE:
        return False
    if use_interpret():
        return True
    sub = _MIN_SUBLANE.get(jnp.dtype(k_pool.dtype).name, 8)
    return head_dim % 128 == 0 and page_size % sub == 0


def _kernel_body(table_ref, limit_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                 acc_ref, m_ref, l_ref, *, page_size, num_blocks,
                 compute_dtype, quantized, ks_ref=None, vs_ref=None):
    """One grid step: slot ``s``, kv-head ``h``, page block ``b``.

    The BlockSpec index_maps already routed ``k_ref``/``v_ref`` (and the
    scale refs) at PHYSICAL page ``table[s, b]`` — in here the block is
    simply "this slot's pages ``b*page .. (b+1)*page`` in logical
    order".  Scratch (``acc``/``m``/``l``) persists across the innermost
    grid dim, carrying the online softmax over the slot's pages.
    """
    s, b = pl.program_id(0), pl.program_id(2)
    limit = limit_ref[s]

    @pl.when(b == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(b * page_size < limit)
    def _step():
        k = k_ref[0, 0]                                   # (page, Dh)
        v = v_ref[0, 0]
        if quantized:  # fused dequant: int8 payload * f32 scale, in-reg
            k = _dequant(k, ks_ref[0, 0], compute_dtype)
            v = _dequant(v, vs_ref[0, 0], compute_dtype)
        q = q_ref[0, 0].astype(k.dtype)                   # (R, Dh)
        Dh = q.shape[-1]
        s_blk = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) / np.sqrt(Dh)  # (R, page)
        # Logical-position mask: page-tail junk / NULL-page trash /
        # partial last page all sit at positions >= limit.
        col = b * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s_blk.shape, 1)
        s_blk = jnp.where(col < limit, s_blk, NEG_INF)

        m_prev = m_ref[:, :1]                             # (R, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new)                        # (R, page) f32
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # _cache_attend discipline: weights cast to V's dtype before the
        # dot, f32 MXU accumulation.
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (R, Dh)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(b == num_blocks - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        empty = l <= 0.0          # fully-masked row (limit == 0)
        l_safe = jnp.where(empty, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(empty, NEG_INF, m + jnp.log(l_safe))  # (R, 1)
        lse_ref[0, 0] = jnp.broadcast_to(lse.reshape(1, -1),
                                         lse_ref.shape[2:])


def _pallas_paged_attend(qg, k_pool, v_pool, k_scale, v_scale, table,
                         limit, compute_dtype):
    S, Hkv, R, Dh = qg.shape
    _, _, ps, _ = k_pool.shape
    max_pages = table.shape[1]
    quantized = k_scale is not None

    # Pad query rows up to a sublane tile so tiny G (or G*W) widths
    # still compile on real hardware; padded rows cost only VPU lanes
    # and are sliced off below.
    R_pad = max(8, -(-R // 8) * 8)
    if R_pad != R:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, R_pad - R), (0, 0)))

    kernel = functools.partial(
        _kernel_body, page_size=ps, num_blocks=max_pages,
        compute_dtype=compute_dtype, quantized=quantized)
    if quantized:
        def kernel(t, lim, q, k, v, ks, vs, o, lse, acc, m, l):  # noqa: F811
            return _kernel_body(
                t, lim, q, k, v, o, lse, acc, m, l, page_size=ps,
                num_blocks=max_pages, compute_dtype=compute_dtype,
                quantized=True, ks_ref=ks, vs_ref=vs)

    # Scalar-prefetch args (table, limit) arrive as trailing index_map
    # operands: the K/V specs use the TABLE ROW to stream the referenced
    # physical page — the "gather" is just block routing.
    q_spec = pl.BlockSpec((1, 1, R_pad, Dh), lambda s, h, b, t, lim: (s, h, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, ps, Dh),
                           lambda s, h, b, t, lim: (t[s, b], h, 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qg, k_pool, v_pool]
    if quantized:
        sc_spec = pl.BlockSpec((1, 1, ps),
                               lambda s, h, b, t, lim: (t[s, b], h, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    o_shape = jax.ShapeDtypeStruct((S, Hkv, R_pad, Dh), jnp.float32)
    # lse rides a sublane-replicated (…, 8, R) layout, like the flash
    # kernel's — callers read row 0.
    lse_shape = jax.ShapeDtypeStruct((S, Hkv, 8, R_pad), jnp.float32)
    out_specs = [
        pl.BlockSpec((1, 1, R_pad, Dh), lambda s, h, b, t, lim: (s, h, 0, 0)),
        pl.BlockSpec((1, 1, 8, R_pad), lambda s, h, b, t, lim: (s, h, 0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, Hkv, max_pages),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((R_pad, Dh), jnp.float32),    # acc
            pltpu.VMEM((R_pad, 128), jnp.float32),   # running max
            pltpu.VMEM((R_pad, 128), jnp.float32),   # running sum
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[o_shape, lse_shape],
        interpret=use_interpret(),
    )(table.astype(jnp.int32), limit.astype(jnp.int32), *operands)
    return o[:, :, :R, :], lse[:, :, 0, :R]


def paged_attend_reference(qg, k_pool, v_pool, k_scale, v_scale, table,
                           limit, *, compute_dtype=None):
    """Pure-JAX reference for :func:`paged_attend` — gather, dequant,
    masked softmax — mirroring the unfused decode path's op-for-op
    rounding (``kv_dequantize``'s f32 contract, ``_cache_attend``'s
    stored-dtype dots with f32 accumulation, normalize-then-cast
    weights).  Used for shapes the TPU tiling cannot serve and as the
    oracle in tests."""
    S, Hkv, R, Dh = qg.shape
    max_pages = table.shape[1]
    ps = k_pool.shape[2]
    if compute_dtype is None:
        compute_dtype = k_pool.dtype

    def gather(pool_l):                       # (P,Hkv,ps,Dh) -> logical
        g = pool_l[table]                     # (S, max_pages, Hkv, ps, Dh)
        return jnp.moveaxis(g, 1, 2).reshape(S, Hkv, max_pages * ps, Dh)

    if k_scale is not None:
        def gather_sc(scale_l):
            g = scale_l[table]
            return jnp.moveaxis(g, 1, 2).reshape(S, Hkv, max_pages * ps)

        kg = _dequant(gather(k_pool), gather_sc(k_scale), compute_dtype)
        vg = _dequant(gather(v_pool), gather_sc(v_scale), compute_dtype)
    else:
        kg = gather(k_pool)
        vg = gather(v_pool)
    s = jnp.einsum("skrd,sktd->skrt", qg.astype(kg.dtype), kg,
                   preferred_element_type=jnp.float32) / np.sqrt(Dh)
    T = max_pages * ps
    vis = (jax.lax.broadcasted_iota(jnp.int32, (T,), 0)[None, :]
           < limit[:, None])                  # (S, T)
    s = jnp.where(vis[:, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    any_vis = (limit > 0)[:, None, None, None]
    p = jnp.exp(s - jnp.where(any_vis, m, 0.0))
    l = jnp.sum(p, axis=-1, keepdims=True)
    w = jnp.where(any_vis, p / l, 0.0)
    o = jnp.einsum("skrt,sktd->skrd", w.astype(vg.dtype), vg,
                   preferred_element_type=jnp.float32)
    lse = jnp.where(any_vis[..., 0], m[..., 0] + jnp.log(l[..., 0]),
                    NEG_INF)
    return o, lse


def paged_attend(qg, k_pool, v_pool, k_scale, v_scale, table, limit, *,
                 compute_dtype=None):
    """Fused decode attention directly against a paged KV pool.

    Args:
      qg: ``(S, H_kv, R, Dh)`` grouped queries — ``R = G`` (GQA group)
        for a one-token decode tick, ``R = G * W`` for a W-wide VERIFY
        window (rows ``g * W + j``).
      k_pool / v_pool: ONE layer's pool, ``(P, H_kv, page, Dh)`` in the
        stored dtype (f32 / bf16 / int8).
      k_scale / v_scale: ``(P, H_kv, page)`` f32 per-vector scales for
        int8 pools, else ``None``.
      table: ``(S, max_pages)`` int32 physical page ids (host data —
        any allocation pattern, one executable).
      limit: ``(S,)`` int32 — attend logical positions ``< limit[s]``
        (``pos + 1`` for decode-at-``pos``, ``pos`` for VERIFY over
        committed pages; ``0`` masks a slot entirely).
      compute_dtype: dtype int8 pages are dequantized TO (the model's
        ``cfg.dtype``); ignored for unquantized pools, which are dotted
        in their stored dtype per ``_cache_attend``.

    Returns:
      ``(o, lse)``: ``o`` ``(S, H_kv, R, Dh)`` f32 attention output
      (zeros for fully-masked rows), ``lse`` ``(S, H_kv, R)`` f32 per-
      row logsumexp of the masked scores (``NEG_INF`` when fully
      masked) for cross-source combining.
    """
    ps, Dh = k_pool.shape[2], k_pool.shape[3]
    if compute_dtype is None:
        compute_dtype = k_pool.dtype
    if not kernel_supported(k_pool, ps, Dh):
        return paged_attend_reference(
            qg, k_pool, v_pool, k_scale, v_scale, table, limit,
            compute_dtype=compute_dtype)
    return _pallas_paged_attend(qg, k_pool, v_pool, k_scale, v_scale,
                                table, limit, compute_dtype)
