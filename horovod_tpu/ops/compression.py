"""Gradient compression applied before allreduce.

Reference: ``horovod/tensorflow/compression.py:20-75`` and
``horovod/torch/compression.py`` — an abstract ``Compressor`` with
``none`` and ``fp16`` instances hung off a ``Compression`` namespace.

TPU note: bfloat16 is the hardware-native 16-bit type (MXU ingests bf16 at
full rate and its exponent range makes loss-scaling unnecessary), so
``Compression.bf16`` is provided and recommended; ``Compression.fp16`` keeps
reference parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Compressor:
    """Interface: ``compress(tree) -> (tree, ctx)``; ``decompress(tree, ctx)``."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (``Compression.none``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def _cast_compressor(dtype):
    class _Cast(Compressor):
        @staticmethod
        def compress(tensor):
            ctx = jax.tree_util.tree_map(lambda t: jnp.asarray(t).dtype, tensor)
            out = jax.tree_util.tree_map(
                lambda t: jnp.asarray(t).astype(dtype)
                if jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating)
                else t,
                tensor,
            )
            return out, ctx

        @staticmethod
        def decompress(tensor, ctx):
            return jax.tree_util.tree_map(
                lambda t, d: jnp.asarray(t).astype(d), tensor, ctx
            )

    return _Cast


FP16Compressor = _cast_compressor(jnp.float16)
BF16Compressor = _cast_compressor(jnp.bfloat16)


class Compression:
    """Namespace of compressor singletons (reference
    ``tensorflow/compression.py:66-75``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
