"""Tensor fusion: batch many small tensors into few large collectives.

Reference: the fusion buffer + response fusion
(``common/fusion_buffer_manager.cc:21-50``, ``Controller::FuseResponses``
``controller.cc:631-752``), with the 64 MB default threshold set at
``operations.cc:408`` and the atomic-unit rounding at
``controller.cc:349-367``.

TPU re-design: there is no persistent byte buffer or memcpy in/out.  Fusion
is a *functional transform*: leaves are grouped by dtype into buckets of at
most ``threshold`` bytes, each bucket is flattened and concatenated, ONE
collective runs per bucket, and results are split and reshaped back.  Under
``jit``, XLA fuses the concat/split into the collective's prologue/epilogue,
so the data movement the reference paid memcpys for disappears into the
compiled program.  The bucket size is the main autotuning knob
(:mod:`horovod_tpu.autotune`).
"""

from __future__ import annotations

import os
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024  # bytes; operations.cc:408


def fusion_threshold_bytes() -> int:
    """Threshold resolution order: live autotuner (the tuned value, applied
    each sample window) → HOROVOD_FUSION_THRESHOLD env → 64 MB default.
    In-graph callers bucket with this value at TRACE time, so the tuned
    threshold affects steps built after tuning; the eager path consults it
    on every call."""
    from horovod_tpu import basics

    if basics.is_initialized():
        at = getattr(basics._ctx(), "autotuner", None)
        if at is not None:
            return int(at.fusion_threshold)
    v = os.environ.get("HOROVOD_FUSION_THRESHOLD")
    if v:
        return int(v)
    return DEFAULT_FUSION_THRESHOLD


def make_buckets(
    leaves: Sequence[Any], threshold: int
) -> List[List[int]]:
    """Greedy dtype-grouped bucketing; returns lists of leaf indices.

    Keeps submission order within a dtype group (the reference fuses
    responses in controller arrival order).
    """
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        a = jnp.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        by_dtype.setdefault(jnp.asarray(a).dtype if not hasattr(a, "dtype") else a.dtype, []).append(i)
    buckets: List[List[int]] = []
    for _, idxs in by_dtype.items():
        cur: List[int] = []
        cur_bytes = 0
        for i in idxs:
            a = leaves[i]
            nbytes = int(np.prod(np.asarray(a).shape if not hasattr(a, "shape") else a.shape) or 1) * jnp.asarray(a).dtype.itemsize if not hasattr(a, "nbytes") else int(a.nbytes)
            if cur and cur_bytes + nbytes > threshold:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def _flatten_bucket(leaves: Sequence[Any]):
    flats = [jnp.ravel(jnp.asarray(l)) for l in leaves]
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0]


def _split_bucket(buf, leaves: Sequence[Any]):
    out = []
    off = 0
    for l in leaves:
        a = jnp.asarray(l)
        n = int(np.prod(a.shape)) if a.ndim else 1
        out.append(jnp.reshape(buf[off : off + n], a.shape))
        off += n
    return out


def fused_allreduce_tree(tree, op=None, *, axis_name=None, threshold: int = None):
    """In-graph fused allreduce of a pytree: bucket → concat → one
    ``psum`` per bucket → split.  The JAX-transform equivalent of the
    reference's fusion buffer cycle
    (``MemcpyInFusionBuffer → ncclAllReduce → MemcpyOutFusionBuffer``,
    ``ops/nccl_operations.cc:122-156``)."""
    from horovod_tpu.ops import collectives as C

    op = op or C.Average
    threshold = threshold if threshold is not None else fusion_threshold_bytes()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    buckets = make_buckets(leaves, threshold)
    out_leaves: List[Any] = [None] * len(leaves)
    for idxs in buckets:
        group = [leaves[i] for i in idxs]
        buf = _flatten_bucket(group)
        red = C.allreduce(buf, op, axis_name=axis_name)
        for i, piece in zip(idxs, _split_bucket(red, group)):
            out_leaves[i] = piece
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def fused_eager_allreduce(tensors: Sequence[Any], op=None) -> List[Any]:
    """Eager grouped allreduce through host-side buckets — the eager
    analogue of one fusion-buffer cycle."""
    from horovod_tpu.ops import collectives as C

    op = op or C.Average
    arrs = [np.asarray(t) for t in tensors]
    if not arrs:
        return []
    threshold = fusion_threshold_bytes()
    buckets = make_buckets(arrs, threshold)
    out: List[Any] = [None] * len(arrs)
    for idxs in buckets:
        group = [arrs[i] for i in idxs]
        flat = np.concatenate([a.ravel() for a in group]) if len(group) > 1 else group[0].ravel()
        red = C._eager_allreduce(flat, op, None, None)
        off = 0
        for i in idxs:
            n = arrs[i].size
            out[i] = red[off : off + n].reshape(arrs[i].shape)
            off += n
    return out
