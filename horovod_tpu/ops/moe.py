"""Expert-parallel mixture-of-experts dispatch (Switch-style top-1 with
capacity factor).

The reference framework has no MoE (this is a beyond-reference extension,
like ring attention); the design follows the Switch-Transformer /
Mesh-TensorFlow dispatch discipline re-thought for XLA static shapes:

* **Route**: top-1 expert per token from a softmax router (f32 for the
  argmax/gate numerics).
* **Capacity**: each expert accepts at most ``cap = ceil(capacity_factor
  * T / E)`` tokens; a token's slot is its running position within its
  expert (cumsum over the static token order), tokens past the capacity
  are DROPPED (their gate is zeroed, so only the residual passes — the
  standard Switch training behavior).  Static shapes throughout: the
  dispatch buffer is ``(E, cap, D)`` with one scratch slot that dropped
  tokens scatter into.
* **Exchange**: under ``shard_map`` with an ``ep`` axis bound, the
  dispatch buffer ``(E, cap, D) = (ep, E_local, cap, D)`` rides ONE
  ``lax.all_to_all`` so each device receives exactly the tokens routed
  to its RESIDENT experts (and only those); expert FFNs run as one
  batched einsum over the local expert axis (MXU-friendly); a reverse
  ``all_to_all`` returns expert outputs to the token owners.  Compute
  per device is ``T_local * FFN`` — flat in E — unlike dense dispatch's
  ``E * T * FFN``, and the ``ep`` axis now shards COMPUTE, not just
  storage.
* **Combine**: gather each token's slot from the returned buffer and
  scale by its gate probability.

Gradients flow through the scatter/gather and both all_to_alls (their
VJPs are the transpose gather/scatter and the reverse all_to_all), so
``jax.grad`` of a loss through :func:`switch_moe` is exact — verified
against the dense-dispatch oracle in ``tests/test_moe.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def capacity(T: int, n_experts: int, capacity_factor: float) -> int:
    """Per-expert token capacity: ``ceil(cf * T / E)`` clamped to [1, T]."""
    cap = int(np.ceil(capacity_factor * T / n_experts))
    return max(1, min(cap, T))


def _cumsum_dispatch(xt, e_star, E: int, cap: int):
    """Original dispatch: f32 one-hot running-position cumsum + row
    scatter into the (E, cap+1, D) buffer.  Kept as the oracle and the
    fallback; the sort dispatch below is the fast path on TPU."""
    T, D = xt.shape
    onehot = jax.nn.one_hot(e_star, E, dtype=jnp.float32)
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1).astype(jnp.int32) - 1
    keep = pos < cap
    slot = jnp.where(keep, pos, cap)  # dropped tokens -> scratch slot
    buf = jnp.zeros((E, cap + 1, D), xt.dtype).at[e_star, slot].set(xt)
    frac = onehot.mean(axis=0)
    return buf[:, :cap], jnp.where(keep, pos, cap), keep, frac


def _sort_dispatch(xt, e_star, E: int, cap: int):
    """Sort-based dispatch: argsort tokens by expert (stable — original
    arrival order within an expert is preserved, so drop semantics match
    the cumsum oracle exactly), then build the (E, cap, D) buffer with
    ONE row gather (row (e, c) = sorted token ``starts[e] + c``).  No
    row scatter and no (T, E) f32 cumsum — the two ops that made the
    cumsum dispatch eat the MFU on chip (only 1-D int sorts/scatters
    remain, plus the unavoidable row gathers whose VJPs are the
    scatter-adds autodiff inserts in the backward)."""
    T, D = xt.shape
    e32 = e_star.astype(jnp.int32)
    order = jnp.argsort(e32, stable=True)
    es = e32[order]
    eye = jnp.arange(E, dtype=e32.dtype)
    starts = jnp.searchsorted(es, eye).astype(jnp.int32)
    counts = (jnp.searchsorted(es, eye, side="right").astype(jnp.int32)
              - starts)
    pos_sorted = jnp.arange(T, dtype=jnp.int32) - starts[es]
    xs = xt[order]
    rowidx = starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None]
    rowvalid = jnp.arange(cap, dtype=jnp.int32)[None] < counts[:, None]
    buf = jnp.where(rowvalid[..., None],
                    xs[jnp.clip(rowidx, 0, T - 1)],
                    jnp.zeros((), xt.dtype))
    # Per-original-token slot: unsort the within-expert positions (1-D
    # int32 scatter — cheap, unlike a (T, D) row scatter).
    slot = jnp.zeros((T,), jnp.int32).at[order].set(pos_sorted)
    keep = slot < cap
    frac = counts.astype(jnp.float32) / T
    return buf, jnp.where(keep, slot, cap), keep, frac


def switch_moe(
    x,
    router,
    w_gate,
    w_up,
    w_down,
    *,
    capacity_factor: float = 2.0,
    axis_name: Optional[str] = None,
    return_aux: bool = False,
    dispatch: str = "sort",
):
    """Top-1 expert-parallel MoE FFN.

    Args:
      x: ``(..., D)`` tokens (leading dims flattened internally).
      router: ``(D, E)`` router weights, REPLICATED (E = global experts).
      w_gate, w_up: ``(E_local, D, F)`` — this device's resident experts
        (the global stack sharded over ``axis_name``; pass the full
        ``(E, D, F)`` stack when ``axis_name`` is None).
      w_down: ``(E_local, F, D)``.
      capacity_factor: per-expert capacity multiplier (see module doc).
      axis_name: the ``ep`` mesh axis bound by ``shard_map``, or None for
        single-device dispatch (still sparse: each token computes ONE
        expert's FFN).
      return_aux: also return the Switch load-balancing auxiliary loss
        ``E * sum_e fraction_e * mean_prob_e`` (1.0 at perfect balance).
      dispatch: ``"sort"`` (argsort + gathers — the fast path on TPU,
        where row scatters and the (T, E) f32 running-position cumsum
        dominate the dispatch cost) or ``"cumsum"`` (the original
        formulation, kept as the oracle).  Identical results including
        drop patterns: the stable sort preserves each expert's original
        arrival order.

    Returns:
      ``y`` shaped like ``x`` (add it to the residual stream), or
      ``(y, aux_loss)`` with ``return_aux``.
    """
    lead, D = x.shape[:-1], x.shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    ep = lax.axis_size(axis_name) if axis_name is not None else 1
    E_loc = w_gate.shape[0]
    E = E_loc * ep
    if router.shape[1] != E:
        raise ValueError(
            f"router routes over {router.shape[1]} experts but the expert "
            f"stack provides {E_loc} local x {ep} devices = {E} "
            "(sharded weights outside shard_map, or axis_name missing?)")
    if dispatch not in ("sort", "cumsum"):
        raise ValueError(f"unknown dispatch {dispatch!r}; "
                         "expected 'sort' or 'cumsum'")
    dt = x.dtype

    logits = xt.astype(jnp.float32) @ router.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    e_star = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.max(probs, axis=-1)  # (T,)

    cap = capacity(T, E, capacity_factor)
    dispatch_fn = _sort_dispatch if dispatch == "sort" else _cumsum_dispatch
    buf, slot, keep, frac = dispatch_fn(xt, e_star, E, cap)
    gate = jnp.where(keep, gate, 0.0)

    if ep > 1:
        # (ep * E_loc, cap, D): chunk e goes to device e // E_loc.  After
        # the exchange, block i holds source i's tokens for MY experts.
        recv = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        toks = (recv.reshape(ep, E_loc, cap, D)
                .transpose(1, 0, 2, 3)
                .reshape(E_loc, ep * cap, D))
    else:
        toks = buf  # (E, cap, D)

    # Resident experts only: one batched einsum over the local expert
    # axis — (E_loc, tokens, D) x (E_loc, D, F) on the MXU.
    g = jnp.einsum("ecd,edf->ecf", toks, w_gate.astype(dt))
    u = jnp.einsum("ecd,edf->ecf", toks, w_up.astype(dt))
    out = jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, w_down.astype(dt))

    if ep > 1:
        # Reverse exchange: piece j = outputs for source j's tokens;
        # the concat arrives back in GLOBAL expert-major order.
        out = (out.reshape(E_loc, ep, cap, D)
               .transpose(1, 0, 2, 3)
               .reshape(E, cap, D))
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=True)

    y = out[e_star, jnp.minimum(slot, cap - 1)]  # (T, D); dropped gate=0
    y = (y * gate[:, None].astype(dt)).reshape(*lead, D)
    if not return_aux:
        return y
    pbar = probs.mean(axis=0)
    aux = E * jnp.sum(frac * pbar)  # frac = routed fraction (pre-drop)
    return y, aux


def dropless_moe(x, router, w_gate, w_up, w_down):
    """Top-1 MoE FFN, DROPLESS, via grouped (ragged) matmuls: sort tokens
    by expert, run the three FFN matmuls as ``lax.ragged_dot`` with the
    per-expert group sizes, unsort, scale by the gate.

    Exact (== the dense dispatch oracle — no capacity, nothing dropped)
    at 1/E of dense FLOPs: each token touches only its own expert's
    weights, and the grouped matmuls stay MXU-shaped.  This is the
    SERVING dispatch: prefill uses it so an E-expert model ingests a
    prompt at 1× FFN cost instead of dense's E× (training keeps
    capacity-factor :func:`switch_moe` — fixed shapes and the one
    all_to_all each way under ``ep``; per-step decode keeps dense — a
    handful of tokens).  Single-device or tp-sharded; no ep axis
    (ragged group sizes are data-dependent, which an all_to_all cannot
    carry statically)."""
    lead, D = x.shape[:-1], x.shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    E = router.shape[1]
    dt = x.dtype

    logits = xt.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    e_star = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    gate = jnp.max(probs, axis=-1)

    order = jnp.argsort(e_star, stable=True)
    xs = xt[order]
    es = e_star[order]
    eye = jnp.arange(E, dtype=jnp.int32)
    counts = (jnp.searchsorted(es, eye, side="right")
              - jnp.searchsorted(es, eye)).astype(jnp.int32)

    g = lax.ragged_dot(xs, w_gate.astype(dt), counts)
    u = lax.ragged_dot(xs, w_up.astype(dt), counts)
    y_s = lax.ragged_dot(jax.nn.silu(g) * u, w_down.astype(dt), counts)

    inv = jnp.argsort(order)  # unsort permutation
    y = y_s[inv] * gate[:, None].astype(dt)
    return y.reshape(*lead, D)
