"""Expert-parallel mixture-of-experts dispatch (Switch-style top-1 with
capacity factor).

The reference framework has no MoE (this is a beyond-reference extension,
like ring attention); the design follows the Switch-Transformer /
Mesh-TensorFlow dispatch discipline re-thought for XLA static shapes:

* **Route**: top-1 expert per token from a softmax router (f32 for the
  argmax/gate numerics).
* **Capacity**: each expert accepts at most ``cap = ceil(capacity_factor
  * T / E)`` tokens; a token's slot is its running position within its
  expert (cumsum over the static token order), tokens past the capacity
  are DROPPED (their gate is zeroed, so only the residual passes — the
  standard Switch training behavior).  Static shapes throughout: the
  dispatch buffer is ``(E, cap, D)`` with one scratch slot that dropped
  tokens scatter into.
* **Exchange**: under ``shard_map`` with an ``ep`` axis bound, the
  dispatch buffer ``(E, cap, D) = (ep, E_local, cap, D)`` rides ONE
  ``lax.all_to_all`` so each device receives exactly the tokens routed
  to its RESIDENT experts (and only those); expert FFNs run as one
  batched einsum over the local expert axis (MXU-friendly); a reverse
  ``all_to_all`` returns expert outputs to the token owners.  Compute
  per device is ``T_local * FFN`` — flat in E — unlike dense dispatch's
  ``E * T * FFN``, and the ``ep`` axis now shards COMPUTE, not just
  storage.
* **Combine**: gather each token's slot from the returned buffer and
  scale by its gate probability.

Gradients flow through the scatter/gather and both all_to_alls (their
VJPs are the transpose gather/scatter and the reverse all_to_all), so
``jax.grad`` of a loss through :func:`switch_moe` is exact — verified
against the dense-dispatch oracle in ``tests/test_moe.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def capacity(T: int, n_experts: int, capacity_factor: float) -> int:
    """Per-expert token capacity: ``ceil(cf * T / E)`` clamped to [1, T]."""
    cap = int(np.ceil(capacity_factor * T / n_experts))
    return max(1, min(cap, T))


def switch_moe(
    x,
    router,
    w_gate,
    w_up,
    w_down,
    *,
    capacity_factor: float = 2.0,
    axis_name: Optional[str] = None,
    return_aux: bool = False,
):
    """Top-1 expert-parallel MoE FFN.

    Args:
      x: ``(..., D)`` tokens (leading dims flattened internally).
      router: ``(D, E)`` router weights, REPLICATED (E = global experts).
      w_gate, w_up: ``(E_local, D, F)`` — this device's resident experts
        (the global stack sharded over ``axis_name``; pass the full
        ``(E, D, F)`` stack when ``axis_name`` is None).
      w_down: ``(E_local, F, D)``.
      capacity_factor: per-expert capacity multiplier (see module doc).
      axis_name: the ``ep`` mesh axis bound by ``shard_map``, or None for
        single-device dispatch (still sparse: each token computes ONE
        expert's FFN).
      return_aux: also return the Switch load-balancing auxiliary loss
        ``E * sum_e fraction_e * mean_prob_e`` (1.0 at perfect balance).

    Returns:
      ``y`` shaped like ``x`` (add it to the residual stream), or
      ``(y, aux_loss)`` with ``return_aux``.
    """
    lead, D = x.shape[:-1], x.shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    ep = lax.axis_size(axis_name) if axis_name is not None else 1
    E_loc = w_gate.shape[0]
    E = E_loc * ep
    if router.shape[1] != E:
        raise ValueError(
            f"router routes over {router.shape[1]} experts but the expert "
            f"stack provides {E_loc} local x {ep} devices = {E} "
            "(sharded weights outside shard_map, or axis_name missing?)")
    dt = x.dtype

    logits = xt.astype(jnp.float32) @ router.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    e_star = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.max(probs, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(e_star, E, dtype=jnp.float32)

    cap = capacity(T, E, capacity_factor)
    # Position of each token within its expert's arrivals (static order).
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1).astype(jnp.int32) - 1
    keep = pos < cap
    gate = jnp.where(keep, gate, 0.0)
    slot = jnp.where(keep, pos, cap)  # dropped tokens -> scratch slot

    # Scatter tokens into the (E, cap, D) dispatch buffer (+1 scratch).
    buf = jnp.zeros((E, cap + 1, D), dt).at[e_star, slot].set(xt)
    buf = buf[:, :cap]

    if ep > 1:
        # (ep * E_loc, cap, D): chunk e goes to device e // E_loc.  After
        # the exchange, block i holds source i's tokens for MY experts.
        recv = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        toks = (recv.reshape(ep, E_loc, cap, D)
                .transpose(1, 0, 2, 3)
                .reshape(E_loc, ep * cap, D))
    else:
        toks = buf  # (E, cap, D)

    # Resident experts only: one batched einsum over the local expert
    # axis — (E_loc, tokens, D) x (E_loc, D, F) on the MXU.
    g = jnp.einsum("ecd,edf->ecf", toks, w_gate.astype(dt))
    u = jnp.einsum("ecd,edf->ecf", toks, w_up.astype(dt))
    out = jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, w_down.astype(dt))

    if ep > 1:
        # Reverse exchange: piece j = outputs for source j's tokens;
        # the concat arrives back in GLOBAL expert-major order.
        out = (out.reshape(E_loc, ep, cap, D)
               .transpose(1, 0, 2, 3)
               .reshape(E, cap, D))
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=True)

    y = out[e_star, jnp.minimum(slot, cap - 1)]  # (T, D); dropped gate=0
    y = (y * gate[:, None].astype(dt)).reshape(*lead, D)
    if not return_aux:
        return y
    frac = onehot.mean(axis=0)  # routed fraction per expert (pre-drop)
    pbar = probs.mean(axis=0)
    aux = E * jnp.sum(frac * pbar)
    return y, aux
