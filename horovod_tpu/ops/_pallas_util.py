"""Shared Pallas TPU plumbing for the fused kernels.

Every Pallas kernel in this package (flash attention in
:mod:`~horovod_tpu.ops.attention`, the fused paged-attention decode
kernel in :mod:`~horovod_tpu.ops.paged_attention`) needs the same four
pieces of scaffolding, factored here so they cannot drift apart:

* the NON-FATAL import guard — pallas is TPU/GPU-oriented and a CPU-only
  install must still import the package (``_PALLAS`` is the availability
  flag every entry point checks before tiling);
* :func:`use_interpret` — run the kernel under the Pallas interpreter on
  non-TPU backends, so the tier-1 CPU suite exercises the REAL kernel
  body (not just the XLA fallback) with identical semantics;
* :func:`out_sds` — ``ShapeDtypeStruct`` that inherits an operand's
  varying-over-mesh-axes type, so a ``pallas_call`` type-checks inside
  ``shard_map`` (ring attention runs per sequence shard, the paged
  decode kernel per tp head shard);
* :func:`smem_spec` / :func:`scalar_operand` — the cached SMEM
  ``BlockSpec`` for scalar operands and the pvary-matched (1,) int32
  wrapper that keeps a traced scalar compatible with sharded tensor
  operands.

``NEG_INF`` is the shared finite mask value: ``exp(NEG_INF - x) == 0``
for any real ``x``, and fully-masked rows report ``NEG_INF`` as their
logsumexp so they vanish in cross-block/cross-source merges.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["NEG_INF", "PALLAS_AVAILABLE", "pl", "pltpu", "use_interpret",
           "out_sds", "scalar_operand", "smem_spec"]

NEG_INF = -1e30  # finite mask value: exp(NEG_INF - anything_real) == 0

try:  # pallas is TPU/GPU-oriented; keep import failure non-fatal on CPU
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    pl = None  # type: ignore[assignment]
    pltpu = None  # type: ignore[assignment]
    PALLAS_AVAILABLE = False


def use_interpret() -> bool:
    """Run kernels under the Pallas interpreter off-TPU: the tier-1 CPU
    suite then exercises the real kernel bodies, not just fallbacks."""
    return jax.default_backend() != "tpu"


def out_sds(shape, dtype, like):
    """ShapeDtypeStruct that inherits ``like``'s varying-over-mesh-axes
    type, so the pallas_call type-checks inside ``shard_map``."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def scalar_operand(value, like):
    """(1,) int32 SMEM operand for the kernels (0 when ``value`` is
    None), pvary-matched to ``like``'s varying-over-axis type."""
    arr = jnp.asarray(0 if value is None else value, jnp.int32).reshape(1)
    try:
        vma = set(jax.typeof(like).vma)
        have = set(jax.typeof(arr).vma)
    except Exception:
        return arr
    need = tuple(vma - have)
    if need:  # match the tensor operands' varying-over-axis type
        arr = jax.lax.pvary(arr, need)
    return arr


_SMEM_SPEC = None


def smem_spec():
    """The cached whole-array SMEM BlockSpec for scalar operands."""
    global _SMEM_SPEC
    if _SMEM_SPEC is None:
        _SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)
    return _SMEM_SPEC
