"""Sparse (row-indexed) gradient reduction for embedding-shaped grads.

The reference reduces ``tf.IndexedSlices`` gradients by ALLGATHERING the
(indices, values) pairs instead of allreducing the dense tensor
(``horovod/tensorflow/__init__.py:74-89`` ``_allreduce_cond`` →
``allgather(values)/allgather(indices)``): an embedding step touches a
few hundred rows of a multi-million-row table, so gathering the touched
rows moves orders of magnitude fewer bytes.

JAX has no IndexedSlices — a token-lookup VJP produces a DENSE zero-
filled table — so the sparse contract here is ROW-SPARSITY DETECTION on
the eager path: extract the nonzero rows, allgather ``(indices,
values)`` (the eager allgatherv supports per-process variable row
counts), and scatter-add back to dense.  Results match the dense eager
allreduce bit-for-bit semantics (chip-weighted ``Sum``/``Average`` —
docs/concepts.md) with wire bytes proportional to the touched rows.

Under ``jit`` gradients are traced (static shapes — no dynamic nnz), so
the sparse route only engages eagerly; traced leaves fall back to the
dense in-graph collective, mirroring the reference where the sparse
path lives in the eager tape.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from horovod_tpu import basics
from horovod_tpu.ops import collectives as C


def sparse_allreduce(
    grad,
    op: str = C.Average,
    *,
    name: Optional[str] = None,
    return_stats: bool = False,
):
    """Reduce a row-sparse dense gradient by allgathering touched rows.

    Args:
      grad: ``(V, ...)`` host array, zero except in the rows a step
        touched (an embedding-lookup gradient).
      op: ``Sum`` or ``Average`` — same chip-weighted semantics as the
        eager dense ``allreduce``.
      name: collective name prefix (two wire ops: ``<name>.idx`` /
        ``<name>.val``).
      return_stats: also return ``{"sparse_bytes", "dense_bytes",
        "rows", "total_rows"}`` for wire accounting.

    Returns:
      The dense reduced gradient (== ``allreduce(grad, op)``), or
      ``(grad, stats)`` with ``return_stats``.
    """
    if op not in (C.Sum, C.Average):
        raise ValueError(
            f"sparse_allreduce supports Sum/Average, got {op!r}")
    g = np.asarray(grad)
    if g.ndim < 1:
        raise ValueError("sparse_allreduce needs a row dimension")
    flat = g.reshape(g.shape[0], -1)
    # A rank may legitimately touch ZERO rows this step (an all-zero
    # embedding grad): rows is then (0,) and vals (0, D), and both ride
    # the same allgatherv round as the peers' nonzero contributions (the
    # eager allgather negotiates per-process first dims, 0 included), so
    # no rank ever skips the collective and deadlocks its peers.
    rows = np.flatnonzero(np.any(flat != 0, axis=1)).astype(np.int32)
    vals = np.ascontiguousarray(flat[rows])

    name = name or "sparse.grad"
    # Chip-weighted eager contract (docs/concepts.md): each process's
    # contribution counts once per ITS OWN local chip — weight BEFORE the
    # gather, because processes may drive different chip counts (the
    # dense eager path weights per process the same way,
    # collectives.py _process_local_counts).
    weighted = vals * np.asarray(basics.local_size(), vals.dtype)
    all_rows = np.asarray(C.allgather(rows, name=f"{name}.idx"))
    all_vals = np.asarray(C.allgather(weighted, name=f"{name}.val"))

    out = np.zeros_like(flat)
    np.add.at(out, all_rows, all_vals)
    if op == C.Average:
        out /= basics.size()  # global chip count
    out = out.reshape(g.shape).astype(g.dtype)
    if not return_stats:
        return out
    stats = {
        "rows": int(rows.size),
        "total_rows": int(g.shape[0]),
        "sparse_bytes": int(rows.nbytes + vals.nbytes),
        "dense_bytes": int(g.nbytes),
    }
    return out, stats


def split_sparse_leaves(grads, sparse_keys: Tuple[str, ...]):
    """Partition a gradient pytree into (dense_tree, [(path, leaf)])
    where a leaf is routed sparse when its tree path contains any of
    ``sparse_keys`` as a substring (e.g. ``("embed",)``) and it is an
    eager (non-traced) array.  The dense tree keeps ``None`` at sparse
    positions for reassembly via :func:`merge_sparse_leaves`."""
    import jax

    paths_leaves = jax.tree_util.tree_leaves_with_path(grads)
    treedef = jax.tree_util.tree_structure(grads)
    dense, sparse = [], []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if (not isinstance(leaf, jax.core.Tracer)
                and any(k in key for k in sparse_keys)
                and np.ndim(leaf) >= 1):
            sparse.append((len(dense), key, leaf))
            dense.append(None)
        else:
            dense.append(leaf)
    return treedef, dense, sparse


def merge_sparse_leaves(treedef, dense, reduced_sparse):
    import jax

    leaves = list(dense)
    for i, leaf in reduced_sparse:
        leaves[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)
