from horovod_tpu.ops import collectives, compression, fusion, adasum  # noqa: F401
from horovod_tpu.ops.collectives import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    alltoall,
    broadcast,
    grouped_allreduce,
    reducescatter,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
