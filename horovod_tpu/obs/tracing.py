"""Per-request tracing: Dapper-style trace ids through the serving
stack, exported onto the ONE process timeline.

Horovod's flagship debugging tool was its timeline — per-tensor
lifecycle events on one time axis (``native/src/timeline.{h,cc}``).
This module extends that idea to the serving path: every request gets a
**trace id** minted at ``ServingServer`` ingress (or accepted from an
``X-Trace-Id`` header) and carried through ``Scheduler.Request`` →
prefill admission → per-tick decode → retirement, so "where did request
X spend its 900 ms" has an answer:

* a :class:`RequestTrace` rides the request and is stamped at each
  stage boundary; its :meth:`~RequestTrace.breakdown` (queue wait,
  prefill, decode, host-sync lag) is returned in the ``/generate``
  response and appended to a structured JSONL event log;
* the :class:`Tracer` renders request spans, engine tick-phase spans,
  and instant events (XLA compiles, engine restarts, watchdog stalls,
  elastic re-rendezvous) through the existing
  :class:`horovod_tpu.timeline.Timeline` writer thread — so ONE
  Perfetto-loadable file interleaves training-step spans and serving
  request spans on one time axis.

Tracing is **off by default**.  When off, the per-request cost is one
module-global read per hot-path site plus a 16-hex-char id mint at
submit; timestamps for the breakdown are stamped regardless (a handful
of ``time.monotonic()`` calls per request — the breakdown is part of
the ``/generate`` response contract, tracing or not).  When on, each
tick adds three queue puts (bounded, drop-on-full — the timeline's
writer decoupling) and each request retirement one JSONL line.

All timestamps are ``time.monotonic()`` seconds — the same clock the
timeline uses (``monotonic_ns / 1e3`` microseconds), so serving spans
land on the same axis as training spans.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

__all__ = [
    "TRACE_ID_HEADER", "PARENT_SPAN_HEADER", "SAMPLED_HEADER",
    "SPAN_EVENT_TYPES", "RETAIN_EVENT_TYPES", "RequestTrace", "Tracer",
    "SpanSampling", "SpanRecorder",
    "mint_trace_id", "valid_trace_id", "mint_span_id", "valid_span_id",
    "head_sampled", "propagation_from_headers",
    "start", "stop", "get", "activate", "deactivate",
    "start_spans", "stop_spans", "spans", "activate_spans",
    "deactivate_spans",
    "instant", "record_compile",
]

TRACE_ID_HEADER = "X-Trace-Id"

#: Cross-process span parentage (docs/observability.md "Distributed
#: tracing"): the router stamps each proxy attempt's span id here, so
#: the replica's request span nests under the attempt that carried it.
#: Only honored alongside a VALID ``X-Trace-Id`` — a parent span on a
#: freshly minted trace would be a dangling (or spoofed) edge.
PARENT_SPAN_HEADER = "X-Parent-Span"

#: Tail-sampling override: ``X-Trace-Sampled: 1`` forces full-detail
#: span retention for this request.  The router sets it on failover /
#: resume re-dispatches — the downstream share of an interesting trace
#: must not be tail-dropped by a replica that saw nothing unusual.
SAMPLED_HEADER = "X-Trace-Sampled"

#: The typed span-event vocabulary.  Events are the autopsy's edges —
#: why a request hopped processes or lost work — and keeping the set
#: closed keeps the collector and the docs honest.
SPAN_EVENT_TYPES = frozenset({
    "retry",           # router retried the request on another replica
    "failover",        # a replica died at the connection level mid-request
    "eviction",        # paged-cache preemption took this request's slot
    "engine_restart",  # supervised engine restart interrupted the request
    "resume",          # the request continued from journaled state
    "spec_fallback",   # adaptive control disabled speculation on the slot
})

#: The FAILURE-CLASS subset whose presence forces full-detail span
#: retention past tail sampling.  ``spec_fallback`` is deliberately
#: excluded: under a sustained low-acceptance speculative workload the
#: adaptive controller fires it routinely, and "routine at peak load"
#: is exactly what tail sampling must not retain — the event record
#: itself is still written (flushed immediately) and still shows in
#: the breakdown, it just doesn't drag the tick detail with it.
RETAIN_EVENT_TYPES = frozenset({
    "retry", "failover", "eviction", "engine_restart", "resume",
})

_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id."""
    return uuid.uuid4().hex[:16]


def valid_trace_id(s) -> bool:
    """True if ``s`` is acceptable as a caller-supplied trace id
    (1-64 chars of ``[A-Za-z0-9._-]``) — anything else is replaced with
    a minted id rather than echoed into logs and trace files."""
    return isinstance(s, str) and bool(_TRACE_ID_RE.match(s))


def mint_span_id() -> str:
    """A fresh 16-hex-char span id."""
    return uuid.uuid4().hex[:16]


def valid_span_id(s) -> bool:
    """Same grammar as trace ids; an invalid ``X-Parent-Span`` is
    DROPPED (the span becomes a root), never echoed into streams."""
    return isinstance(s, str) and bool(_TRACE_ID_RE.match(s))


def propagation_from_headers(headers) -> Tuple[str, Optional[str],
                                               bool]:
    """THE ingress trust rule, single-sourced for every HTTP front
    (replica server and router alike): returns ``(trace_id,
    parent_span, sampled)``.  A valid ``X-Trace-Id`` is accepted,
    anything else replaced with a minted id; and ``X-Parent-Span`` /
    ``X-Trace-Sampled`` are honored ONLY alongside that valid id — a
    parent on a freshly minted trace would be a dangling (or spoofed)
    edge, and a forced-retention flag from an untraced caller is not
    trusted.  ``headers`` is any mapping with ``.get`` (http.server's
    message object qualifies)."""
    hdr = headers.get(TRACE_ID_HEADER)
    valid = valid_trace_id(hdr)
    trace_id = hdr if valid else mint_trace_id()
    parent = headers.get(PARENT_SPAN_HEADER)
    parent = parent if (valid and valid_span_id(parent)) else None
    sampled = valid and headers.get(SAMPLED_HEADER) == "1"
    return trace_id, parent, sampled


def head_sampled(trace_id: str, rate: float) -> bool:
    """Deterministic head sampling: hash the trace id into [0, 1) and
    compare against ``rate``.  Every process holding the same trace id
    reaches the same verdict with no coordination — a head-sampled
    trace is retained END TO END or not at all, never half a tree."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = int(hashlib.md5(trace_id.encode()).hexdigest()[:8], 16)
    return h / float(0xFFFFFFFF) < rate


class RequestTrace:
    """Per-request timing record, stamped as the request moves through
    the stack (all instants ``time.monotonic()`` seconds):

    * ``submitted_at`` — scheduler enqueue (``Scheduler.submit``);
    * ``admitted_at`` — taken from the queue into a prefill batch;
    * ``first_token_at`` — prefill logits fetched (TTFT instant);
    * ``finished_at`` — future resolved (tokens OR typed error);
    * ``decode_ticks`` — decode ticks that emitted a token to this
      request; ``host_sync_lag`` — dispatch→host-fetch latency of the
      latest such tick (with the overlapped pipeline this is the
      one-tick lag made visible);
    * ``finish`` / ``error`` — finish_reason or exception type name.

    Span identity (docs/observability.md "Distributed tracing"):
    ``span_id`` names this request's span in the cross-process tree,
    ``parent_span_id`` is the upstream caller's span (the router's
    proxy-attempt span, via ``X-Parent-Span``), ``sampled`` forces
    full-detail tail-sampling retention, ``events`` collects typed
    span events (resume, eviction, …) and ``ticks`` buffers per-tick
    detail ``(dispatched_at, fetched_at, tokens)`` tuples — written
    out only if the trace survives tail sampling.
    """

    __slots__ = ("trace_id", "submitted_at", "admitted_at",
                 "first_token_at", "finished_at", "slot", "decode_ticks",
                 "tokens", "host_sync_lag", "finish", "error",
                 "span_id", "parent_span_id", "sampled", "events",
                 "ticks", "ticks_overflow")

    #: hard cap on buffered per-tick tuples (memory bound per request)
    MAX_TICKS = 4096

    def __init__(self, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id or mint_trace_id()
        self.submitted_at: Optional[float] = None
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.slot: Optional[int] = None
        self.decode_ticks: int = 0
        self.tokens: int = 0
        self.host_sync_lag: Optional[float] = None
        self.finish: Optional[str] = None
        self.error: Optional[str] = None
        self.span_id: str = mint_span_id()
        self.parent_span_id: Optional[str] = parent_span_id
        self.sampled: bool = False
        self.events: List[Tuple[str, float, Optional[Dict]]] = []
        self.ticks: List[Tuple[float, float, int]] = []
        # ticks seen past the MAX_TICKS buffer cap — never buffered,
        # but COUNTED so drop markers stay honest for long generations
        self.ticks_overflow: int = 0

    def breakdown(self, now: Optional[float] = None) -> Dict:
        """The timing breakdown the ``/generate`` response carries.
        Safe at any stage: missing stamps yield None fields, an
        unfinished request is measured up to ``now``."""
        end = self.finished_at
        if end is None:
            end = now if now is not None else time.monotonic()

        def span(a, b):
            return round(b - a, 6) if a is not None and b is not None \
                else None

        first_wait_end = self.admitted_at if self.admitted_at is not None \
            else end
        events = [
            {"type": k, "t_s": round(t - self.submitted_at, 6)
             if self.submitted_at is not None else None}
            for k, t, _ in self.events]
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            **({"events": events} if events else {}),
            "queue_wait_s": span(self.submitted_at, first_wait_end),
            "prefill_s": span(self.admitted_at, self.first_token_at),
            "decode_s": span(self.first_token_at, end),
            "decode_ticks": self.decode_ticks,
            "tokens": self.tokens,
            "host_sync_lag_s": round(self.host_sync_lag, 6)
            if self.host_sync_lag is not None else None,
            "total_s": span(self.submitted_at, end),
            "finish": self.finish if self.finish is not None else self.error,
        }


class Tracer:
    """Render request spans, tick-phase spans, instants, and a JSONL
    event log through a :class:`horovod_tpu.timeline.Timeline`.

    Thread-safe: resolution can come from the engine thread, the
    watchdog thread, or an HTTP handler — the timeline queue and a JSONL
    lock serialize everything.  Perfetto layout: tick-phase spans on one
    synthetic thread row, request spans on one row per cache slot (so
    concurrent requests never overlap on a track)."""

    TICK_TID = 90           # engine tick-phase row
    QUEUE_TID = 199         # requests rejected/resolved before admission
    SLOT_TID_BASE = 200     # + slot index
    TICK_BATCH = 128        # tick-phase events buffered per queue put

    def __init__(self, timeline, jsonl_path: Optional[str] = None):
        self._tl = timeline
        self._own_timeline = False
        if jsonl_path:
            from horovod_tpu.timeline import expand_rank_path

            jsonl_path = expand_rank_path(jsonl_path)
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._jsonl_lock = threading.Lock()
        self.jsonl_path = jsonl_path
        self._named_tids = set()
        self._tid_lock = threading.Lock()
        # Tick-phase events are the hot emitter (3 per decode tick):
        # buffer them locally and hand the timeline ONE batch per
        # TICK_BATCH events — a per-event queue put wakes the writer
        # thread every time, and those context switches (not the dict
        # builds) are what would show up in steady-state decode tok/s.
        self._tick_buf: list = []
        self._tick_lock = threading.Lock()
        self._name_tid(self.TICK_TID, "serving: engine ticks")
        self._name_tid(self.QUEUE_TID, "serving: queue")

    # -- timeline emission -------------------------------------------------

    def _name_tid(self, tid: int, name: str) -> None:
        with self._tid_lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        self._tl.thread_name(tid, name)

    def instant(self, name: str, args: Optional[Dict] = None) -> None:
        self._tl.instant(name, args)

    def tick_phase(self, name: str, start_s: float, dur_s: float) -> None:
        """One engine tick phase (dispatch / device wait / host) as a
        complete span on the tick row.  Hot path: append one TUPLE —
        event dicts are built (and the writer woken) only once per
        TICK_BATCH at flush, so the steady-state decode loop pays
        nanoseconds, not queue wakeups."""
        with self._tick_lock:
            self._tick_buf.append((name, start_s, dur_s))
            if len(self._tick_buf) < self.TICK_BATCH:
                return
            batch, self._tick_buf = self._tick_buf, []
        self._flush_ticks(batch)

    def _flush_ticks(self, batch: list) -> None:
        pid, tid = self._tl.pid, self.TICK_TID
        self._tl.emit_batch([
            {"name": name, "cat": "serving.tick", "ph": "X",
             "ts": start_s * 1e6, "dur": max(dur_s, 0.0) * 1e6,
             "pid": pid, "tid": tid}
            for name, start_s, dur_s in batch])

    def flush(self) -> None:
        """Hand any buffered tick-phase events to the writer."""
        with self._tick_lock:
            batch, self._tick_buf = self._tick_buf, []
        if batch:
            self._flush_ticks(batch)

    def request_done(self, tr: RequestTrace) -> None:
        """A request resolved: emit its span (with nested
        queue/prefill/decode phases) and append the JSONL record."""
        b = tr.breakdown()
        if tr.slot is not None:
            tid = self.SLOT_TID_BASE + tr.slot
            self._name_tid(tid, f"serving: slot {tr.slot}")
        else:
            tid = self.QUEUE_TID
        start, end = tr.submitted_at, tr.finished_at
        if start is not None and end is not None:
            self._tl.complete(f"request {tr.trace_id}", start, end - start,
                              category="serving.request", tid=tid, args=b)
            for phase, a, z in (
                    ("queue", tr.submitted_at, tr.admitted_at),
                    ("prefill", tr.admitted_at, tr.first_token_at),
                    ("decode", tr.first_token_at, tr.finished_at)):
                if a is not None and z is not None and z >= a:
                    self._tl.complete(phase, a, z - a,
                                      category="serving.request", tid=tid)
        self.log_event({"event": "request", "wall_time": time.time(), **b})

    # -- structured log ----------------------------------------------------

    def log_event(self, record: Dict) -> None:
        if self._jsonl is None:
            return
        line = json.dumps(record)
        with self._jsonl_lock:
            self._jsonl.write(line + "\n")
            self._jsonl.flush()

    def close(self) -> None:
        self.flush()
        if self._jsonl is not None:
            with self._jsonl_lock:
                self._jsonl.close()
                self._jsonl = None


# -- cross-process spans (docs/observability.md "Distributed tracing") -------


@dataclasses.dataclass(frozen=True)
class SpanSampling:
    """Tail-sampling policy for per-request span DETAIL (phase + tick
    spans).  Attempt-level span records (start/finish/events) are
    always written — the start line must hit the stream before a
    SIGKILL can land for the autopsy to exist at all, and they cost a
    few lines per request; the per-tick detail is what scales with
    tokens and gets sampled.

    A trace keeps its detail when it ERRORS, carries a typed event
    (failover/resume/eviction/…), was FORCED by the ``X-Trace-Sampled``
    header, ran longer than ``latency_threshold_s``, or falls in the
    deterministic ``head_rate`` hash sample (same verdict in every
    process — see :func:`head_sampled`).  Everything else keeps only
    the breakdown already on the finish record."""

    latency_threshold_s: float = 1.0
    head_rate: float = 0.0
    max_tick_spans: int = 512


class SpanRecorder:
    """Append structured spans to a per-process JSONL stream.

    One recorder per process; every line is flushed as written (same
    SIGKILL-durability contract as the request journal), so a killed
    process leaves behind exactly the spans it had started plus every
    typed event up to the kill instant — which is what the collector
    (:mod:`horovod_tpu.obs.trace_store`) renders as an UNFINISHED span
    in the autopsy tree.

    Line vocabulary (``k`` discriminates):

    * ``anchor`` — process identity + a ``(monotonic, wall)`` clock
      pair.  All span timestamps are monotonic seconds; the collector
      uses the anchor to place every process on one wall-clock axis.
    * ``s`` — span start: id, parent, trace, name, t0.  Durable.
    * ``e`` — typed event (:data:`SPAN_EVENT_TYPES`) on a span.  Durable.
    * ``f`` — span finish: t1, status, attrs (the request breakdown
      rides here), and the retention verdict.
    * ``d`` — one DETAIL span (phase or tick), written only for
      retained traces, at finish time.
    * ``x`` — tail-drop marker: how many detail spans were discarded.

    Thread-safe; all writes serialize on one lock.  Failures never
    propagate — spans must not fail serving."""

    def __init__(self, path: str, *, proc: Optional[str] = None,
                 role: str = "process",
                 sampling: Optional[SpanSampling] = None):
        from horovod_tpu.timeline import expand_rank_path

        self.path = expand_rank_path(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self.proc = proc or f"pid{os.getpid()}"
        self.role = role
        self.sampling = sampling or SpanSampling()
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")
        # Plain ints for benchmark/test introspection; the registry
        # families below are the operational view of the same counts.
        self.n_finished = 0
        self.n_retained = 0
        self.n_dropped = 0
        self._m = _span_metrics()
        self._write({"k": "anchor", "proc": self.proc, "role": self.role,
                     "pid": os.getpid(), "mono": time.monotonic(),
                     "wall": time.time()})

    # -- primitives --------------------------------------------------------

    def _write(self, obj: Dict) -> None:
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(json.dumps(obj, separators=(",", ":"))
                              + "\n")
                self._f.flush()
            except (OSError, ValueError):  # pragma: no cover - disk
                pass

    def begin(self, name: str, trace_id: str, *,
              parent: Optional[str] = None,
              span_id: Optional[str] = None,
              t0: Optional[float] = None,
              attrs: Optional[Dict] = None) -> str:
        """Open a span (written immediately); returns its id."""
        sid = span_id or mint_span_id()
        rec = {"k": "s", "id": sid, "trace": trace_id, "name": name,
               "proc": self.proc,
               "t0": t0 if t0 is not None else time.monotonic()}
        if parent:
            rec["parent"] = parent
        if attrs:
            rec["a"] = attrs
        self._write(rec)
        if self._m is not None:
            self._m.spans.inc()
        return sid

    def event(self, trace_id: str, span_id: Optional[str], etype: str,
              attrs: Optional[Dict] = None,
              t: Optional[float] = None) -> None:
        """One typed event (written immediately).  Unknown types raise
        — the vocabulary is closed so autopsies and docs stay in sync."""
        if etype not in SPAN_EVENT_TYPES:
            raise ValueError(f"unknown span event type {etype!r} "
                             f"(know {sorted(SPAN_EVENT_TYPES)})")
        rec = {"k": "e", "trace": trace_id, "type": etype,
               "proc": self.proc,
               "t": t if t is not None else time.monotonic()}
        if span_id:
            rec["span"] = span_id
        if attrs:
            rec["a"] = attrs
        self._write(rec)
        if self._m is not None:
            self._m.events.labels(type=etype).inc()

    def finish(self, span_id: str, *, t1: Optional[float] = None,
               status: str = "ok",
               attrs: Optional[Dict] = None) -> None:
        rec = {"k": "f", "id": span_id, "proc": self.proc,
               "t1": t1 if t1 is not None else time.monotonic(),
               "status": status}
        if attrs:
            rec["a"] = attrs
        self._write(rec)

    # -- request integration ----------------------------------------------

    def request_begin(self, tr: "RequestTrace", name: str = "generate",
                      attrs: Optional[Dict] = None) -> None:
        """Open the request span for ``tr`` (engine submit); the span
        id was minted with the trace, the parent came from
        ``X-Parent-Span``."""
        self.begin(name, tr.trace_id, parent=tr.parent_span_id,
                   span_id=tr.span_id,
                   t0=tr.submitted_at, attrs=attrs)

    def request_event(self, tr: "RequestTrace", etype: str,
                      attrs: Optional[Dict] = None) -> None:
        """Typed event on a request's span: recorded on the trace (for
        the retention verdict and the response breakdown) AND written
        to the stream immediately (durability)."""
        t = time.monotonic()
        tr.events.append((etype, t, attrs))
        self.event(tr.trace_id, tr.span_id, etype, attrs=attrs, t=t)

    def retention(self, tr: "RequestTrace") -> Optional[str]:
        """Why this trace keeps its detail spans, or None (tail-drop)."""
        if tr.error is not None:
            return "error"
        if tr.sampled:
            return "forced"
        if any(k in RETAIN_EVENT_TYPES for k, _, _ in tr.events):
            return "event"
        if (tr.submitted_at is not None and tr.finished_at is not None
                and tr.finished_at - tr.submitted_at
                > self.sampling.latency_threshold_s):
            return "latency"
        if head_sampled(tr.trace_id, self.sampling.head_rate):
            return "head"
        return None

    def request_done(self, tr: "RequestTrace") -> None:
        """Resolution: apply the tail-sampling verdict, write the
        retained detail (phase spans + per-tick spans) or the drop
        marker, then the finish record carrying the breakdown."""
        reason = self.retention(tr)
        # Counters under the lock: resolution can come from the engine
        # thread, the watchdog, or an HTTP handler concurrently.
        with self._lock:
            self.n_finished += 1
            if reason is not None:
                self.n_retained += 1
            else:
                self.n_dropped += 1
        if self._m is not None:
            self._m.requests.inc()
        if reason is not None:
            if self._m is not None:
                self._m.retained.labels(reason=reason).inc()
            for phase, a, z in (
                    ("queue", tr.submitted_at, tr.admitted_at),
                    ("prefill", tr.admitted_at, tr.first_token_at),
                    ("decode", tr.first_token_at, tr.finished_at)):
                if a is not None and z is not None and z >= a:
                    self._write({"k": "d", "trace": tr.trace_id,
                                 "parent": tr.span_id, "proc": self.proc,
                                 "name": phase, "t0": a, "t1": z})
            cap = self.sampling.max_tick_spans
            for t0, t1, n in tr.ticks[:cap]:
                self._write({"k": "d", "trace": tr.trace_id,
                             "parent": tr.span_id, "proc": self.proc,
                             "name": "tick", "t0": t0, "t1": t1,
                             "a": {"tokens": n}})
            # overflow past the buffer cap counts as shed detail too —
            # the drop marker must account for EVERY tick span that
            # did not reach the stream, not just the buffered tail
            shed = max(len(tr.ticks) - cap, 0) + tr.ticks_overflow
            if shed:
                self._write({"k": "x", "trace": tr.trace_id,
                             "span": tr.span_id, "proc": self.proc,
                             "n": shed, "why": "max_tick_spans"})
        else:
            if self._m is not None:
                self._m.dropped.inc()
            if tr.ticks or tr.ticks_overflow:
                self._write({"k": "x", "trace": tr.trace_id,
                             "span": tr.span_id, "proc": self.proc,
                             "n": len(tr.ticks) + tr.ticks_overflow,
                             "why": "tail"})
        b = tr.breakdown()
        b["proc"] = self.proc
        if reason is not None:
            b["retained"] = reason
        self.finish(tr.span_id, t1=tr.finished_at,
                    status=("error:" + tr.error) if tr.error is not None
                    else "ok", attrs=b)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None


_span_metrics_ns = None


def _span_metrics():
    """The ``trace_*`` families in the default registry (created once,
    shared by every recorder this process opens)."""
    global _span_metrics_ns
    if _span_metrics_ns is not None:
        return _span_metrics_ns
    try:
        from horovod_tpu.obs.registry import default_registry

        r = default_registry()

        class _NS:
            spans = r.counter(
                "trace_spans_total",
                "span start records written to the span stream",
                exist_ok=True)
            requests = r.counter(
                "trace_requests_total",
                "request spans finalized (retained + tail-dropped)",
                exist_ok=True)
            retained = r.counter(
                "trace_retained_total",
                "request spans that kept full detail, by reason",
                labels=("reason",), exist_ok=True)
            dropped = r.counter(
                "trace_dropped_total",
                "request spans whose detail was tail-dropped",
                exist_ok=True)
            events = r.counter(
                "trace_events_total",
                "typed span events recorded", labels=("type",),
                exist_ok=True)

        _span_metrics_ns = _NS()
    except Exception:  # pragma: no cover - metrics must not break spans
        _span_metrics_ns = None
    return _span_metrics_ns


_spans: Optional[SpanRecorder] = None


def start_spans(path: str, *, proc: Optional[str] = None,
                role: str = "process",
                sampling: Optional[SpanSampling] = None) -> SpanRecorder:
    """Open the process-wide span recorder (``%r`` rank substitution
    accepted in ``path``).  One per process; the engine, server, and
    router all pick it up via :func:`spans`."""
    global _spans
    if _spans is not None:
        raise ValueError("span recording already started")
    rec = SpanRecorder(path, proc=proc, role=role, sampling=sampling)
    _spans = rec
    return rec


def stop_spans() -> None:
    global _spans
    rec, _spans = _spans, None
    if rec is not None:
        rec.close()


def spans() -> Optional[SpanRecorder]:
    """The active span recorder, or None (the hot-path check — one
    global read)."""
    return _spans


def activate_spans(rec: Optional[SpanRecorder]
                   ) -> Optional[SpanRecorder]:
    """Swap the active recorder without touching its file — the A/B
    seam for overhead benchmarks.  Returns the previous one."""
    global _spans
    prev, _spans = _spans, rec
    return prev


def deactivate_spans() -> Optional[SpanRecorder]:
    return activate_spans(None)


# -- module-global tracer lifecycle ------------------------------------------

_tracer: Optional[Tracer] = None


def start(path: Optional[str] = None,
          jsonl_path: Optional[str] = None) -> Tracer:
    """Start request tracing.  Attaches to the already-active process
    timeline when there is one (``HOROVOD_TIMELINE`` /
    ``start_timeline``) so serving and training share one trace file;
    otherwise starts a timeline at ``path``.  Both paths accept the
    ``%r`` rank substitution (docs/timeline.md) so multi-process runs
    don't clobber each other's files."""
    global _tracer
    if _tracer is not None:
        raise ValueError("tracing already started")
    from horovod_tpu import timeline as TL

    tl = TL.get()
    own = False
    if tl is None:
        if not path:
            raise ValueError(
                "no active timeline to attach to; pass a trace path")
        tl = TL.start_timeline(path)
        own = True
    t = Tracer(tl, jsonl_path=jsonl_path)
    t._own_timeline = own
    _tracer = t
    return t


def stop() -> None:
    """Stop tracing; closes the timeline only if :func:`start` opened
    it (an attached training timeline keeps recording)."""
    global _tracer
    t, _tracer = _tracer, None
    if t is None:
        return
    t.close()
    if t._own_timeline:
        from horovod_tpu import timeline as TL

        TL.stop_timeline()


def get() -> Optional[Tracer]:
    """The active tracer, or None (the hot-path check — one global
    read)."""
    return _tracer


def activate(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Swap the active tracer in/out without touching its files —
    the A/B seam for overhead benchmarks and tests.  Returns the
    previously active tracer."""
    global _tracer
    prev, _tracer = _tracer, tracer
    return prev


def deactivate() -> Optional[Tracer]:
    """Detach the active tracer (returned) leaving its files open;
    re-attach with :func:`activate`."""
    return activate(None)


# -- cross-cutting event helpers ---------------------------------------------

def instant(name: str, args: Optional[Dict] = None) -> None:
    """Emit an instant event onto whatever is recording: the active
    tracer's timeline, else the process timeline, else nothing.  Used
    by the engine (restarts, stalls) and the elastic layer
    (re-rendezvous) so lifecycle landmarks land in the trace whichever
    subsystem opened it."""
    tp = _tracer
    if tp is not None:
        tp.instant(name, args)
        return
    from horovod_tpu import timeline as TL

    tl = TL.get()
    if tl is not None:
        tl.instant(name, args)


def record_compile(fn: str) -> None:
    """Count an XLA trace/compile event (``xla_compiles_total{fn=...}``
    in the default registry) and mark it as an instant on the active
    trace.  Call from inside a traced-function body — it runs exactly
    once per (re)compilation."""
    try:
        from horovod_tpu.obs.registry import training_metrics

        training_metrics().compiles.labels(fn=fn).inc()
    except Exception:  # pragma: no cover - registry must never break jit
        pass
    instant("xla_compile", {"fn": fn})
