"""Per-request tracing: Dapper-style trace ids through the serving
stack, exported onto the ONE process timeline.

Horovod's flagship debugging tool was its timeline — per-tensor
lifecycle events on one time axis (``native/src/timeline.{h,cc}``).
This module extends that idea to the serving path: every request gets a
**trace id** minted at ``ServingServer`` ingress (or accepted from an
``X-Trace-Id`` header) and carried through ``Scheduler.Request`` →
prefill admission → per-tick decode → retirement, so "where did request
X spend its 900 ms" has an answer:

* a :class:`RequestTrace` rides the request and is stamped at each
  stage boundary; its :meth:`~RequestTrace.breakdown` (queue wait,
  prefill, decode, host-sync lag) is returned in the ``/generate``
  response and appended to a structured JSONL event log;
* the :class:`Tracer` renders request spans, engine tick-phase spans,
  and instant events (XLA compiles, engine restarts, watchdog stalls,
  elastic re-rendezvous) through the existing
  :class:`horovod_tpu.timeline.Timeline` writer thread — so ONE
  Perfetto-loadable file interleaves training-step spans and serving
  request spans on one time axis.

Tracing is **off by default**.  When off, the per-request cost is one
module-global read per hot-path site plus a 16-hex-char id mint at
submit; timestamps for the breakdown are stamped regardless (a handful
of ``time.monotonic()`` calls per request — the breakdown is part of
the ``/generate`` response contract, tracing or not).  When on, each
tick adds three queue puts (bounded, drop-on-full — the timeline's
writer decoupling) and each request retirement one JSONL line.

All timestamps are ``time.monotonic()`` seconds — the same clock the
timeline uses (``monotonic_ns / 1e3`` microseconds), so serving spans
land on the same axis as training spans.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from typing import Dict, Optional

__all__ = [
    "TRACE_ID_HEADER", "RequestTrace", "Tracer",
    "mint_trace_id", "valid_trace_id",
    "start", "stop", "get", "activate", "deactivate",
    "instant", "record_compile",
]

TRACE_ID_HEADER = "X-Trace-Id"

_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id."""
    return uuid.uuid4().hex[:16]


def valid_trace_id(s) -> bool:
    """True if ``s`` is acceptable as a caller-supplied trace id
    (1-64 chars of ``[A-Za-z0-9._-]``) — anything else is replaced with
    a minted id rather than echoed into logs and trace files."""
    return isinstance(s, str) and bool(_TRACE_ID_RE.match(s))


class RequestTrace:
    """Per-request timing record, stamped as the request moves through
    the stack (all instants ``time.monotonic()`` seconds):

    * ``submitted_at`` — scheduler enqueue (``Scheduler.submit``);
    * ``admitted_at`` — taken from the queue into a prefill batch;
    * ``first_token_at`` — prefill logits fetched (TTFT instant);
    * ``finished_at`` — future resolved (tokens OR typed error);
    * ``decode_ticks`` — decode ticks that emitted a token to this
      request; ``host_sync_lag`` — dispatch→host-fetch latency of the
      latest such tick (with the overlapped pipeline this is the
      one-tick lag made visible);
    * ``finish`` / ``error`` — finish_reason or exception type name.
    """

    __slots__ = ("trace_id", "submitted_at", "admitted_at",
                 "first_token_at", "finished_at", "slot", "decode_ticks",
                 "tokens", "host_sync_lag", "finish", "error")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or mint_trace_id()
        self.submitted_at: Optional[float] = None
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.slot: Optional[int] = None
        self.decode_ticks: int = 0
        self.tokens: int = 0
        self.host_sync_lag: Optional[float] = None
        self.finish: Optional[str] = None
        self.error: Optional[str] = None

    def breakdown(self, now: Optional[float] = None) -> Dict:
        """The timing breakdown the ``/generate`` response carries.
        Safe at any stage: missing stamps yield None fields, an
        unfinished request is measured up to ``now``."""
        end = self.finished_at
        if end is None:
            end = now if now is not None else time.monotonic()

        def span(a, b):
            return round(b - a, 6) if a is not None and b is not None \
                else None

        first_wait_end = self.admitted_at if self.admitted_at is not None \
            else end
        return {
            "trace_id": self.trace_id,
            "queue_wait_s": span(self.submitted_at, first_wait_end),
            "prefill_s": span(self.admitted_at, self.first_token_at),
            "decode_s": span(self.first_token_at, end),
            "decode_ticks": self.decode_ticks,
            "tokens": self.tokens,
            "host_sync_lag_s": round(self.host_sync_lag, 6)
            if self.host_sync_lag is not None else None,
            "total_s": span(self.submitted_at, end),
            "finish": self.finish if self.finish is not None else self.error,
        }


class Tracer:
    """Render request spans, tick-phase spans, instants, and a JSONL
    event log through a :class:`horovod_tpu.timeline.Timeline`.

    Thread-safe: resolution can come from the engine thread, the
    watchdog thread, or an HTTP handler — the timeline queue and a JSONL
    lock serialize everything.  Perfetto layout: tick-phase spans on one
    synthetic thread row, request spans on one row per cache slot (so
    concurrent requests never overlap on a track)."""

    TICK_TID = 90           # engine tick-phase row
    QUEUE_TID = 199         # requests rejected/resolved before admission
    SLOT_TID_BASE = 200     # + slot index
    TICK_BATCH = 128        # tick-phase events buffered per queue put

    def __init__(self, timeline, jsonl_path: Optional[str] = None):
        self._tl = timeline
        self._own_timeline = False
        if jsonl_path:
            from horovod_tpu.timeline import expand_rank_path

            jsonl_path = expand_rank_path(jsonl_path)
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._jsonl_lock = threading.Lock()
        self.jsonl_path = jsonl_path
        self._named_tids = set()
        self._tid_lock = threading.Lock()
        # Tick-phase events are the hot emitter (3 per decode tick):
        # buffer them locally and hand the timeline ONE batch per
        # TICK_BATCH events — a per-event queue put wakes the writer
        # thread every time, and those context switches (not the dict
        # builds) are what would show up in steady-state decode tok/s.
        self._tick_buf: list = []
        self._tick_lock = threading.Lock()
        self._name_tid(self.TICK_TID, "serving: engine ticks")
        self._name_tid(self.QUEUE_TID, "serving: queue")

    # -- timeline emission -------------------------------------------------

    def _name_tid(self, tid: int, name: str) -> None:
        with self._tid_lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        self._tl.thread_name(tid, name)

    def instant(self, name: str, args: Optional[Dict] = None) -> None:
        self._tl.instant(name, args)

    def tick_phase(self, name: str, start_s: float, dur_s: float) -> None:
        """One engine tick phase (dispatch / device wait / host) as a
        complete span on the tick row.  Hot path: append one TUPLE —
        event dicts are built (and the writer woken) only once per
        TICK_BATCH at flush, so the steady-state decode loop pays
        nanoseconds, not queue wakeups."""
        with self._tick_lock:
            self._tick_buf.append((name, start_s, dur_s))
            if len(self._tick_buf) < self.TICK_BATCH:
                return
            batch, self._tick_buf = self._tick_buf, []
        self._flush_ticks(batch)

    def _flush_ticks(self, batch: list) -> None:
        pid, tid = self._tl.pid, self.TICK_TID
        self._tl.emit_batch([
            {"name": name, "cat": "serving.tick", "ph": "X",
             "ts": start_s * 1e6, "dur": max(dur_s, 0.0) * 1e6,
             "pid": pid, "tid": tid}
            for name, start_s, dur_s in batch])

    def flush(self) -> None:
        """Hand any buffered tick-phase events to the writer."""
        with self._tick_lock:
            batch, self._tick_buf = self._tick_buf, []
        if batch:
            self._flush_ticks(batch)

    def request_done(self, tr: RequestTrace) -> None:
        """A request resolved: emit its span (with nested
        queue/prefill/decode phases) and append the JSONL record."""
        b = tr.breakdown()
        if tr.slot is not None:
            tid = self.SLOT_TID_BASE + tr.slot
            self._name_tid(tid, f"serving: slot {tr.slot}")
        else:
            tid = self.QUEUE_TID
        start, end = tr.submitted_at, tr.finished_at
        if start is not None and end is not None:
            self._tl.complete(f"request {tr.trace_id}", start, end - start,
                              category="serving.request", tid=tid, args=b)
            for phase, a, z in (
                    ("queue", tr.submitted_at, tr.admitted_at),
                    ("prefill", tr.admitted_at, tr.first_token_at),
                    ("decode", tr.first_token_at, tr.finished_at)):
                if a is not None and z is not None and z >= a:
                    self._tl.complete(phase, a, z - a,
                                      category="serving.request", tid=tid)
        self.log_event({"event": "request", "wall_time": time.time(), **b})

    # -- structured log ----------------------------------------------------

    def log_event(self, record: Dict) -> None:
        if self._jsonl is None:
            return
        line = json.dumps(record)
        with self._jsonl_lock:
            self._jsonl.write(line + "\n")
            self._jsonl.flush()

    def close(self) -> None:
        self.flush()
        if self._jsonl is not None:
            with self._jsonl_lock:
                self._jsonl.close()
                self._jsonl = None


# -- module-global tracer lifecycle ------------------------------------------

_tracer: Optional[Tracer] = None


def start(path: Optional[str] = None,
          jsonl_path: Optional[str] = None) -> Tracer:
    """Start request tracing.  Attaches to the already-active process
    timeline when there is one (``HOROVOD_TIMELINE`` /
    ``start_timeline``) so serving and training share one trace file;
    otherwise starts a timeline at ``path``.  Both paths accept the
    ``%r`` rank substitution (docs/timeline.md) so multi-process runs
    don't clobber each other's files."""
    global _tracer
    if _tracer is not None:
        raise ValueError("tracing already started")
    from horovod_tpu import timeline as TL

    tl = TL.get()
    own = False
    if tl is None:
        if not path:
            raise ValueError(
                "no active timeline to attach to; pass a trace path")
        tl = TL.start_timeline(path)
        own = True
    t = Tracer(tl, jsonl_path=jsonl_path)
    t._own_timeline = own
    _tracer = t
    return t


def stop() -> None:
    """Stop tracing; closes the timeline only if :func:`start` opened
    it (an attached training timeline keeps recording)."""
    global _tracer
    t, _tracer = _tracer, None
    if t is None:
        return
    t.close()
    if t._own_timeline:
        from horovod_tpu import timeline as TL

        TL.stop_timeline()


def get() -> Optional[Tracer]:
    """The active tracer, or None (the hot-path check — one global
    read)."""
    return _tracer


def activate(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Swap the active tracer in/out without touching its files —
    the A/B seam for overhead benchmarks and tests.  Returns the
    previously active tracer."""
    global _tracer
    prev, _tracer = _tracer, tracer
    return prev


def deactivate() -> Optional[Tracer]:
    """Detach the active tracer (returned) leaving its files open;
    re-attach with :func:`activate`."""
    return activate(None)


# -- cross-cutting event helpers ---------------------------------------------

def instant(name: str, args: Optional[Dict] = None) -> None:
    """Emit an instant event onto whatever is recording: the active
    tracer's timeline, else the process timeline, else nothing.  Used
    by the engine (restarts, stalls) and the elastic layer
    (re-rendezvous) so lifecycle landmarks land in the trace whichever
    subsystem opened it."""
    tp = _tracer
    if tp is not None:
        tp.instant(name, args)
        return
    from horovod_tpu import timeline as TL

    tl = TL.get()
    if tl is not None:
        tl.instant(name, args)


def record_compile(fn: str) -> None:
    """Count an XLA trace/compile event (``xla_compiles_total{fn=...}``
    in the default registry) and mark it as an instant on the active
    trace.  Call from inside a traced-function body — it runs exactly
    once per (re)compilation."""
    try:
        from horovod_tpu.obs.registry import training_metrics

        training_metrics().compiles.labels(fn=fn).inc()
    except Exception:  # pragma: no cover - registry must never break jit
        pass
    instant("xla_compile", {"fn": fn})
