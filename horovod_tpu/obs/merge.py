"""Merge per-rank Perfetto/Chrome trace files onto one time axis.

Horovod's timeline was a single cross-worker file by construction (rank
0 wrote everyone's negotiation events); here every rank records its own
host-side timeline (``HOROVOD_TIMELINE`` with the ``%r`` rank
substitution — see docs/timeline.md), so N ranks produce N JSON files
that Perfetto can only show one at a time.  This tool merges them::

    python -m horovod_tpu.obs.merge merged.json rank0.json rank1.json ...
    python -m horovod_tpu.obs.merge merged.json 'trace.rank*.json'

Each input file's ``pid`` values are remapped into a disjoint per-input
range, and a ``process_name`` / ``process_sort_index`` metadata pair is
emitted per input, so the merged file shows ONE labeled process track
per rank — train steps, serving spans, tick phases, and instants from
all ranks on a shared clock.  (Timestamps are ``CLOCK_MONOTONIC``
microseconds: directly comparable for ranks on one host, which is
where multi-process tests and single-host multi-chip jobs live.  For
ranks from different hosts pass ``--align-start`` to re-zero each
input at its earliest event — relative phasing across hosts is then
approximate.)

Truncated inputs (a rank killed before its writer appended the closing
bracket — exactly the ranks worth debugging) are repaired on read.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_trace", "merge_traces", "main"]

# Per-input pid block: input i owns [(i+1)*PID_STRIDE, (i+2)*PID_STRIDE).
PID_STRIDE = 1000

_RANK_RE = re.compile(r"(?:rank|\br)[._-]?(\d+)", re.IGNORECASE)
# The `%r` filename style (tl.0.json ... tl.11.json): bare digits right
# before the final extension are the rank — without this, lexicographic
# glob order would label tl.10.json "rank 2".
_TRAILING_NUM_RE = re.compile(r"(\d+)\.[^.]+$")


def load_trace(path: str) -> List[dict]:
    """Load a Chrome-trace JSON event array, repairing the truncation a
    killed writer leaves behind: a missing ``]``, a trailing comma, or
    a PARTIAL last event (buffered IO means a SIGKILL cuts the file at
    an arbitrary byte — the partial object is dropped back to the last
    complete event boundary)."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        # A rank killed before its writer's first flush leaves a 0-byte
        # file: that is "no events", not a merge-stopping error.
        return []

    def _as_events(data):
        if isinstance(data, dict):  # {"traceEvents": [...]} container
            data = data.get("traceEvents", [])
        if not isinstance(data, list):
            raise ValueError(f"{path}: not a Chrome-trace event array")
        return data

    try:
        return _as_events(json.loads(text))
    except json.JSONDecodeError:
        pass
    body = text.strip().rstrip(",")
    if body.endswith("]"):
        return _as_events(json.loads(body))  # re-raises if hopeless
    try:  # clean truncation: events intact, only the trailer missing
        return _as_events(json.loads(body + "\n]"))
    except json.JSONDecodeError:
        pass
    # Cut back to the last complete event: try each '}' from the end as
    # the final closing brace (an inner brace of a nested args dict
    # fails to parse and the scan continues leftward).
    i = len(body)
    while True:
        i = body.rfind("}", 0, i)
        if i < 0:
            raise ValueError(f"{path}: unrecoverable truncated trace")
        try:
            return _as_events(json.loads(body[:i + 1].rstrip(",") + "\n]"))
        except json.JSONDecodeError:
            continue


def _label_for(path: str, index: int) -> str:
    base = os.path.basename(path)
    m = _RANK_RE.search(base) or _TRAILING_NUM_RE.search(base)
    return f"rank {m.group(1)}" if m else f"rank {index}"


def merge_traces(inputs: List[str], *,
                 labels: Optional[List[str]] = None,
                 align_start: bool = False
                 ) -> Tuple[List[dict], Dict[str, int]]:
    """Merge trace files into one event list.

    Returns ``(events, stats)`` where stats counts events per input.
    Each input gets a disjoint pid block (one distinct Perfetto process
    track per rank) with ``process_name`` metadata, events otherwise
    untouched (same clock) unless ``align_start`` re-zeroes each input
    at its earliest timestamp."""
    merged: List[dict] = []
    stats: Dict[str, int] = {}
    for i, path in enumerate(inputs):
        try:
            events = load_trace(path)
        except (OSError, ValueError) as e:
            # One hopeless input (mid-write garbage, a deleted dead-rank
            # file, an unmatched glob kept as a literal path) must not
            # cost the healthy ranks their merged view.
            print(f"  {path}: skipped ({e})", file=sys.stderr)
            stats[path] = 0
            continue
        label = labels[i] if labels and i < len(labels) \
            else _label_for(path, i)
        base = (i + 1) * PID_STRIDE
        pid_map: Dict[object, int] = {}
        t0 = None
        if align_start:
            ts = [e["ts"] for e in events if "ts" in e]
            t0 = min(ts) if ts else 0.0

        def _pid(orig) -> int:
            new = pid_map.get(orig)
            if new is None:
                new = base + len(pid_map)
                pid_map[orig] = new
                name = label if len(pid_map) == 1 \
                    else f"{label} (pid {orig})"
                merged.append({"name": "process_name", "ph": "M",
                               "pid": new, "args": {"name": name}})
                merged.append({"name": "process_sort_index", "ph": "M",
                               "pid": new, "args": {"sort_index": i}})
            return new

        n = 0
        for ev in events:
            ev = dict(ev)
            ev["pid"] = _pid(ev.get("pid", 0))
            if t0 is not None and "ts" in ev:
                ev["ts"] = ev["ts"] - t0
            merged.append(ev)
            n += 1
        stats[path] = n
    return merged, stats


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.obs.merge",
        description="Merge per-rank timeline JSON files into one "
                    "Perfetto trace with one process track per rank.")
    ap.add_argument("output", help="merged trace path (overwritten)")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank trace files (globs accepted)")
    ap.add_argument("--align-start", action="store_true",
                    help="re-zero each input at its earliest event "
                         "(for ranks from different hosts whose "
                         "monotonic clocks do not share an epoch)")
    args = ap.parse_args(argv)

    paths: List[str] = []
    for pattern in args.inputs:
        hits = sorted(glob.glob(pattern))
        paths.extend(hits if hits else [pattern])
    # De-dup while keeping order; never merge the output into itself.
    seen = set()
    out_abs = os.path.abspath(args.output)
    paths = [p for p in paths
             if os.path.abspath(p) != out_abs
             and not (os.path.abspath(p) in seen
                      or seen.add(os.path.abspath(p)))]
    if not paths:
        ap.error("no input trace files matched")

    events, stats = merge_traces(paths, align_start=args.align_start)
    if not any(stats.values()):
        print("error: no readable trace events in any input; "
              "not writing " + args.output, file=sys.stderr)
        return 1
    with open(args.output, "w") as f:
        json.dump(events, f)
    for path, n in stats.items():
        print(f"  {path}: {n} events")
    print(f"merged {len(paths)} trace(s), {len(events)} events "
          f"-> {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
