"""Cross-rank metric aggregation: merge per-rank registry exports into
one fleet view.

Horovod's cross-rank timeline existed because per-worker views hide
exactly the failures that matter at fleet scale — negotiation stalls
and stragglers (Sergeev & Del Balso, 2018).  The metrics analogue: each
elastic worker publishes its registry's mergeable export
(:meth:`~horovod_tpu.obs.registry.MetricsRegistry.export`) over the
rendezvous KV, and the driver merges them here into ONE scrape target.

Merge semantics, by instrument kind:

* **counters** sum across ranks per label-set (the Prometheus
  federation convention — a fleet-total counter is the only counter
  that means anything);
* **gauges** cannot be summed meaningfully (occupancy, epoch, skew…),
  so every rank's series is kept, re-labeled with ``rank=``/``host=``,
  PLUS a cross-rank roll-up: ``<name>_min`` / ``<name>_median`` /
  ``<name>_max`` synthetic gauges per label-set;
* **histograms** merge bucket-wise — per-bucket counts, sum, and count
  add — which is exact (no quantile-of-quantiles estimation error),
  but REQUIRES identical bucket edges on every rank: a mismatch raises
  the typed :class:`BucketMismatchError` rather than silently
  producing garbage percentiles.

A kind disagreement between ranks (one rank says counter, another says
gauge for the same family — a version-skew smell) raises the typed
:class:`MergeConflictError`.

Percentiles over merged histograms inherit the single-histogram edge
semantics (see :meth:`~horovod_tpu.obs.registry.Histogram.percentile`):
values land on bucket upper edges, and a quantile falling in the +Inf
overflow reports the largest finite edge.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Tuple

from horovod_tpu.obs.registry import (
    Histogram,
    _escape_help,
    _fmt_labels,
    _fmt_value,
)

__all__ = [
    "MergeConflictError", "BucketMismatchError", "FleetAggregate",
    "merge_exports", "merged_histogram",
]


class MergeConflictError(ValueError):
    """Two ranks exported the same family name with different kinds or
    label names — aggregation refuses to guess."""


class BucketMismatchError(MergeConflictError):
    """Two ranks exported the same histogram family with different
    bucket edges; bucket-wise merging would silently mis-bin, so this
    is a typed error instead."""


def merged_histogram(states: List[Dict]) -> Histogram:
    """Bucket-wise merge of :meth:`Histogram.state` dicts into one
    (in-memory) :class:`Histogram` — counts, sum, and count add; edges
    must agree (:class:`BucketMismatchError` otherwise)."""
    if not states:
        raise ValueError("nothing to merge")
    edges = list(states[0]["buckets"])
    h = Histogram(buckets=edges)
    for st in states:
        if list(st["buckets"]) != edges:
            raise BucketMismatchError(
                f"histogram bucket edges differ across ranks: "
                f"{edges} vs {list(st['buckets'])}")
        counts = list(st["counts"])
        if len(counts) != len(edges) + 1:
            raise BucketMismatchError(
                f"histogram has {len(counts)} buckets for "
                f"{len(edges)} edges (expected {len(edges) + 1})")
        for i, c in enumerate(counts):
            h._counts[i] += int(c)
        h._sum += float(st["sum"])
        h._count += int(st["count"])
    return h


def _series_key(labels: Mapping[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _MergedFamily:
    __slots__ = ("kind", "help", "labelnames", "per_rank")

    def __init__(self, kind: str, help: str, labelnames: List[str]):
        self.kind = kind
        self.help = help
        self.labelnames = list(labelnames)
        # series-key -> rank -> scalar value | histogram state
        self.per_rank: Dict[Tuple, Dict[str, object]] = {}


class FleetAggregate:
    """The merged view of many ranks' registry exports.

    Build with :func:`merge_exports`; consume via :meth:`to_prometheus`
    (the fleet scrape body, ``rank``/``host``-labeled) or
    :meth:`snapshot` (the ``/fleet`` JSON view)."""

    def __init__(self, hosts: Optional[Mapping[str, str]] = None):
        self._fams: Dict[str, _MergedFamily] = {}
        self._hosts: Dict[str, str] = dict(hosts or {})
        self.ranks: List[str] = []

    # -- building ----------------------------------------------------------

    def add(self, rank, export: Mapping[str, Dict],
            host: Optional[str] = None) -> None:
        """Fold one rank's registry export in.  ``rank`` becomes the
        ``rank=`` label value; ``host`` (optional) the ``host=``
        label."""
        rank = str(rank)
        if rank not in self.ranks:
            self.ranks.append(rank)
        if host is not None:
            self._hosts[rank] = str(host)
        for name, fam in export.items():
            kind = fam.get("kind")
            labelnames = list(fam.get("labels", ()))
            mf = self._fams.get(name)
            if mf is None:
                mf = self._fams[name] = _MergedFamily(
                    kind, fam.get("help", ""), labelnames)
            elif mf.kind != kind or mf.labelnames != labelnames:
                raise MergeConflictError(
                    f"family {name!r} disagrees across ranks: "
                    f"{mf.kind}{mf.labelnames} vs {kind}{labelnames}")
            for s in fam.get("series", ()):
                key = _series_key(s.get("l", {}))
                slot = mf.per_rank.setdefault(key, {})
                slot[rank] = s["h"] if kind == "histogram" else s["v"]

    # -- consumption -------------------------------------------------------

    def _merged_series(self, mf: _MergedFamily):
        """Yield (series_key, merged_value) where merged_value is the
        summed counter, the merged Histogram, or (for gauges) the
        per-rank dict."""
        for key in sorted(mf.per_rank):
            ranks = mf.per_rank[key]
            if mf.kind == "counter":
                yield key, sum(ranks.values())
            elif mf.kind == "histogram":
                yield key, merged_histogram(
                    [ranks[r] for r in sorted(ranks)])
            else:
                yield key, ranks

    @staticmethod
    def _gauge_rollup(values: List[float]) -> Dict[str, float]:
        import statistics

        vs = [float(v) for v in values]
        return {"min": min(vs), "median": statistics.median(vs),
                "max": max(vs)}

    def snapshot(self) -> Dict:
        """JSON-friendly merged view (the ``/fleet`` ``metrics`` key):
        counters as fleet sums, gauges as ``{per_rank, min, median,
        max}``, histograms as the merged
        :meth:`~horovod_tpu.obs.registry.Histogram.snapshot`."""
        out: Dict = {}
        for name in sorted(self._fams):
            mf = self._fams[name]
            fam_out: Dict = {}
            for key, merged in self._merged_series(mf):
                skey = ",".join(f'{k}="{v}"' for k, v in key) or "_"
                if mf.kind == "counter":
                    fam_out[skey] = merged
                elif mf.kind == "histogram":
                    fam_out[skey] = merged.snapshot()
                else:
                    per_rank = {r: v for r, v in sorted(merged.items())}
                    fam_out[skey] = {
                        "per_rank": per_rank,
                        **self._gauge_rollup(list(per_rank.values())),
                    }
            out[name] = fam_out if mf.labelnames else \
                fam_out.get("_", fam_out)
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of the fleet view:
        counters summed, each gauge series per rank with
        ``rank``/``host`` labels plus ``_min``/``_median``/``_max``
        roll-up gauges, histograms merged bucket-wise."""
        lines: List[str] = []
        for name in sorted(self._fams):
            mf = self._fams[name]
            if mf.kind == "counter":
                self._emit_counter(lines, name, mf)
            elif mf.kind == "histogram":
                self._emit_histogram(lines, name, mf)
            else:
                self._emit_gauge(lines, name, mf)
        return "\n".join(lines) + "\n" if lines else ""

    def _head(self, lines, name, kind, help):
        if help:
            lines.append(f"# HELP {name} {_escape_help(help)}")
        lines.append(f"# TYPE {name} {kind}")

    def _emit_counter(self, lines, name, mf) -> None:
        self._head(lines, name, "counter", mf.help)
        for key, total in self._merged_series(mf):
            labels = _fmt_labels([k for k, _ in key], [v for _, v in key])
            lines.append(f"{name}{labels} {_fmt_value(total)}")

    def _rank_extra(self, rank: str):
        extra = [("rank", rank)]
        host = self._hosts.get(rank)
        if host is not None:
            extra.append(("host", host))
        return extra

    def _emit_gauge(self, lines, name, mf) -> None:
        self._head(lines, name, "gauge", mf.help)
        rollups: List[Tuple[Tuple, Dict[str, float]]] = []
        for key, ranks in self._merged_series(mf):
            names = [k for k, _ in key]
            values = [v for _, v in key]
            for rank in sorted(ranks, key=lambda r: (len(r), r)):
                labels = _fmt_labels(names, values,
                                     extra=self._rank_extra(rank))
                lines.append(f"{name}{labels} {_fmt_value(ranks[rank])}")
            rollups.append((key, self._gauge_rollup(
                list(ranks.values()))))
        for stat in ("min", "median", "max"):
            self._head(lines, f"{name}_{stat}", "gauge",
                       f"Cross-rank {stat} of {name}" if mf.help else "")
            for key, roll in rollups:
                labels = _fmt_labels([k for k, _ in key],
                                     [v for _, v in key])
                lines.append(
                    f"{name}_{stat}{labels} {_fmt_value(roll[stat])}")

    def _emit_histogram(self, lines, name, mf) -> None:
        self._head(lines, name, "histogram", mf.help)
        for key, h in self._merged_series(mf):
            names = [k for k, _ in key]
            values = [v for _, v in key]
            labels = _fmt_labels(names, values)
            cum, total, s = h.cumulative()
            for edge, c in zip(h.buckets, cum):
                le = _fmt_labels(names, values, extra=[("le", "%g" % edge)])
                lines.append(f"{name}_bucket{le} {c}")
            le = _fmt_labels(names, values, extra=[("le", "+Inf")])
            lines.append(f"{name}_bucket{le} {total}")
            lines.append(f"{name}_sum{labels} {_fmt_value(s)}")
            lines.append(f"{name}_count{labels} {total}")


def merge_exports(exports: Mapping[object, Mapping[str, Dict]],
                  hosts: Optional[Mapping[object, str]] = None
                  ) -> FleetAggregate:
    """Merge ``{rank: registry.export()}`` into one
    :class:`FleetAggregate` (``hosts`` optionally maps rank →
    hostname for the ``host=`` label)."""
    agg = FleetAggregate(
        hosts={str(k): str(v) for k, v in (hosts or {}).items()})
    for rank in sorted(exports, key=lambda r: (len(str(r)), str(r))):
        agg.add(rank, exports[rank])
    return agg
