"""CLI over the span collector: request autopsies from the terminal.

    # list every trace the span streams know about
    python -m horovod_tpu.obs.trace --spans /tmp/spans --list

    # ASCII tree of one trace (the SIGKILL-failover autopsy view)
    python -m horovod_tpu.obs.trace --spans /tmp/spans 1f0c9a2b40d311ee

    # full autopsy JSON (same payload as the router's GET /trace/<id>)
    python -m horovod_tpu.obs.trace --spans /tmp/spans TRACE --json

    # Perfetto export: one track per process, spans + typed events
    python -m horovod_tpu.obs.trace --spans /tmp/spans TRACE \\
        --perfetto /tmp/trace.json

``--spans`` points at the spans directory every process of one
deployment writes into (``ReplicaSupervisor(span_dir=...)`` + the
router's own recorder); individual stream files or globs work too.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from horovod_tpu.obs.trace_store import TraceStore

__all__ = ["main"]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.obs.trace",
        description="Assemble per-process span streams into one "
                    "cross-process trace tree (ASCII / JSON / Perfetto).")
    ap.add_argument("trace_id", nargs="?", default=None,
                    help="trace id to render (omit with --list)")
    ap.add_argument("--spans", required=True, action="append",
                    help="spans directory, stream file, or glob "
                         "(repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list known trace ids with a one-line summary")
    ap.add_argument("--json", action="store_true",
                    help="print the full autopsy JSON instead of the "
                         "ASCII tree")
    ap.add_argument("--perfetto", default="",
                    help="write a Chrome-trace/Perfetto file for the "
                         "trace (one track per process)")
    args = ap.parse_args(argv)

    paths = []
    for p in args.spans:
        paths.append(os.path.join(p, "*.jsonl") if os.path.isdir(p)
                     else p)
    store = TraceStore(paths)

    if args.list:
        ids = store.trace_ids()
        if not ids:
            print("no traces found", file=sys.stderr)
            return 1
        for tid in ids:
            a = store.autopsy(tid)
            dur = f"{a['duration_s']:.3f}s" \
                if a["duration_s"] is not None else "?"
            flags = []
            if a["resumed"]:
                flags.append("resumed")
            if a["failovers"]:
                flags.append(f"failovers={a['failovers']}")
            if a["unfinished_spans"]:
                flags.append(f"unfinished={len(a['unfinished_spans'])}")
            print(f"{tid}  spans={a['span_count']} "
                  f"procs={len(a['processes'])} dur={dur}"
                  + (("  [" + ", ".join(flags) + "]") if flags else ""))
        return 0

    if not args.trace_id:
        ap.error("need a trace id (or --list)")
    autopsy = store.autopsy(args.trace_id)
    if autopsy is None:
        print(f"trace {args.trace_id} not found in "
              f"{len(store.paths)} stream(s)", file=sys.stderr)
        return 1

    if args.perfetto:
        events = store.perfetto(args.trace_id)
        with open(args.perfetto, "w") as f:
            json.dump(events, f)
        print(f"wrote {len(events)} events -> {args.perfetto}")

    if args.json:
        print(json.dumps(autopsy, indent=2))
    else:
        print(store.ascii_tree(args.trace_id))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # `... | head` closed the pipe: not an error
        raise SystemExit(0)
