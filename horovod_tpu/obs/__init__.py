"""Unified observability: metrics registry, Prometheus exposition, and
request tracing onto one Perfetto timeline (docs/observability.md).

Three pieces, one time axis:

* :mod:`horovod_tpu.obs.registry` — typed process-wide metrics
  (Counter/Gauge/Histogram with labels, duplicate-name detection,
  Prometheus text exposition).  Serving engines keep private
  registries; training/elastic/timeline metrics live in
  :func:`~horovod_tpu.obs.registry.default_registry`.
* :mod:`horovod_tpu.obs.tracing` — per-request trace ids
  (``X-Trace-Id``) propagated submit → prefill → decode → retirement,
  with a timing breakdown in every ``/generate`` response and a JSONL
  event log; request spans, tick-phase spans, and lifecycle instants
  (XLA compiles, engine restarts, watchdog stalls, elastic
  re-rendezvous) render through the existing
  :class:`horovod_tpu.timeline.Timeline` writer.
* :func:`training_step` — the training-side span: wraps one step,
  observing ``training_step_seconds`` and nesting a ``train_step``
  span into the same timeline the serving spans land on.

    from horovod_tpu import obs
    obs.tracing.start("/tmp/trace.json", jsonl_path="/tmp/trace.jsonl")
    for batch in data:
        with obs.training_step():
            params, opt_state, loss = step(params, opt_state, batch)
"""

from __future__ import annotations

import contextlib
import time

from horovod_tpu.obs import (  # noqa: F401
    aggregate,
    fleet,
    registry,
    trace_store,
    tracing,
    xprof,
)
from horovod_tpu.obs.registry import (  # noqa: F401
    Counter,
    DuplicateMetricError,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    elastic_metrics,
    training_metrics,
)
from horovod_tpu.obs.trace_store import TraceStore  # noqa: F401
from horovod_tpu.obs.tracing import (  # noqa: F401
    PARENT_SPAN_HEADER,
    SAMPLED_HEADER,
    TRACE_ID_HEADER,
    RequestTrace,
    SpanRecorder,
    SpanSampling,
    Tracer,
    mint_span_id,
    mint_trace_id,
    record_compile,
)

__all__ = [
    "aggregate", "fleet", "registry", "trace_store", "tracing", "xprof",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DuplicateMetricError", "default_registry",
    "training_metrics", "elastic_metrics",
    "TRACE_ID_HEADER", "PARENT_SPAN_HEADER", "SAMPLED_HEADER",
    "RequestTrace", "Tracer", "SpanRecorder", "SpanSampling",
    "TraceStore", "mint_trace_id", "mint_span_id",
    "record_compile", "training_step",
]


@contextlib.contextmanager
def training_step(name: str = "train_step"):
    """Span one training step: observes ``training_step_seconds`` /
    ``training_steps_total`` / ``training_last_step_seconds`` in the
    default registry (the last-step gauge also rides the elastic
    heartbeat, feeding the driver's straggler detector), refreshes the
    live ``training_mfu`` gauge when
    :func:`horovod_tpu.obs.xprof.set_training_cost` armed it, and, when
    a timeline is recording, nests a ``train_step`` span onto the same
    time axis as the serving request spans."""
    m = training_metrics()
    from horovod_tpu import timeline as TL

    tl = TL.get()
    t0 = time.monotonic()
    if tl is not None:
        tl.begin(name, "training")
    try:
        yield
    finally:
        dt = time.monotonic() - t0
        if tl is not None:
            tl.end(name)
        m.step_time.observe(dt)
        m.steps.inc()
        m.last_step.set(dt)
        xprof.observe_step(dt, m.mfu)
