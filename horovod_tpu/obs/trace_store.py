"""Assemble cross-process span streams into per-trace span TREES.

The span layer (:mod:`horovod_tpu.obs.tracing`) has every process —
router, each replica generation, anything else holding a
:class:`~horovod_tpu.obs.tracing.SpanRecorder` — append spans to its
own JSONL stream.  This module is the collector: it reads any number of
those streams, aligns their ``time.monotonic()`` timestamps onto ONE
wall-clock axis via each stream's anchor record, and reassembles the
Dapper-style causal tree per trace id:

    store = TraceStore.from_dir("/tmp/spans")
    store.autopsy("1f0c9a2b...")   # full JSON: every attempt, events,
                                   # carried-token accounting
    store.ascii_tree("1f0c9a2b...")
    store.perfetto("1f0c9a2b...")  # one track per process

The streams it reads are crash evidence, not neat exports: a SIGKILL'd
replica's stream ends mid-request, with a start record and some events
but no finish — the collector keeps that span as ``unfinished`` (end
time unknown, status ``"unfinished"``), which is precisely the signature
a failover autopsy needs ("this attempt never answered").  Torn final
lines are skipped like the request journal does.

Clock alignment: every stream opens with
``{"k": "anchor", "mono": ..., "wall": ...}``; a span's wall time is
``t + (wall - mono)``.  For processes on one host (the replica
deployment model here) that is exact; across hosts it inherits
wall-clock skew, the same caveat as ``obs.merge --align-start``.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Iterable, List, Optional

__all__ = ["SpanNode", "TraceStore"]


class SpanNode:
    """One span, wall-clock aligned, with its children and events."""

    __slots__ = ("id", "parent", "trace", "name", "proc", "role",
                 "t0", "t1", "status", "attrs", "events", "children",
                 "detail")

    def __init__(self, *, id, parent, trace, name, proc, role,
                 t0, t1=None, status=None, attrs=None, detail=False):
        self.id = id
        self.parent = parent
        self.trace = trace
        self.name = name
        self.proc = proc
        self.role = role
        self.t0 = t0
        self.t1 = t1                # None => unfinished (process died?)
        self.status = status        # None until finished
        self.attrs = attrs or {}
        self.events: List[Dict] = []
        self.children: List["SpanNode"] = []
        self.detail = detail        # phase/tick span (tail-sampled tier)

    @property
    def unfinished(self) -> bool:
        return self.t1 is None

    def as_dict(self, origin: float) -> Dict:
        """JSON form with times relative to the trace origin."""
        return {
            "span_id": self.id,
            "parent_span_id": self.parent,
            "name": self.name,
            "proc": self.proc,
            "role": self.role,
            "start_s": round(self.t0 - origin, 6),
            "end_s": round(self.t1 - origin, 6)
            if self.t1 is not None else None,
            "status": self.status
            if self.status is not None else "unfinished",
            "unfinished": self.unfinished,
            "attrs": self.attrs,
            "events": [
                {"type": e["type"], "proc": e["proc"],
                 "t_s": round(e["t"] - origin, 6), "attrs": e["attrs"]}
                for e in self.events],
            "children": [c.as_dict(origin) for c in self.children],
        }


class TraceStore:
    """Parse span JSONL streams and serve per-trace trees.

    ``paths`` may mix files and globs; unreadable or empty inputs are
    skipped (one dead stream must not cost the autopsy — the healthy
    processes' spans still assemble).  Streams are re-read per
    construction: build a fresh store per query, the autopsy path is
    cold by design."""

    def __init__(self, paths: Iterable[str]):
        self.paths: List[str] = []
        for p in paths:
            hits = sorted(glob.glob(p))
            self.paths.extend(hits if hits else [p])
        # trace_id -> span_id -> SpanNode (detail spans get synthetic ids)
        self._spans: Dict[str, Dict[str, SpanNode]] = {}
        # span_id -> node across ALL traces (ids are uuid-unique):
        # finish-record resolution must be O(1), not a scan per record
        self._by_id: Dict[str, SpanNode] = {}
        # trace_id -> events that named no (known) span
        self._loose: Dict[str, List[Dict]] = {}
        self._drops: Dict[str, int] = {}
        self.processes: List[str] = []
        #: streams actually opened and decoded — 0 means the store
        #: found NOTHING (wrong directory, every file unreadable),
        #: which callers must distinguish from "trace id unknown"
        self.n_readable: int = 0
        self._load()

    @classmethod
    def from_dir(cls, span_dir: str) -> "TraceStore":
        """Every ``*.jsonl`` stream under one spans directory — the
        layout ``ReplicaSupervisor(span_dir=...)`` and the router's own
        recorder share."""
        return cls([os.path.join(span_dir, "*.jsonl")])

    # -- parsing -----------------------------------------------------------

    def _load(self) -> None:
        seen_procs: List[str] = []
        synth = 0
        for path in self.paths:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    raw = f.read()
            except (OSError, UnicodeDecodeError, ValueError):
                # One unreadable input (permissions, stray binary file
                # matching the glob) must not cost the healthy streams
                # their autopsy.
                continue
            self.n_readable += 1
            offset = 0.0   # wall = mono + offset; 0 until the anchor
            proc = os.path.basename(path)
            role = "process"
            # Two passes per file: spans first, then events/finishes —
            # a finish record can precede nothing, but events may refer
            # to spans started later in a concurrent writer's stream
            # ordering.  (Within one file starts do come first, but the
            # two-pass shape keeps the parser order-independent.)
            # Every RECORD is individually guarded: a foreign or
            # corrupted line (e.g. "t0": null) is skipped, never a
            # store-wide failure.
            pend: List[Dict] = []
            for line in raw.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn write at a kill instant
                if not isinstance(ev, dict):
                    continue  # foreign JSONL sharing the directory
                k = ev.get("k")
                try:
                    if k == "anchor":
                        offset = float(ev.get("wall", 0.0)) \
                            - float(ev.get("mono", 0.0))
                        proc = ev.get("proc", proc)
                        role = ev.get("role", role)
                        if proc not in seen_procs:
                            seen_procs.append(proc)
                    elif k == "s":
                        tid = ev.get("trace")
                        node = SpanNode(
                            id=ev.get("id"), parent=ev.get("parent"),
                            trace=tid, name=ev.get("name", "?"),
                            proc=ev.get("proc", proc), role=role,
                            t0=float(ev.get("t0", 0.0)) + offset,
                            attrs=ev.get("a"))
                        self._spans.setdefault(tid, {})[node.id] = node
                        self._by_id[node.id] = node
                    elif k == "d":
                        tid = ev.get("trace")
                        synth += 1
                        node = SpanNode(
                            id=f"_d{synth}", parent=ev.get("parent"),
                            trace=tid, name=ev.get("name", "?"),
                            proc=ev.get("proc", proc), role=role,
                            t0=float(ev.get("t0", 0.0)) + offset,
                            t1=float(ev.get("t1", 0.0)) + offset,
                            status="ok", attrs=ev.get("a"), detail=True)
                        self._spans.setdefault(tid, {})[node.id] = node
                    elif k in ("e", "f", "x"):
                        pend.append({**ev, "_offset": offset,
                                     "_proc": proc})
                except (TypeError, ValueError):
                    continue  # one malformed record, not a dead store
            for ev in pend:
                k, offset = ev["k"], ev["_offset"]
                try:
                    if k == "f":
                        node = self._by_id.get(ev.get("id"))
                        if node is not None:
                            node.t1 = float(ev.get("t1", 0.0)) + offset
                            node.status = ev.get("status", "ok")
                            if ev.get("a"):
                                node.attrs.update(ev["a"])
                    elif k == "e":
                        tid = ev.get("trace")
                        rec = {"type": ev.get("type"),
                               "t": float(ev.get("t", 0.0)) + offset,
                               "proc": ev.get("proc", ev["_proc"]),
                               "span": ev.get("span"),
                               "attrs": ev.get("a") or {}}
                        node = self._spans.get(tid, {}).get(
                            ev.get("span"))
                        if node is not None:
                            node.events.append(rec)
                        else:
                            self._loose.setdefault(tid, []).append(rec)
                    elif k == "x":
                        tid = ev.get("trace")
                        self._drops[tid] = self._drops.get(tid, 0) \
                            + int(ev.get("n", 0))
                except (TypeError, ValueError):
                    continue
        self.processes = seen_procs

    # -- assembly ----------------------------------------------------------

    def trace_ids(self) -> List[str]:
        return sorted(t for t in self._spans if t)

    def tree(self, trace_id: str) -> List[SpanNode]:
        """Root spans of ``trace_id`` with children attached (sorted by
        start time).  A span whose parent is unknown — upstream process
        not collected, or the parent id came from a caller outside this
        deployment — becomes a root rather than vanishing."""
        spans = self._spans.get(trace_id, {})
        for node in spans.values():
            node.children = []
        roots: List[SpanNode] = []
        for node in spans.values():
            parent = spans.get(node.parent) if node.parent else None
            if parent is not None and parent is not node:
                parent.children.append(node)
            else:
                roots.append(node)
        for node in spans.values():
            node.children.sort(key=lambda n: n.t0)
            node.events.sort(key=lambda e: e["t"])
        roots.sort(key=lambda n: n.t0)
        return roots

    # -- views -------------------------------------------------------------

    def autopsy(self, trace_id: str) -> Optional[Dict]:
        """The full post-mortem JSON for one trace (what the router
        serves at ``GET /trace/<id>``), or None for an unknown id."""
        roots = self.tree(trace_id)
        loose = self._loose.get(trace_id, [])
        if not roots and not loose:
            return None
        spans = self._spans.get(trace_id, {})
        origin = min(n.t0 for n in spans.values()) if spans \
            else min(e["t"] for e in loose)
        ends = [n.t1 for n in spans.values() if n.t1 is not None]
        events: List[Dict] = list(loose)
        for node in spans.values():
            events.extend(node.events)
        events.sort(key=lambda e: e["t"])
        carried = sum(e["attrs"].get("carried", 0) for e in events
                      if e["type"] == "resume")
        # "Attempts" = the spans a failover postmortem reads first:
        # each replica-side request span (one per engine that touched
        # the request) and each router proxy-attempt span.
        attempts = sorted(
            (n for n in spans.values()
             if not n.detail and (n.role == "replica"
                                  or n.name.startswith("attempt"))),
            key=lambda n: n.t0)
        return {
            "trace_id": trace_id,
            "processes": sorted({n.proc for n in spans.values()}
                                | {e["proc"] for e in loose}),
            "span_count": len(spans),
            "unfinished_spans": sorted(
                n.id for n in spans.values() if n.unfinished),
            "start_wall": round(origin, 6),
            "duration_s": round(max(ends) - origin, 6) if ends else None,
            "events": [
                {"type": e["type"], "proc": e["proc"],
                 "span": e.get("span"),
                 "t_s": round(e["t"] - origin, 6), "attrs": e["attrs"]}
                for e in events],
            "resumed": any(e["type"] == "resume" for e in events),
            "failovers": sum(e["type"] == "failover" for e in events),
            "retries": sum(e["type"] == "retry" for e in events),
            "carried_tokens": carried,
            "detail_spans_dropped": self._drops.get(trace_id, 0),
            "attempts": [
                {"span_id": n.id, "name": n.name, "proc": n.proc,
                 "start_s": round(n.t0 - origin, 6),
                 "end_s": round(n.t1 - origin, 6)
                 if n.t1 is not None else None,
                 "status": n.status
                 if n.status is not None else "unfinished",
                 "unfinished": n.unfinished,
                 "attrs": n.attrs}
                for n in attempts],
            "tree": [r.as_dict(origin) for r in roots],
        }

    def ascii_tree(self, trace_id: str) -> Optional[str]:
        """Render one trace as an indented ASCII tree (the CLI view)."""
        roots = self.tree(trace_id)
        if not roots:
            return None
        spans = self._spans.get(trace_id, {})
        origin = min(n.t0 for n in spans.values())
        lines = [f"trace {trace_id}  "
                 f"({len(spans)} spans, "
                 f"{len({n.proc for n in spans.values()})} process(es))"]

        def fmt(node: SpanNode) -> str:
            if node.t1 is not None:
                tail = (f"{node.t0 - origin:7.3f}s +"
                        f"{node.t1 - node.t0:.3f}s  {node.status}")
            else:
                tail = (f"{node.t0 - origin:7.3f}s +?       "
                        f"UNFINISHED (no finish record — process died?)")
            return f"{node.name} [{node.proc}]  {tail}"

        def walk(node: SpanNode, prefix: str, last: bool) -> None:
            branch = "`- " if last else "|- "
            lines.append(prefix + branch + fmt(node))
            child_prefix = prefix + ("   " if last else "|  ")
            items: List = [("e", e) for e in node.events] \
                + [("n", c) for c in node.children]
            items.sort(key=lambda it: it[1]["t"] if it[0] == "e"
                       else it[1].t0)
            for i, (kind, it) in enumerate(items):
                last_i = i == len(items) - 1
                if kind == "e":
                    b = "`- " if last_i else "|- "
                    attrs = f"  {it['attrs']}" if it["attrs"] else ""
                    lines.append(child_prefix + b
                                 + f"! {it['type']} @"
                                 f"{it['t'] - origin:.3f}s{attrs}")
                else:
                    walk(it, child_prefix, last_i)

        for i, root in enumerate(roots):
            walk(root, "", i == len(roots) - 1)
        drops = self._drops.get(trace_id, 0)
        if drops:
            lines.append(f"({drops} detail span(s) tail-dropped)")
        return "\n".join(lines)

    def perfetto(self, trace_id: Optional[str] = None) -> List[Dict]:
        """Chrome-trace events for one trace (or all), ONE process
        track per recording process — load in https://ui.perfetto.dev.
        Same pid-block idiom as :mod:`horovod_tpu.obs.merge`."""
        ids = [trace_id] if trace_id is not None else self.trace_ids()
        nodes: List[SpanNode] = []
        for tid in ids:
            nodes.extend(self._spans.get(tid, {}).values())
        if not nodes:
            return []
        origin = min(n.t0 for n in nodes)
        procs: Dict[str, int] = {}
        rows: Dict[str, Dict[str, int]] = {}   # proc -> span_id -> tid
        out: List[Dict] = []

        def pid(proc: str) -> int:
            p = procs.get(proc)
            if p is None:
                p = (len(procs) + 1) * 1000
                procs[proc] = p
                rows[proc] = {"_next": 1}
                out.append({"name": "process_name", "ph": "M", "pid": p,
                            "args": {"name": proc}})
                out.append({"name": "process_sort_index", "ph": "M",
                            "pid": p,
                            "args": {"sort_index": len(procs)}})
            return p

        def tid(n: SpanNode) -> int:
            """One thread row per same-process span FAMILY: a span
            whose parent lives in the same process inherits its row
            (children of one request are sequential, so same-row
            slices render as true nesting), while independent roots —
            e.g. concurrent requests on one replica — each get their
            own row instead of false-stacking."""
            r = rows[n.proc]
            parent_tid = r.get(n.parent)
            if parent_tid is None:
                parent_tid = r["_next"]
                r["_next"] += 1
            r[n.id] = parent_tid
            return parent_tid

        # sorted by t0: a same-process parent is always assigned its
        # row before its children look it up
        for n in sorted(nodes, key=lambda n: n.t0):
            p = pid(n.proc)
            tid_row = tid(n)
            end = n.t1 if n.t1 is not None else n.t0
            out.append({
                "name": n.name, "cat": "trace.span", "ph": "X",
                "ts": (n.t0 - origin) * 1e6,
                "dur": max(end - n.t0, 0.0) * 1e6,
                "pid": p, "tid": tid_row,
                "args": {"trace_id": n.trace, "span_id": n.id,
                         "status": n.status or "unfinished",
                         **({"unfinished": True} if n.unfinished
                            else {}), **n.attrs}})
            for e in n.events:
                out.append({
                    "name": e["type"], "cat": "trace.event", "ph": "i",
                    "ts": (e["t"] - origin) * 1e6, "pid": p,
                    "tid": tid_row, "s": "p", "args": e["attrs"]})
        return out
