"""Driver-side fleet observability: aggregate worker snapshots, detect
stragglers, serve one fleet-level scrape.

The ElasticDriver can already see *liveness* (exit codes, stale
heartbeats); this module gives it *slowness* and *state*:

* each elastic worker publishes its registry export and a step-duration
  heartbeat payload over the rendezvous KV
  (:class:`horovod_tpu.elastic.worker.WorkerNotificationManager`);
* the driver feeds them into a :class:`FleetMonitor`, which merges the
  exports (:mod:`horovod_tpu.obs.aggregate` — counters sum, gauges get
  ``rank``/``host`` labels + min/median/max, histograms merge
  bucket-wise) and watches per-rank step durations for **stragglers**:
  a rank whose step time exceeds ``straggler_threshold`` × the fleet
  median for ``straggler_patience`` consecutive step reports is
  flagged — a warning log, an ``elastic_straggler_total{rank=}``
  counter, and an ``elastic_straggler`` timeline instant.  Detection is
  REPORT-ONLY: the driver surfaces the rank (``/fleet`` carries the
  same list the Blacklist would need) but never evicts on slowness —
  slow-but-correct must stay a human call;
* :class:`FleetServer` serves the merged view over HTTP:
  ``GET /metrics`` (Prometheus 0.0.4, the strict-parser-clean fleet
  exposition) and ``GET /fleet`` (JSON: per-rank status, skew,
  stragglers, merged metrics).

Horovod's cross-worker timeline existed for exactly this blind spot —
per-worker views hide negotiation stalls and stragglers; the skew gauge
(slowest/median step time, ``elastic_step_time_skew``) is that signal
as a number.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from horovod_tpu.obs import tracing as obs_tracing
from horovod_tpu.obs.aggregate import FleetAggregate, merge_exports
from horovod_tpu.obs.registry import MetricsRegistry

logger = logging.getLogger("horovod_tpu")

__all__ = ["FleetMonitor", "FleetServer", "parse_heartbeat"]


def parse_heartbeat(raw: bytes) -> Dict:
    """Decode a heartbeat KV payload: the structured JSON form
    (``{"t": wall, "steps": n, "step_s": last}``) or the legacy bare
    ``repr(time.time())`` float (pre-fleet workers keep working)."""
    text = raw.decode() if isinstance(raw, (bytes, bytearray)) else raw
    try:
        payload = json.loads(text)
    except (json.JSONDecodeError, ValueError):
        return {}
    if isinstance(payload, dict):
        return payload
    if isinstance(payload, (int, float)):
        return {"t": float(payload)}
    return {}


class _RankState:
    __slots__ = ("host", "export", "step_s", "steps", "strikes",
                 "flagged", "last_seen")

    def __init__(self, host: Optional[str]):
        self.host = host
        self.export: Optional[Dict] = None
        self.step_s: Optional[float] = None
        self.steps: Optional[float] = None
        self.strikes = 0
        self.flagged = False
        self.last_seen: Optional[float] = None


class FleetMonitor:
    """Thread-safe store + detector behind the driver's fleet view.

    Feed it with :meth:`heartbeat` / :meth:`snapshot` as KV data
    arrives; read :meth:`prometheus`, :meth:`fleet_json`, and
    :meth:`stragglers`.  ``begin_epoch`` clears per-rank state at a
    re-rendezvous (rank ids are reassigned across epochs) while the
    monitor's own counters — straggler episodes are a job-lifetime
    fact — survive."""

    def __init__(self, *, straggler_threshold: float = 2.0,
                 straggler_patience: int = 3,
                 registry: Optional[MetricsRegistry] = None):
        if straggler_threshold <= 1.0:
            raise ValueError("straggler_threshold must be > 1.0")
        if straggler_patience < 1:
            raise ValueError("straggler_patience must be >= 1")
        self.straggler_threshold = straggler_threshold
        self.straggler_patience = straggler_patience
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._skew = self.registry.gauge(
            "elastic_step_time_skew",
            "Slowest/median per-rank step duration across the fleet "
            "(1.0 = perfectly even)", exist_ok=True)
        self._straggler_total = self.registry.counter(
            "elastic_straggler_total",
            "Sustained-straggler episodes detected (report-only)",
            labels=("rank",), exist_ok=True)
        self._ranks_reporting = self.registry.gauge(
            "fleet_ranks_reporting",
            "Ranks with a live fleet heartbeat this epoch",
            exist_ok=True)
        self._lock = threading.Lock()
        self._ranks: Dict[str, _RankState] = {}
        self.epoch: Optional[int] = None

    # -- ingestion ---------------------------------------------------------

    def begin_epoch(self, epoch: int) -> None:
        with self._lock:
            self.epoch = epoch
            self._ranks.clear()
            self._ranks_reporting.set(0)
            self._skew.set(0.0)

    def _rank(self, rank, host) -> _RankState:
        key = str(rank)
        st = self._ranks.get(key)
        if st is None:
            st = self._ranks[key] = _RankState(host)
            self._ranks_reporting.set(len(self._ranks))
        if host is not None:
            st.host = host
        return st

    def heartbeat(self, rank, host: Optional[str],
                  payload: Dict) -> None:
        """One heartbeat KV observation.  Step-duration fields advance
        the straggler detector only when ``steps`` moved — one strike
        per *step report*, not per driver poll, so ``patience`` reads
        as "flagged within K slow steps"."""
        with self._lock:
            st = self._rank(rank, host)
            st.last_seen = time.monotonic()
            steps = payload.get("steps")
            step_s = payload.get("step_s")
            fresh = (steps is not None and steps != st.steps)
            if steps is not None:
                st.steps = steps
            if step_s is not None:
                st.step_s = float(step_s)
            if fresh and st.step_s is not None:
                self._evaluate_locked(str(rank), st)

    def snapshot(self, rank, host: Optional[str], export: Dict) -> None:
        """One registry-export KV observation."""
        with self._lock:
            st = self._rank(rank, host)
            st.export = export
            st.last_seen = time.monotonic()

    # -- straggler detection -----------------------------------------------

    def _evaluate_locked(self, rank: str, st: _RankState) -> None:
        steps = {r: s.step_s for r, s in self._ranks.items()
                 if s.step_s is not None and s.step_s > 0}
        if len(steps) < 2:
            return
        self._skew.set(max(steps.values())
                       / statistics.median(steps.values()))
        # Compare against the median of the OTHER ranks: including the
        # suspect in its own reference would make slowest/median
        # mathematically bounded below 2x on a 2-rank fleet — a 10x
        # straggler could never be flagged at the default threshold.
        peers = [s for r, s in steps.items() if r != rank]
        if not peers:
            return
        med = statistics.median(peers)
        ratio = st.step_s / med
        if ratio <= self.straggler_threshold:
            st.strikes = 0
            st.flagged = False
            return
        st.strikes += 1
        if st.strikes < self.straggler_patience or st.flagged:
            return
        st.flagged = True
        self._straggler_total.labels(rank=rank).inc()
        logger.warning(
            "fleet: rank %s%s is a sustained straggler: step %.4fs vs "
            "peer median %.4fs (%.1fx > %.1fx threshold for %d "
            "consecutive steps) — report-only, not evicting",
            rank, f" on {st.host}" if st.host else "", st.step_s, med,
            ratio, self.straggler_threshold, st.strikes)
        try:
            obs_tracing.instant("elastic_straggler", {
                "rank": rank, "host": st.host, "step_s": st.step_s,
                "median_step_s": med, "ratio": round(ratio, 3)})
        except Exception:  # pragma: no cover - tracing never gates
            pass

    def stragglers(self) -> List[str]:
        """Ranks currently flagged as sustained stragglers (the list a
        blacklist-on-slowness policy would consume; today report-only)."""
        with self._lock:
            return sorted(r for r, st in self._ranks.items() if st.flagged)

    @property
    def skew(self) -> float:
        return self._skew.value

    # -- views -------------------------------------------------------------

    def aggregate(self) -> FleetAggregate:
        """Merge the currently-held rank exports."""
        with self._lock:
            exports = {r: st.export for r, st in self._ranks.items()
                       if st.export is not None}
            hosts = {r: st.host for r, st in self._ranks.items()
                     if st.export is not None and st.host}
        return merge_exports(exports, hosts)

    def prometheus(self) -> str:
        """The fleet ``/metrics`` body: every rank's families merged
        (``rank``/``host``-labeled) plus the monitor's own skew /
        straggler / reporting families."""
        return self.aggregate().to_prometheus() \
            + self.registry.to_prometheus()

    def fleet_json(self) -> Dict:
        """The ``/fleet`` JSON view: per-rank status + skew +
        stragglers + the merged metric snapshot."""
        now = time.monotonic()
        with self._lock:
            ranks = {
                r: {
                    "host": st.host,
                    "heartbeat_age_s": (round(now - st.last_seen, 3)
                                        if st.last_seen is not None
                                        else None),
                    "steps": st.steps,
                    "step_seconds": st.step_s,
                    "straggler": st.flagged,
                    "has_metrics": st.export is not None,
                }
                for r, st in self._ranks.items()
            }
            epoch = self.epoch
        return {
            "epoch": epoch,
            "ranks": ranks,
            "step_time_skew": self.skew,
            "straggler_threshold": self.straggler_threshold,
            "straggler_patience": self.straggler_patience,
            "stragglers": self.stragglers(),
            "metrics": self.aggregate().snapshot(),
        }


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet: the scrape IS the log
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        monitor: FleetMonitor = self.server.monitor
        try:
            if self.path == "/metrics":
                self._send(200, monitor.prometheus().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/fleet":
                self._send(200, json.dumps(monitor.fleet_json()).encode(),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": f"unknown path {self.path}",
                     "paths": ["/metrics", "/fleet"]}).encode(),
                    "application/json")
        except Exception as e:  # aggregation conflicts -> 500, not a hang
            self._send(500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(),
                "application/json")


class FleetServer:
    """Threaded stdlib-HTTP front for a :class:`FleetMonitor`
    (``GET /metrics`` + ``GET /fleet``); port 0 binds ephemeral."""

    def __init__(self, monitor: FleetMonitor, *,
                 host: str = "127.0.0.1", port: int = 0):
        self.monitor = monitor
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        """``(host, port)`` a scraper can actually connect to: a
        0.0.0.0 wildcard bind is reported as this host's reachable
        name (``HOROVOD_HOSTNAME``, like the rendezvous server) — the
        wildcard is a bind address, not a destination."""
        if self._httpd is None:
            host, port = self.host, self.port
        else:
            host, port = self._httpd.server_address[:2]
        if host in ("0.0.0.0", "::"):
            host = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")
        return (host, port)

    def start(self) -> "FleetServer":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd.monitor = self.monitor
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-metrics-http",
            daemon=True)
        self._thread.start()
        logger.info("fleet: metrics endpoint at http://%s:%d/metrics",
                    *self.address)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
