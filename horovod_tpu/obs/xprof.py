"""XLA cost/memory introspection as a library: honest FLOPs, HBM, and
live MFU from the compiler's own analysis.

``bench.py`` proved the technique — ``compiled.cost_analysis()`` counts
the FLOPs XLA actually scheduled (forward + backward + optimizer,
BN/padding included), which is the honest denominator-free utilization
number TPU practice leans on (the Xprof approach) — but it lived as
ad-hoc benchmark code.  This module library-izes it:

* :func:`introspect` — one call on a lowered-and-compiled function
  returns a :class:`CostReport` (FLOPs, bytes accessed, peak HBM from
  ``memory_analysis``) and registers it as ``xla_flops{fn=}`` /
  ``xla_hbm_peak_bytes{fn=}`` gauges so ``/metrics`` carries the
  compiler's view of every instrumented program;
* :data:`PEAK_FLOPS_BY_KIND` / :func:`chip_peak_flops` — the
  per-generation bf16 peak table (previously duplicated by hand in
  ``bench.py`` and ``benchmarks/transformer.py``);
* :func:`set_training_cost` + :func:`observe_step` — tell the
  observability layer the per-step model FLOPs once, and every
  ``obs.training_step()`` thereafter sets the live ``training_mfu``
  gauge from its measured wall-clock (step FLOPs / step seconds /
  chip peak) — MFU becomes a scrapeable signal instead of a
  benchmark-only artifact;
* :func:`transformer_flops_per_token` — the analytic decode-side model
  cost (2 FLOPs per parameter per token, PaLM appendix-B convention)
  that turns the serving engine's token counters into achieved FLOP/s
  in ``/stats`` (``EngineConfig.model_flops_per_token``).

Everything degrades to ``None`` rather than raising when the backend
cannot answer (CPU smoke runs, older JAX): observability must never
gate the workload.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from horovod_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    training_metrics,
)

__all__ = [
    "CostReport", "introspect", "PEAK_FLOPS_BY_KIND", "chip_peak_flops",
    "mfu", "set_training_cost", "training_cost", "observe_step",
    "matmul_param_count", "transformer_flops_per_token",
]

# Peak dense bf16 FLOP/s per chip by device kind (the table bench.py and
# benchmarks/transformer.py used to carry separately).  Matching is by
# prefix on jax's device_kind string; unknown chips yield None so MFU
# fields become JSON null, never NaN.
PEAK_FLOPS_BY_KIND: Dict[str, float] = {
    "TPU v2": 46e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def chip_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s of ``device`` (default: the first visible
    device), or None when the chip generation is unknown (CPU
    fallback, new hardware)."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            return None
    kind = getattr(device, "device_kind", "")
    return next(
        (v for k, v in PEAK_FLOPS_BY_KIND.items() if kind.startswith(k)),
        None)


@dataclasses.dataclass
class CostReport:
    """What the compiler knows about one compiled program (per-device:
    cost_analysis describes the SPMD-partitioned module, i.e. the LOCAL
    shard's work — divide by the local batch, not the global one)."""

    fn: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    peak_hbm_bytes: Optional[float] = None

    def mfu(self, step_seconds: float,
            peak: Optional[float] = None) -> Optional[float]:
        """Utilization of this program at the measured step time."""
        return mfu(self.flops, step_seconds, peak)


def _cost_dict(compiled) -> Optional[Dict]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return ca or None


def _peak_hbm(compiled) -> Optional[float]:
    """Peak HBM of one executable from ``memory_analysis``: arguments +
    outputs + temporaries, minus donated/aliased buffers (counted once,
    not twice)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    ma = ma[0] if isinstance(ma, (list, tuple)) else ma
    if ma is None:
        return None
    try:
        total = (float(getattr(ma, "argument_size_in_bytes", 0) or 0)
                 + float(getattr(ma, "output_size_in_bytes", 0) or 0)
                 + float(getattr(ma, "temp_size_in_bytes", 0) or 0)
                 - float(getattr(ma, "alias_size_in_bytes", 0) or 0))
    except (TypeError, ValueError):
        return None
    return total if total > 0 else None


def introspect(compiled, fn: str = "step", *,
               registry: Optional[MetricsRegistry] = None,
               register: bool = True) -> CostReport:
    """Run XLA's own cost and memory analysis on a compiled function
    (the result of ``jax.jit(f).lower(...).compile()``) and register
    the findings as gauges.

    Returns a :class:`CostReport` with ``flops`` (everything the chip
    actually runs — higher than analytic model FLOPs, which is the
    honest utilization of what was *scheduled*), ``bytes_accessed``,
    and ``peak_hbm_bytes``.  With ``register`` (default), sets
    ``xla_flops{fn=...}`` and ``xla_hbm_peak_bytes{fn=...}`` in the
    (default) registry so a scrape carries the compiler's view.  Any
    field the backend cannot answer is None — never an exception."""
    ca = _cost_dict(compiled)
    report = CostReport(
        fn=fn,
        flops=float(ca["flops"]) if ca and "flops" in ca else None,
        bytes_accessed=(float(ca["bytes accessed"])
                        if ca and "bytes accessed" in ca else None),
        peak_hbm_bytes=_peak_hbm(compiled),
    )
    if register:
        try:
            r = registry if registry is not None else default_registry()
            if report.flops is not None:
                r.gauge("xla_flops",
                        "FLOPs per execution of an instrumented "
                        "compiled function (XLA cost_analysis)",
                        labels=("fn",), exist_ok=True).labels(
                            fn=fn).set(report.flops)
            if report.peak_hbm_bytes is not None:
                r.gauge("xla_hbm_peak_bytes",
                        "Peak HBM bytes of an instrumented compiled "
                        "function (XLA memory_analysis: args + outputs "
                        "+ temps - aliased)",
                        labels=("fn",), exist_ok=True).labels(
                            fn=fn).set(report.peak_hbm_bytes)
        except Exception:  # pragma: no cover - metrics never gate the run
            pass
    return report


def mfu(flops_per_step: Optional[float], step_seconds: float,
        peak: Optional[float] = None) -> Optional[float]:
    """Model-FLOPs utilization: ``flops / seconds / chip_peak`` —
    exactly the computation ``bench.py`` reports.  None when FLOPs or
    the chip peak are unknown, or the step time is non-positive."""
    if peak is None:
        peak = chip_peak_flops()
    if not flops_per_step or not peak or step_seconds <= 0:
        return None
    return flops_per_step / step_seconds / peak


# -- live training MFU --------------------------------------------------------
#
# set_training_cost() is called once (after compiling the step, e.g.
# right where bench.py runs introspect); every obs.training_step() then
# calls observe_step(dt), which sets the `training_mfu` gauge.  The
# disabled cost is one lock-free tuple read per step.

_training_cost = (None, None)  # (flops_per_step, peak_flops)
_training_lock = threading.Lock()


def set_training_cost(flops_per_step: Optional[float],
                      peak: Optional[float] = None) -> None:
    """Arm the live ``training_mfu`` gauge: per-step model FLOPs (from
    :func:`introspect` or an analytic count) and the chip peak
    (defaults to :func:`chip_peak_flops`).  Pass None to disarm."""
    global _training_cost
    if flops_per_step is None:
        with _training_lock:
            _training_cost = (None, None)
        return
    if peak is None:
        peak = chip_peak_flops()
    with _training_lock:
        _training_cost = (float(flops_per_step),
                          float(peak) if peak else None)


def training_cost():
    """The armed ``(flops_per_step, peak_flops)`` pair (None, None when
    disarmed)."""
    return _training_cost


def observe_step(step_seconds: float, mfu_gauge=None) -> Optional[float]:
    """One training step took ``step_seconds``: update the
    ``training_mfu`` gauge when armed.  Returns the MFU (or None).

    ``mfu_gauge`` lets the caller hand over the gauge it already holds
    (``obs.training_step`` does) so the armed per-step cost stays one
    tuple read + one gauge set, not a registry lookup."""
    flops, peak = _training_cost
    if flops is None or peak is None or step_seconds <= 0:
        return None
    u = flops / step_seconds / peak
    try:
        (mfu_gauge if mfu_gauge is not None
         else training_metrics().mfu).set(u)
    except Exception:  # pragma: no cover - metrics never gate training
        pass
    return u


def matmul_param_count(params) -> int:
    """Parameters participating in matmuls: every leaf of the pytree
    minus the ``embed`` table (lookup, not matmul).  The shared count
    under both the analytic train-side MFU numerator
    (benchmarks/transformer.py) and the serving-side
    :func:`transformer_flops_per_token` — one place to adjust if the
    model grows another non-matmul table."""
    import numpy as np

    try:
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        total = sum(int(np.prod(l.shape)) for l in leaves)
        embed = params.get("embed") if isinstance(params, dict) else None
        if embed is not None:
            total -= int(np.prod(embed.shape))
    except Exception:
        return 0
    return total


def transformer_flops_per_token(params) -> float:
    """Analytic decode-side model FLOPs per generated token: 2 FLOPs
    per matmul parameter (forward only — the PaLM appendix-B
    convention, attention-score term omitted as cache-length-dependent
    and small at serving lengths).  ``params`` is the transformer
    param pytree; the embedding table is excluded (lookup, not
    matmul).  Feed the result to
    ``EngineConfig.model_flops_per_token`` so the serving ``/stats``
    reports achieved FLOP/s."""
    return 2.0 * matmul_param_count(params)
