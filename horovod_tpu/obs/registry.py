"""Typed process-wide metrics registry with Prometheus text exposition.

The reference exposes its runtime state as free-form timeline events and
ad-hoc counters; production serving (ROADMAP north star) needs the other
two legs of observability: *scrapeable* metrics in a standard format and
one place where every subsystem's instruments live.  This module is that
place — deliberately dependency-free (stdlib only) so every layer
(serving engine, training loop, elastic supervisor, timeline) can
register instruments without import cycles.

Design:

* **Instruments** — :class:`Counter` (monotonic), :class:`Gauge`
  (set-to-value), :class:`Histogram` (fixed buckets + implicit +Inf
  overflow; constant memory forever).  All thread-safe: they are updated
  from engine/watchdog/notification threads and read from HTTP handler
  threads.
* **Families** — a metric created with ``labels=(...)`` is a family;
  :meth:`_Family.labels` returns the per-labelset child instrument
  (created lazily, cached).
* **Registry** — maps *unique* names to instruments.  Duplicate
  registration raises :class:`DuplicateMetricError` (the classic
  copy-paste bug where two subsystems silently share a counter);
  idempotent create-or-fetch is explicit via ``exist_ok=True`` and still
  type-checks the existing entry.
* **Exposition** — :meth:`MetricsRegistry.to_prometheus` renders the
  Prometheus text format (0.0.4): ``# HELP`` / ``# TYPE`` headers,
  cumulative ``_bucket{le=...}`` series + ``_sum`` / ``_count`` for
  histograms.  :meth:`MetricsRegistry.snapshot` is the JSON-friendly
  view ``/stats``-style endpoints serve.

Two registry scopes exist on purpose: each serving engine owns a private
registry (its lifetime — tests and benchmarks create many engines per
process), while process-wide training/elastic/timeline metrics live in
:func:`default_registry`.  ``ServingServer``'s ``/metrics`` renders
both, so one scrape covers serving, training, and elastic families.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DuplicateMetricError", "default_registry",
    "training_metrics", "elastic_metrics",
    "DEFAULT_LATENCY_BUCKETS", "TICK_PHASE_BUCKETS",
]


class DuplicateMetricError(ValueError):
    """A metric with this name is already registered (or exists with a
    different type/label set than the one requested)."""


_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class Counter:
    """Monotonic counter."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: Union[int, float] = 1) -> None:
        if n < 0:
            raise ValueError(f"counters only go up (inc by {n})")
        with self._lock:
            self._v += n

    @property
    def value(self) -> Union[int, float]:
        return self._v


class Gauge:
    """Instantaneous value."""

    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._v -= n

    @property
    def value(self) -> float:
        return self._v


# Latency buckets in seconds: 1ms .. 60s, roughly x2.5 per step — wide
# enough for CPU-smoke ticks and TPU production alike.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Tick-phase buckets extend down to 10us: an async dispatch (and a
# fully-hidden device wait) is sub-millisecond, which the request-level
# buckets above cannot resolve.
TICK_PHASE_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
) + DEFAULT_LATENCY_BUCKETS


class Histogram:
    """Fixed-bucket histogram with an implicit +Inf overflow bucket.

    Percentiles come from the cumulative bucket counts (the
    Prometheus-style estimate: the reported pN is the upper edge of the
    bucket containing the N-th percentile observation), which keeps
    memory constant no matter how long the server runs.
    """

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.buckets: List[float] = sorted(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = 0
        while i < len(self.buckets) and v > self.buckets[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def mean(self) -> Optional[float]:
        return self._sum / self._count if self._count else None

    def _percentile(self, counts: List[int], total: int,
                    q: float) -> Optional[float]:
        if not total:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
        return self.buckets[-1]

    def percentile(self, q: float) -> Optional[float]:
        """Upper edge of the bucket holding the q-quantile observation
        (q in [0, 1]); None when empty.

        Edge semantics (docs/observability.md "Percentile semantics"),
        relied on by fleet-merged p99s — these are *bucket estimates*,
        not exact quantiles:

        * the returned value is always a configured bucket **upper
          edge** — an observation of 1.5 in buckets (1, 2, 4) reports
          as 2;
        * an observation exactly **on** an edge belongs to that edge's
          bucket (``observe`` advances while ``v > edge``), so
          ``observe(2.0)`` → ``percentile(1.0) == 2``;
        * quantiles landing in the **+Inf overflow bucket report the
          largest finite edge** (the histogram cannot know how far past
          it the tail went) — a merged p99 equal to the top edge means
          "at least this", not "exactly this";
        * ``q == 0`` reports the smallest configured edge (whether or
          not that bucket holds any mass) — a floor, not a minimum.
        """
        with self._lock:
            counts, total = list(self._counts), self._count
        return self._percentile(counts, total, q)

    def state(self) -> Dict:
        """The mergeable raw state under ONE lock hold: bucket edges,
        per-bucket (non-cumulative) counts including the trailing +Inf
        overflow, sum, and count — the wire format
        :mod:`horovod_tpu.obs.aggregate` merges bucket-wise across
        ranks."""
        with self._lock:
            return {
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }

    def snapshot(self) -> Dict:
        # One locked copy; count/sum/buckets AND percentiles all
        # describe the same population (an observe() racing /stats must
        # not split them).
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        return {
            "count": total,
            "sum": round(s, 6),
            "mean": round(s / total, 6) if total else None,
            "p50": self._percentile(counts, total, 0.50),
            "p99": self._percentile(counts, total, 0.99),
            "buckets": {
                ("%g" % b): c for b, c in zip(self.buckets, counts)
            } | {"+Inf": counts[-1]},
        }

    def cumulative(self) -> Tuple[List[int], int, float]:
        """(cumulative per-bucket counts incl. +Inf, count, sum) under
        one lock hold — the Prometheus ``_bucket{le=...}`` series."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return cum, total, s


class _Family:
    """A labeled metric family: one child instrument per label-value
    tuple, created lazily and cached forever (label cardinality is the
    caller's responsibility, as in Prometheus clients)."""

    def __init__(self, make, labelnames: Sequence[str]):
        self._make = make
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"expected labels {self.labelnames}, got {tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class _Entry:
    __slots__ = ("kind", "help", "labelnames", "obj")

    def __init__(self, kind, help, labelnames, obj):
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.obj = obj


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v) -> str:
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class MetricsRegistry:
    """Name -> instrument map with duplicate detection, lock-safe
    snapshots, and Prometheus text exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    # -- creation ----------------------------------------------------------

    def _create(self, name: str, kind: str, make, help: str,
                labels: Sequence[str], exist_ok: bool):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for l in labels:
            if not _LABEL_NAME_RE.match(l):
                raise ValueError(f"invalid label name {l!r}")
        with self._lock:
            e = self._entries.get(name)
            if e is not None:
                if (exist_ok and e.kind == kind
                        and e.labelnames == tuple(labels)):
                    return e.obj
                raise DuplicateMetricError(
                    f"metric {name!r} already registered as {e.kind} "
                    f"with labels {e.labelnames} "
                    f"(requested {kind} with labels {tuple(labels)}"
                    f"{'' if exist_ok else '; pass exist_ok=True to share'})")
            obj = _Family(make, labels) if labels else make()
            self._entries[name] = _Entry(kind, help, labels, obj)
            return obj

    def counter(self, name: str, help: str = "", *,
                labels: Sequence[str] = (), exist_ok: bool = False):
        """Create and register a :class:`Counter` (or a counter family
        when ``labels`` is non-empty)."""
        return self._create(name, "counter", Counter, help, labels, exist_ok)

    def gauge(self, name: str, help: str = "", *,
              labels: Sequence[str] = (), exist_ok: bool = False):
        return self._create(name, "gauge", Gauge, help, labels, exist_ok)

    def histogram(self, name: str, help: str = "", *,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  labels: Sequence[str] = (), exist_ok: bool = False):
        return self._create(name, "histogram",
                            lambda: Histogram(buckets=buckets),
                            help, labels, exist_ok)

    # -- introspection -----------------------------------------------------

    def get(self, name: str):
        """The registered instrument/family, or None."""
        with self._lock:
            e = self._entries.get(name)
        return e.obj if e is not None else None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def _series(self, e: _Entry) -> Iterable[Tuple[Tuple[str, ...], object]]:
        if e.labelnames:
            return e.obj.children()
        return [((), e.obj)]

    def snapshot(self) -> Dict:
        """JSON-friendly view: scalar for counters/gauges, the
        histogram snapshot dict for histograms; labeled families map
        ``label="value"`` series keys to the same."""
        with self._lock:
            entries = sorted(self._entries.items())
        out: Dict = {}
        for name, e in entries:
            def one(inst):
                if e.kind == "histogram":
                    return inst.snapshot()
                return inst.value
            if e.labelnames:
                out[name] = {
                    ",".join(f'{n}="{v}"' for n, v in zip(e.labelnames, key)):
                        one(inst)
                    for key, inst in self._series(e)
                }
            else:
                out[name] = one(e.obj)
        return out

    def export(self) -> Dict:
        """Typed, mergeable JSON view — the fleet-aggregation wire
        format (:mod:`horovod_tpu.obs.aggregate`).  Unlike
        :meth:`snapshot` (which flattens to /stats-friendly scalars and
        loses the instrument kind), this keeps everything a remote
        merger needs: kind, help, label names, and per-series values —
        histograms as raw ``{buckets, counts, sum, count}`` state so
        they merge bucket-wise::

            {name: {"kind": "counter"|"gauge"|"histogram",
                    "help": ..., "labels": [...],
                    "series": [{"l": {label: value}, "v": scalar}
                               | {"l": {...}, "h": histogram_state}]}}
        """
        with self._lock:
            entries = sorted(self._entries.items())
        out: Dict = {}
        for name, e in entries:
            series = []
            for key, inst in self._series(e):
                s: Dict = {"l": dict(zip(e.labelnames, key))}
                if e.kind == "histogram":
                    s["h"] = inst.state()
                else:
                    s["v"] = inst.value
                series.append(s)
            out[name] = {"kind": e.kind, "help": e.help,
                         "labels": list(e.labelnames), "series": series}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 for every registered
        metric (serve with content type
        ``text/plain; version=0.0.4``)."""
        with self._lock:
            entries = sorted(self._entries.items())
        lines: List[str] = []
        for name, e in entries:
            if e.help:
                lines.append(f"# HELP {name} {_escape_help(e.help)}")
            lines.append(f"# TYPE {name} {e.kind}")
            for key, inst in self._series(e):
                labels = _fmt_labels(e.labelnames, key)
                if e.kind == "histogram":
                    cum, total, s = inst.cumulative()
                    for edge, c in zip(inst.buckets, cum):
                        le = _fmt_labels(e.labelnames, key,
                                         extra=[("le", "%g" % edge)])
                        lines.append(f"{name}_bucket{le} {c}")
                    le = _fmt_labels(e.labelnames, key,
                                     extra=[("le", "+Inf")])
                    lines.append(f"{name}_bucket{le} {total}")
                    lines.append(f"{name}_sum{labels} {_fmt_value(s)}")
                    lines.append(f"{name}_count{labels} {total}")
                else:
                    lines.append(f"{name}{labels} {_fmt_value(inst.value)}")
        return "\n".join(lines) + "\n" if lines else ""


# -- process-wide default registry -------------------------------------------

_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry: training, elastic, eager-runtime, and
    timeline metrics live here.  Serving engines keep private
    registries (one per engine lifetime); ``/metrics`` renders both."""
    return _default


class _Namespace:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def training_metrics(registry: Optional[MetricsRegistry] = None) -> _Namespace:
    """Create-or-fetch the training metric family: step time, step
    count, XLA compile events (labeled by instrumented function), the
    last-step-duration gauge (rides the elastic heartbeat payload so
    the driver's straggler detector sees per-rank step time), and the
    live MFU gauge (set by ``obs.training_step`` once
    :func:`horovod_tpu.obs.xprof.set_training_cost` told it the step's
    FLOPs).  Idempotent — every caller gets the same instruments."""
    r = registry if registry is not None else _default
    return _Namespace(
        step_time=r.histogram(
            "training_step_seconds",
            "Wall-clock duration of one training step "
            "(horovod_tpu.obs.training_step)", exist_ok=True),
        steps=r.counter(
            "training_steps_total",
            "Training steps completed", exist_ok=True),
        last_step=r.gauge(
            "training_last_step_seconds",
            "Wall-clock duration of the most recent training step "
            "(published in the elastic heartbeat payload)",
            exist_ok=True),
        mfu=r.gauge(
            "training_mfu",
            "Live model-FLOPs utilization of the last training step "
            "(step FLOPs / step seconds / chip peak; requires "
            "obs.xprof.set_training_cost)", exist_ok=True),
        compiles=r.counter(
            "xla_compiles_total",
            "XLA trace/compile events observed at instrumented jit sites",
            labels=("fn",), exist_ok=True),
    )


def elastic_metrics(registry: Optional[MetricsRegistry] = None) -> _Namespace:
    """Create-or-fetch the elastic metric family: supervised restarts,
    re-rendezvous count + current epoch, and the worker-side
    commit/rollback counters.  Idempotent."""
    r = registry if registry is not None else _default
    return _Namespace(
        restarts=r.counter(
            "elastic_restarts_total",
            "Elastic restarts (driver resets + worker-side retries)",
            exist_ok=True),
        rendezvous=r.counter(
            "elastic_rendezvous_total",
            "Rendezvous epochs started (driver) / re-inits (worker)",
            exist_ok=True),
        rendezvous_epoch=r.gauge(
            "elastic_rendezvous_epoch",
            "Current rendezvous epoch", exist_ok=True),
        commits=r.counter(
            "elastic_commits_total",
            "State.commit() calls (committed-consistent boundaries)",
            exist_ok=True),
        rollbacks=r.counter(
            "elastic_rollbacks_total",
            "State.rollback() calls (uncommitted steps discarded)",
            exist_ok=True),
    )
