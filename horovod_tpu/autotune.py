"""Autotuning: Bayesian optimization of runtime knobs.

Reference: ``horovod/common/parameter_manager.{h,cc}`` (tunable-knob
manager, warmup/sample scoring by bytes/sec, winner broadcast via
``Controller::SynchronizeParameters``) and ``horovod/common/optim/`` —
Gaussian-process regression with an RBF kernel and Expected-Improvement
acquisition (``bayesian_optimization.h:93``, ``gaussian_process.{h,cc}``).

TPU re-design: XLA already schedules collectives, so the knob set changes
(SURVEY.md §7 hard-part #5).  What remains worth tuning on TPU:

* ``fusion_threshold`` — bucket bytes for the gradient-fusion transform
  (too small → many collective launches; too large → less overlap with
  backward compute);
* ``compression`` — {none, bf16} wire compression (categorical);

Score = throughput (bytes reduced per second) exactly like the reference.
The GP/EI core is a faithful re-implementation in numpy (host-side, tiny
problem sizes), not a port of the Eigen code.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcessRegressor:
    """GP with RBF kernel, exact inference via Cholesky.

    Mirrors ``common/optim/gaussian_process.{h,cc}`` at the math level.
    """

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6) -> None:
        self.length_scale = length_scale
        self.noise = noise
        self._x: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = a[:, None, :] - b[None, :, :]
        sq = np.sum(d * d, axis=-1)
        return np.exp(-0.5 * sq / (self.length_scale**2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        self._x = x
        self._ymean = y.mean() if y.size else 0.0
        self._ystd = y.std() + 1e-12
        yn = (y - self._ymean) / self._ystd
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._L = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._L, ks.T)
        var = np.clip(1.0 - np.sum(v * v, axis=0), 1e-12, None)
        return mu * self._ystd + self._ymean, np.sqrt(var) * self._ystd


class BayesianOptimization:
    """EI-driven optimizer over a box domain
    (``optim/bayesian_optimization.h``; acquisition maximized by random +
    local refinement instead of L-BFGS — equivalent at these dimensions)."""

    def __init__(
        self,
        bounds: Sequence[Tuple[float, float]],
        *,
        xi: float = 0.01,
        seed: int = 0,
    ) -> None:
        self.bounds = np.asarray(bounds, np.float64)
        self.xi = xi
        self.gp = GaussianProcessRegressor(length_scale=0.3)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self._rng = np.random.RandomState(seed)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / (hi - lo)

    def _denormalize(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def register(self, x: Sequence[float], y: float) -> None:
        self.xs.append(self._normalize(np.asarray(x, np.float64)))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def expected_improvement(self, u: np.ndarray) -> np.ndarray:
        """EI(u) = (mu - best - xi) Phi(z) + sigma phi(z)
        (``bayesian_optimization.h:93``)."""
        mu, sigma = self.gp.predict(u)
        best = max(self.ys) if self.ys else 0.0
        imp = mu - best - self.xi
        z = imp / sigma
        phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1 + _erf(z / np.sqrt(2)))
        ei = imp * Phi + sigma * phi
        ei[sigma < 1e-10] = 0.0
        return ei

    def suggest(self) -> np.ndarray:
        if len(self.xs) < 3:  # bootstrap with random exploration
            u = self._rng.rand(self.bounds.shape[0])
            return self._denormalize(u)
        cand = self._rng.rand(512, self.bounds.shape[0])
        ei = self.expected_improvement(cand)
        return self._denormalize(cand[int(np.argmax(ei))])


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz & Stegun 7.1.26, vectorized; enough precision for EI.
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    y = 1.0 - (
        ((((1.061405429 * t - 1.453152027) * t) + 1.421413741) * t - 0.284496736)
        * t
        + 0.254829592
    ) * t * np.exp(-x * x)
    return sign * y


@dataclass
class CategoricalParam:
    """A discretely-valued knob tuned by exhaustive sweep
    (``parameter_manager.h`` ``CategoricalParameter``)."""

    name: str
    values: List

    def __post_init__(self) -> None:
        self.best_idx = 0


def _default_categoricals() -> List[CategoricalParam]:
    """Default categorical knob set: response-cache capacity (0 disables
    the bit-vector fast path, ``HOROVOD_CACHE_CAPACITY``).

    The reference also sweeps its hierarchical-collective toggles
    (``common.h:76-77``); here those are compile-time choices — toggling
    the env flag cannot change an already-traced step, so sweeping them by
    default would score identical executables and fix a winner from noise.
    They remain supported as explicit ``CategoricalParam``s for callers
    that rebuild/select step variants per window (consult ``.settings``
    each window, e.g. two precompiled steps).

    ``values[0]`` must be what the runtime is ACTUALLY running when the
    sweep reaches the param (the first window's score is attributed to it
    without an apply), so it is seeded from the env configuration."""
    cap = int(os.environ.get("HOROVOD_CACHE_CAPACITY", 1024))
    return [
        CategoricalParam("cache_capacity", [cap, 0 if cap != 0 else 1024]),
    ]


@dataclass
class Autotuner:
    """Parameter manager (``parameter_manager.h:42-246``): scores each
    sample window by bytes/sec and tunes, in reference order,

    1. warmup samples (discarded);
    2. categorical knobs by chained sweep — each value of each param gets
       one sample window while the others are held, best value is fixed
       before moving on (``CategoricalParameterChain``);
    3. the joint (fusion threshold MB, cycle time ms) box by Bayesian
       optimization (``BayesianParameter``), then freezes at the best
       seen.

    Cross-rank agreement: at every sample boundary the score is averaged
    across processes through the eager data plane, so each rank's tuner
    registers IDENTICAL scores and (with the shared RNG seed) proposes
    IDENTICAL next settings — the decentralized equivalent of the
    reference's rank-0 ``Controller::SynchronizeParameters`` broadcast.
    """

    warmup_samples: int = 3       # HOROVOD_AUTOTUNE_WARMUP_SAMPLES (common.h:67)
    steps_per_sample: int = 10    # HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE
    bo_samples: int = 12          # joint-BO budget before freezing
    log_path: Optional[str] = None  # HOROVOD_AUTOTUNE_LOG
    sync_scores: bool = True
    categoricals: List[CategoricalParam] = field(
        default_factory=_default_categoricals
    )
    # joint knobs: (log2 fusion threshold MB in [0,7] → 1..128 MB,
    #               cycle time ms in [0.5, 10])
    bo: BayesianOptimization = field(
        default_factory=lambda: BayesianOptimization(
            bounds=[(0.0, 7.0), (0.5, 10.0)]
        )
    )

    def __post_init__(self) -> None:
        self._bytes = 0.0
        self._seconds = 0.0
        self._steps = 0
        self._samples_seen = 0
        # Seed the joint knobs from the user's env settings when present
        # (the reference ParameterManager starts from the configured
        # values): HOROVOD_FUSION_THRESHOLD bytes / HOROVOD_CYCLE_TIME ms.
        thr = int(os.environ.get("HOROVOD_FUSION_THRESHOLD")
                  or self._threshold_from_knob(6.0))
        cyc = float(os.environ.get("HOROVOD_CYCLE_TIME") or 1.0)
        lo, hi = self.bo.bounds[0]
        knob0 = float(np.clip(np.log2(max(thr, 1) / (1024 * 1024)), lo, hi))
        lo1, hi1 = self.bo.bounds[1]
        self._knobs = (knob0, float(np.clip(cyc, lo1, hi1)))
        self._current = {
            "fusion_threshold": thr,
            "cycle_time_ms": cyc,
        }
        for p in self.categoricals:
            self._current[p.name] = p.values[0]
        self._best: Tuple[float, Dict] = (-1.0, dict(self._current))
        self._active = True
        # phase machine: warmup → cat(i, j) sweeps → bo → frozen.
        # warmup_samples=0 starts directly in the first tuning phase so the
        # first window's score is credited instead of discarded.
        if self.warmup_samples > 0:
            self._phase = "warmup"
        else:
            self._phase = "cat" if self.categoricals else "bo"
        self._cat_i = 0
        self._cat_j = 0
        self._cat_scores: List[float] = []
        if self.log_path:
            self._log_file = open(self.log_path, "w", newline="")
            self._log = csv.writer(self._log_file)
            self._log.writerow(["sample", "phase", "settings", "score_bytes_per_sec"])
        else:
            self._log = None

    @classmethod
    def from_env(cls) -> "Autotuner":
        return cls(
            warmup_samples=int(os.environ.get("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3)),
            steps_per_sample=int(
                os.environ.get("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10)
            ),
            log_path=os.environ.get("HOROVOD_AUTOTUNE_LOG") or None,
        )

    @staticmethod
    def _threshold_from_knob(knob: float) -> int:
        return int(2 ** float(knob) * 1024 * 1024)

    # ---- current settings -------------------------------------------------

    @property
    def fusion_threshold(self) -> int:
        """Current fusion threshold to use for the next step."""
        return self._current["fusion_threshold"]

    @property
    def cycle_time_ms(self) -> float:
        return self._current["cycle_time_ms"]

    @property
    def settings(self) -> Dict:
        """All current knob settings (reference ParameterManager state)."""
        return dict(self._current)

    @property
    def active(self) -> bool:
        return self._active

    # ---- scoring ----------------------------------------------------------

    def record(self, nbytes: float, seconds: float) -> None:
        """Report one step's reduced-byte volume and duration
        (``ParameterManager::Update``, scored in bytes/sec)."""
        if not self._active:
            return
        self._bytes += nbytes
        self._seconds += seconds
        self._steps += 1
        if self._steps < self.steps_per_sample:
            return
        score = self._bytes / max(self._seconds, 1e-9)
        self._bytes = self._seconds = 0.0
        self._steps = 0
        score = self._sync_score(score)
        self._samples_seen += 1
        if self._log:
            self._log.writerow(
                [self._samples_seen, self._phase, repr(self._current), score]
            )
            self._log_file.flush()
        self._advance(score)

    def _sync_score(self, score: float) -> float:
        """Average the window score across processes so every rank's tuner
        sees the same value and the per-rank state machines stay in
        lockstep (all ranks reach the boundary at the same step count)."""
        if not self.sync_scores:
            return score
        from horovod_tpu import basics

        if not basics.is_initialized() or basics.num_processes() <= 1:
            return score
        from horovod_tpu.ops import collectives as C

        out = C.allreduce(
            np.asarray([score], np.float64),
            C.Average,
            name=f"autotune.score.{self._samples_seen}",
        )
        return float(np.asarray(out)[0])

    # ---- phase machine ------------------------------------------------------

    def _advance(self, score: float) -> None:
        if self._phase == "warmup":
            if self._samples_seen >= self.warmup_samples:
                self._phase = "cat" if self.categoricals else "bo"
                if self._phase == "cat":
                    self._apply({self.categoricals[0].name:
                                 self.categoricals[0].values[0]})
            return
        if self._phase == "cat":
            self._advance_categorical(score)
            return
        if self._phase == "bo":
            self._advance_bo(score)

    def _advance_categorical(self, score: float) -> None:
        param = self.categoricals[self._cat_i]
        self._cat_scores.append(score)
        if score > self._best[0]:
            self._best = (score, dict(self._current))
        if self._cat_j + 1 < len(param.values):
            # next value of the same param
            self._cat_j += 1
            self._apply({param.name: param.values[self._cat_j]})
            return
        # sweep of this param done: fix the best value
        param.best_idx = int(np.argmax(self._cat_scores))
        self._apply({param.name: param.values[param.best_idx]})
        self._cat_scores = []
        self._cat_j = 0
        self._cat_i += 1
        if self._cat_i >= len(self.categoricals):
            self._phase = "bo"

    def _advance_bo(self, score: float) -> None:
        self.bo.register(list(self._knobs), score)
        if score > self._best[0]:
            self._best = (score, dict(self._current))
        if len(self.bo.ys) >= self.bo_samples:  # converge: freeze at best
            self._apply(self._best[1])
            self._active = False
            if self._log:
                self._log_file.close()
                self._log = None
            return
        knobs = self.bo.suggest()
        self._knobs = (float(knobs[0]), float(knobs[1]))
        self._apply(
            {
                "fusion_threshold": self._threshold_from_knob(self._knobs[0]),
                "cycle_time_ms": self._knobs[1],
            }
        )

    # ---- application ---------------------------------------------------------

    def _apply(self, settings: Dict) -> None:
        """Apply knob settings to the live runtime.  Safe to call on every
        rank: settings are identical by construction (synced scores +
        shared seed).  Cycle time is per-rank local and the bit-vector
        protocol pads cache-capacity races, but the FUSION threshold must
        never differ across ranks for the same response stream (ranks
        would group allreduces differently → mismatched global
        collectives), so threshold changes are applied behind a native
        BARRIER flush: after the barrier, no op negotiated under the old
        threshold is outstanding anywhere, and ops submitted later can
        only become ready once every rank has also passed its _apply at
        the same step boundary."""
        self._current.update(settings)
        try:
            from horovod_tpu import eager_runtime

            rt = eager_runtime.get()
        except Exception:  # pragma: no cover - defensive
            rt = None
        if rt is not None and "fusion_threshold" in settings:
            rt.barrier()
        for k, v in settings.items():
            if k == "fusion_threshold" and rt is not None:
                rt.set_fusion_bytes(int(v))
            elif k == "cycle_time_ms" and rt is not None:
                rt.set_cycle_ms(float(v))
            elif k == "cache_capacity" and rt is not None:
                rt.set_cache_capacity(int(v))
            elif k in ("hierarchical_allreduce", "hierarchical_allgather"):
                # Read at trace/build time by the in-graph ops
                # (ops/collectives.py hierarchical_*_enabled) — affects
                # steps built after this point; running compiled steps are
                # immutable, so callers doing variant selection should
                # consult .settings each window.
                os.environ["HOROVOD_" + k.upper()] = "1" if v else "0"

    def synchronize(self) -> None:
        """Broadcast the current settings from rank 0 and apply them — the
        explicit analogue of ``Controller::SynchronizeParameters``
        (``controller.cc:33-47``).  With ``sync_scores`` the per-rank
        tuners already agree; this is the belt-and-braces path for callers
        that disabled score syncing."""
        from horovod_tpu import state as S

        self._apply(dict(S.broadcast_object(self._current, 0)))
