"""Autotuning: Bayesian optimization of runtime knobs.

Reference: ``horovod/common/parameter_manager.{h,cc}`` (tunable-knob
manager, warmup/sample scoring by bytes/sec, winner broadcast via
``Controller::SynchronizeParameters``) and ``horovod/common/optim/`` —
Gaussian-process regression with an RBF kernel and Expected-Improvement
acquisition (``bayesian_optimization.h:93``, ``gaussian_process.{h,cc}``).

TPU re-design: XLA already schedules collectives, so the knob set changes
(SURVEY.md §7 hard-part #5).  What remains worth tuning on TPU:

* ``fusion_threshold`` — bucket bytes for the gradient-fusion transform
  (too small → many collective launches; too large → less overlap with
  backward compute);
* ``compression`` — {none, bf16} wire compression (categorical);

Score = throughput (bytes reduced per second) exactly like the reference.
The GP/EI core is a faithful re-implementation in numpy (host-side, tiny
problem sizes), not a port of the Eigen code.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcessRegressor:
    """GP with RBF kernel, exact inference via Cholesky.

    Mirrors ``common/optim/gaussian_process.{h,cc}`` at the math level.
    """

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6) -> None:
        self.length_scale = length_scale
        self.noise = noise
        self._x: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d = a[:, None, :] - b[None, :, :]
        sq = np.sum(d * d, axis=-1)
        return np.exp(-0.5 * sq / (self.length_scale**2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        self._x = x
        self._ymean = y.mean() if y.size else 0.0
        self._ystd = y.std() + 1e-12
        yn = (y - self._ymean) / self._ystd
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._L = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._L, ks.T)
        var = np.clip(1.0 - np.sum(v * v, axis=0), 1e-12, None)
        return mu * self._ystd + self._ymean, np.sqrt(var) * self._ystd


class BayesianOptimization:
    """EI-driven optimizer over a box domain
    (``optim/bayesian_optimization.h``; acquisition maximized by random +
    local refinement instead of L-BFGS — equivalent at these dimensions)."""

    def __init__(
        self,
        bounds: Sequence[Tuple[float, float]],
        *,
        xi: float = 0.01,
        seed: int = 0,
    ) -> None:
        self.bounds = np.asarray(bounds, np.float64)
        self.xi = xi
        self.gp = GaussianProcessRegressor(length_scale=0.3)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self._rng = np.random.RandomState(seed)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / (hi - lo)

    def _denormalize(self, u: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def register(self, x: Sequence[float], y: float) -> None:
        self.xs.append(self._normalize(np.asarray(x, np.float64)))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def expected_improvement(self, u: np.ndarray) -> np.ndarray:
        """EI(u) = (mu - best - xi) Phi(z) + sigma phi(z)
        (``bayesian_optimization.h:93``)."""
        mu, sigma = self.gp.predict(u)
        best = max(self.ys) if self.ys else 0.0
        imp = mu - best - self.xi
        z = imp / sigma
        phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1 + _erf(z / np.sqrt(2)))
        ei = imp * Phi + sigma * phi
        ei[sigma < 1e-10] = 0.0
        return ei

    def suggest(self) -> np.ndarray:
        if len(self.xs) < 3:  # bootstrap with random exploration
            u = self._rng.rand(self.bounds.shape[0])
            return self._denormalize(u)
        cand = self._rng.rand(512, self.bounds.shape[0])
        ei = self.expected_improvement(cand)
        return self._denormalize(cand[int(np.argmax(ei))])


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz & Stegun 7.1.26, vectorized; enough precision for EI.
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    y = 1.0 - (
        ((((1.061405429 * t - 1.453152027) * t) + 1.421413741) * t - 0.284496736)
        * t
        + 0.254829592
    ) * t * np.exp(-x * x)
    return sign * y


@dataclass
class Autotuner:
    """Parameter manager (``parameter_manager.h:42-246``): scores each
    sample window by bytes/sec, proposes the next knob setting, converges to
    the best seen, and can synchronize the winner across processes."""

    warmup_samples: int = 3       # HOROVOD_AUTOTUNE_WARMUP_SAMPLES (common.h:67)
    steps_per_sample: int = 10    # HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE
    log_path: Optional[str] = None  # HOROVOD_AUTOTUNE_LOG
    # knob: log2 of fusion threshold MB in [0, 7] → 1 MB .. 128 MB
    bo: BayesianOptimization = field(
        default_factory=lambda: BayesianOptimization(bounds=[(0.0, 7.0)])
    )

    def __post_init__(self) -> None:
        self._samples_seen = 0
        self._bytes = 0.0
        self._seconds = 0.0
        self._steps = 0
        self._current = self._threshold_from_knob(6.0)  # 64 MB default
        self._current_knob = 6.0
        self._best: Tuple[float, int] = (-1.0, self._current)
        self._active = True
        if self.log_path:
            self._log_file = open(self.log_path, "w", newline="")
            self._log = csv.writer(self._log_file)
            self._log.writerow(["sample", "fusion_threshold", "score_bytes_per_sec"])
        else:
            self._log = None

    @classmethod
    def from_env(cls) -> "Autotuner":
        return cls(
            warmup_samples=int(os.environ.get("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3)),
            steps_per_sample=int(
                os.environ.get("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10)
            ),
            log_path=os.environ.get("HOROVOD_AUTOTUNE_LOG") or None,
        )

    @staticmethod
    def _threshold_from_knob(knob: float) -> int:
        return int(2 ** float(knob) * 1024 * 1024)

    @property
    def fusion_threshold(self) -> int:
        """Current fusion threshold to use for the next step."""
        return self._current

    @property
    def active(self) -> bool:
        return self._active

    def record(self, nbytes: float, seconds: float) -> None:
        """Report one step's reduced-byte volume and duration
        (``ParameterManager::Update``, scored in bytes/sec)."""
        if not self._active:
            return
        self._bytes += nbytes
        self._seconds += seconds
        self._steps += 1
        if self._steps < self.steps_per_sample:
            return
        score = self._bytes / max(self._seconds, 1e-9)
        self._samples_seen += 1
        if self._log:
            self._log.writerow([self._samples_seen, self._current, score])
            self._log_file.flush()
        if self._samples_seen > self.warmup_samples:
            self.bo.register([self._current_knob], score)
            if score > self._best[0]:
                self._best = (score, self._current)
            knob = float(self.bo.suggest()[0])
        else:
            knob = self._current_knob  # warmup: keep defaults, discard score
        self._current_knob = knob
        self._current = self._threshold_from_knob(knob)
        self._bytes = self._seconds = 0.0
        self._steps = 0
        if len(self.bo.ys) >= 12:  # converge: freeze at best
            self._current = self._best[1]
            self._active = False
            if self._log:
                self._log_file.close()
        # NOTE: the new threshold is NOT applied to the native planner here.
        # Per-rank scores (and therefore suggestions) differ, and fusion
        # grouping must be identical on every rank or collectives mismatch;
        # call synchronize() to broadcast rank 0's choice and apply it.

    def _push_to_native(self) -> None:
        """Apply the (synchronized) threshold to the native fusion planner
        so the eager path buckets at the tuned size (the reference applies
        ParameterManager output to TensorFusionThresholdBytes only after
        Controller::SynchronizeParameters)."""
        try:
            from horovod_tpu import eager_runtime

            rt = eager_runtime.get()
            if rt is not None:
                rt.set_fusion_bytes(self._current)
        except Exception:  # pragma: no cover - defensive
            pass

    def synchronize(self) -> None:
        """Broadcast the winning threshold from rank 0 so all processes
        fuse identically (``Controller::SynchronizeParameters``,
        ``controller.cc:33-47``)."""
        from horovod_tpu import state as S

        self._current = int(S.broadcast_object(self._current, 0))
        self._push_to_native()
