"""High-level gradient-averaging API: ``DistributedOptimizer`` and
``DistributedGradientTape`` equivalents.

Reference: ``horovod/tensorflow/__init__.py:230-531`` (``_make_allreduce_
grads_fn``, ``_DistributedOptimizer``, ``DistributedGradientTape``) and
``horovod/torch/__init__.py:61-216`` (per-parameter hook optimizer with
``backward_passes_per_step`` accumulation).

TPU re-design: the optimizer is an **optax gradient transformation** — the
allreduce is a pure function inside the compiled train step, so XLA overlaps
it with the backward pass the way the reference's background thread did
dynamically, but with a static schedule.  There are no hooks, handles, or
``synchronize()``: data dependencies express completion.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.ops import collectives as C
from horovod_tpu.ops import fusion as F
from horovod_tpu.ops.compression import Compression


def distributed_gradients(
    grads,
    op: str = C.Average,
    *,
    axis_name=None,
    compression=Compression.none,
    fuse: bool = True,
    fusion_threshold: Optional[int] = None,
    sparse_keys=(),
):
    """Allreduce a gradient pytree (the reference's
    ``_make_allreduce_grads_fn``, ``tensorflow/__init__.py:230-251``).

    ``fuse=True`` buckets leaves into large flat collectives
    (:mod:`horovod_tpu.ops.fusion`); compression casts to 16-bit for the
    wire and restores dtype after (``tensorflow/compression.py``).

    ``sparse_keys``: tree-path substrings (e.g. ``("embed",)``) whose
    EAGER leaves reduce by allgathering touched rows instead of the
    dense allreduce — the reference's IndexedSlices path
    (``tensorflow/__init__.py:74-89``), re-created for JAX's dense
    lookup VJPs by row-sparsity detection
    (:func:`horovod_tpu.ops.sparse.sparse_allreduce`).  Traced leaves
    (inside jit) always reduce dense — static shapes; compression is
    not applied to the sparse leaves (their values ride the wire
    already-small)."""
    if sparse_keys and op in (C.Average, C.Sum):
        from horovod_tpu.ops import sparse as SP

        treedef, dense, sparse = SP.split_sparse_leaves(
            grads, tuple(sparse_keys))
        if sparse:
            idx = [i for i, l in enumerate(dense) if l is not None]
            reduced = distributed_gradients(
                [dense[i] for i in idx], op, axis_name=axis_name,
                compression=compression, fuse=fuse,
                fusion_threshold=fusion_threshold)
            out = [None] * len(dense)
            for i, r in zip(idx, reduced):
                out[i] = r
            red_sparse = [
                (i, SP.sparse_allreduce(leaf, op, name=f"sparse.{i}"))
                for i, _key, leaf in sparse
            ]
            return SP.merge_sparse_leaves(treedef, out, red_sparse)
    grads, ctx = compression.compress(grads)
    if fuse and op in (C.Average, C.Sum):
        out = F.fused_allreduce_tree(
            grads, op, axis_name=axis_name, threshold=fusion_threshold
        )
    else:
        out = C.allreduce(grads, op, axis_name=axis_name)
    return compression.decompress(out, ctx)


class _AccumState(NamedTuple):
    inner: Any
    acc: Any
    counter: jnp.ndarray


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: str = C.Average,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    average_aggregated_gradients: bool = True,
    axis_name=None,
    fuse: bool = True,
    fusion_threshold: Optional[int] = None,
    sparse_keys=(),
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates are computed from
    cross-worker-reduced gradients.

    Reference semantics matched:

    * ``op=Average|Sum|Adasum`` (``tensorflow/__init__.py:410-471``).
    * ``backward_passes_per_step`` accumulates gradients locally and only
      allreduces (and steps) every k-th call; non-boundary calls return zero
      updates (``torch/__init__.py:95-157``).
    * ``average_aggregated_gradients`` divides the accumulated sum by k
      before reduction (``tensorflow/__init__.py:328-365``).
    * ``sparse_keys`` — embedding-shaped leaves reduce sparsely on the
      eager path (see :func:`distributed_gradients`; the reference's
      IndexedSlices allgather, ``tensorflow/__init__.py:74-89``).
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    def _reduce(grads):
        return distributed_gradients(
            grads,
            op,
            axis_name=axis_name,
            compression=compression,
            fuse=fuse,
            fusion_threshold=fusion_threshold,
            sparse_keys=sparse_keys,
        )

    if backward_passes_per_step == 1:

        def init_fn(params):
            return optimizer.init(params)

        def update_fn(grads, state, params=None, **extra):
            return optimizer.update(_reduce(grads), state, params, **extra)

        return optax.GradientTransformation(init_fn, update_fn)

    k = backward_passes_per_step

    def init_fn(params):
        return _AccumState(
            inner=optimizer.init(params),
            acc=jax.tree_util.tree_map(jnp.zeros_like, params),
            counter=jnp.zeros((), jnp.int32),
        )

    def update_fn(grads, state, params=None, **extra):
        acc = jax.tree_util.tree_map(lambda a, g: a + g, state.acc, grads)
        count = state.counter + 1
        boundary = count >= k

        def do_step(operands):
            acc, inner, params = operands
            scale = 1.0 / k if average_aggregated_gradients else 1.0
            scaled = jax.tree_util.tree_map(
                lambda a: a * jnp.asarray(scale, a.dtype), acc
            )
            reduced = _reduce(scaled)
            updates, inner2 = optimizer.update(reduced, inner, params, **extra)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return updates, inner2, zeroed

        def skip_step(operands):
            acc, inner, _params = operands
            updates = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return updates, inner, acc

        updates, inner, acc = jax.lax.cond(
            boundary, do_step, skip_step, (acc, state.inner, params)
        )
        counter = jnp.where(boundary, 0, count)
        return updates, _AccumState(inner=inner, acc=acc, counter=counter)

    return optax.GradientTransformation(init_fn, update_fn)


class _AdasumDeltaState(NamedTuple):
    inner: Any
    start: Any       # params at the last sync (None when k == 1)
    counter: jnp.ndarray


def DistributedAdasumOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name=None,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
) -> optax.GradientTransformation:
    """Delta-model Adasum: combine LOCAL OPTIMIZER UPDATES, not gradients.

    The published Adasum usage mode (reference
    ``tensorflow/__init__.py:313-407`` ``_DistributedAdasumOptimizer``,
    ``torch/__init__.py:219-407``): each worker applies its own optimizer
    step, and the resulting parameter delta — which already carries the
    optimizer's adaptive scaling — is Adasum-allreduced, so the
    scale-insensitive pairwise combination operates on actual model
    movement:

        start  = params at the last sync
        local  = params + inner_update(grads)          (optimizer logic)
        delta  = local - start
        global = adasum_allreduce(delta)
        params = start + global

    In optax terms the inner update IS the per-step delta, so with
    ``backward_passes_per_step == 1`` no snapshot is needed: the returned
    update is ``adasum(inner_update)``.  With k > 1, updates apply
    locally for k-1 steps (workers drift) and the k-th step reduces the
    CUMULATIVE drift from ``start``, mirroring the reference's
    ``_is_comm_step`` handling.
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    from horovod_tpu.ops import adasum as AD

    def _adasum(tree):
        tree, ctx = compression.compress(tree)
        out = AD.adasum_allreduce(tree, axis_name=axis_name)
        return compression.decompress(out, ctx)

    if backward_passes_per_step == 1:

        def init_fn(params):
            return optimizer.init(params)

        def update_fn(grads, state, params=None, **extra):
            updates, inner = optimizer.update(grads, state, params, **extra)
            return _adasum(updates), inner

        return optax.GradientTransformation(init_fn, update_fn)

    k = backward_passes_per_step

    def init_fn(params):
        return _AdasumDeltaState(
            inner=optimizer.init(params),
            start=jax.tree_util.tree_map(jnp.asarray, params),
            counter=jnp.zeros((), jnp.int32),
        )

    def update_fn(grads, state, params=None, **extra):
        if params is None:
            raise ValueError(
                "DistributedAdasumOptimizer with backward_passes_per_step "
                "> 1 needs params passed to update()")
        local_updates, inner = optimizer.update(
            grads, state.inner, params, **extra)
        count = state.counter + 1
        boundary = count >= k

        def do_sync(operands):
            local_updates, params, start = operands
            # Cumulative drift since the last sync, including this step's
            # local update.
            delta = jax.tree_util.tree_map(
                lambda p, u, s: p + u - s, params, local_updates, start)
            global_delta = _adasum(delta)
            new_start = jax.tree_util.tree_map(
                lambda s, g: s + g, start, global_delta)
            updates = jax.tree_util.tree_map(
                lambda ns, p: ns - p, new_start, params)
            return updates, new_start

        def skip_sync(operands):
            local_updates, _params, start = operands
            return local_updates, start

        updates, start = jax.lax.cond(
            boundary, do_sync, skip_sync, (local_updates, params, state.start)
        )
        counter = jnp.where(boundary, 0, count)
        return updates, _AdasumDeltaState(
            inner=inner, start=start, counter=counter)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTape(
    fun,
    *,
    op: str = C.Average,
    compression=Compression.none,
    axis_name=None,
    has_aux: bool = False,
    fuse: bool = True,
    sparse_keys=(),
):
    """Return ``value_and_grad(fun)`` whose gradients are allreduced.

    JAX analogue of ``hvd.DistributedGradientTape``
    (``tensorflow/__init__.py:474-531``): TF tapes record eagerly, JAX
    differentiates functionally, so the "tape" is a transformed
    ``value_and_grad``.  ``sparse_keys`` routes embedding-shaped leaves
    through the sparse (indices, values) allgather on the eager path —
    the IndexedSlices analogue.

        loss, grads = hvd.DistributedGradientTape(loss_fn)(params, batch)
    """
    vg = jax.value_and_grad(fun, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        val, grads = vg(*args, **kwargs)
        grads = distributed_gradients(
            grads, op, axis_name=axis_name, compression=compression,
            fuse=fuse, sparse_keys=sparse_keys
        )
        return val, grads

    return wrapped
