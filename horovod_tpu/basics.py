"""Process/topology core: init, rank/size queries, and the device mesh.

TPU-native re-design of the reference's basics layer
(``horovod/common/basics.py:22-211`` and the C API in
``horovod/common/operations.cc:650-788``).  Differences by design:

* A *worker* is a TPU chip (device), not a process.  One Python process per
  host drives all local chips SPMD-style, so ``size()`` is the total device
  count and ``local_size()`` the per-host device count.  The reference's
  GLOBAL / LOCAL / CROSS communicator triple (``common/common.h:110-114``)
  maps onto a 2-D device mesh with axes ``(cross, local)``: ``local`` rides
  ICI within a host/slice, ``cross`` rides DCN between hosts.
* There is no background thread or negotiation at init: topology is known
  statically from the JAX process environment, and collectives issued inside
  ``jit`` are compiled to XLA collectives whose schedule is identical on all
  processes by SPMD construction (see SURVEY.md §7).
* Multi-process bootstrap replaces MPI_Init (``mpi/mpi_context.cc:103-111``)
  with the JAX distributed runtime: the launcher exports ``HOROVOD_RANK`` /
  ``HOROVOD_SIZE`` / ``HOROVOD_COORDINATOR_ADDR`` and we call
  ``jax.distributed.initialize``.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger("horovod_tpu")

# HOROVOD_LOG_LEVEL values, matching the reference's leveled logger
# (common/logging.{h,cc}; exported by the launcher's --log-level flag).
_LOG_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}


def _configure_logging() -> None:
    """Apply HOROVOD_LOG_LEVEL to the ``horovod_tpu`` logger.  The native
    runtime reads the same variable itself (native/src/logging.h)."""
    raw = os.environ.get("HOROVOD_LOG_LEVEL", "").lower()
    if not raw:
        return
    if raw not in _LOG_LEVELS:
        logger.warning("HOROVOD_LOG_LEVEL=%r not recognized; using warning", raw)
    logger.setLevel(_LOG_LEVELS.get(raw, logging.WARNING))
    if not logger.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s %(levelname)s] %(message)s")
        )
        logger.addHandler(h)

# Default mesh axis name for the flat worker axis (the reference's GLOBAL
# communicator).  All collective ops default to this axis.
AXIS: str = "hvd"
# Hierarchical axis names (reference LOCAL / CROSS communicators).
LOCAL_AXIS: str = "local"
CROSS_AXIS: str = "cross"


class NotInitializedError(RuntimeError):
    """Raised when the API is used before ``init()``.

    Mirrors ``CheckInitialized`` (``common/operations.cc:643``) which raises
    "Horovod has not been initialized; use hvd.init()".
    """

    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; use horovod_tpu.init()."
        )


@dataclass
class _Context:
    """Singleton runtime state (analogue of ``HorovodGlobalState``,
    ``common/global_state.h:42-122`` — minus everything SPMD compilation
    makes unnecessary: tensor queue, fusion buffer, response cache live in
    the eager runtime module instead)."""

    mesh: Mesh
    hierarchical_mesh: Optional[Mesh]
    process_rank: int
    num_processes: int
    local_device_count: int
    axis_name: str = AXIS
    elastic_enabled: bool = False
    timeline: Optional[object] = None  # horovod_tpu.timeline.Timeline
    autotuner: Optional[object] = None
    lock: threading.Lock = field(default_factory=threading.Lock)


_context: Optional[_Context] = None


def _parse_env_int(*names: str) -> Optional[int]:
    for n in names:
        v = os.environ.get(n)
        if v is not None and v != "":
            try:
                return int(v)
            except ValueError:
                raise ValueError(f"Environment variable {n}={v!r} is not an int")
    return None


def _bootstrap_distributed() -> None:
    """Connect this process to the cluster coordination service.

    Replaces the reference's MPI bootstrap + Gloo HTTP rendezvous
    (``gloo/gloo_context.cc:113-160``): the launcher exports
    ``HOROVOD_RANK``/``HOROVOD_SIZE``/``HOROVOD_COORDINATOR_ADDR`` and every
    process dials the JAX coordination service instead of an MPI runtime.
    """
    nproc = _parse_env_int("HOROVOD_NUM_PROC", "HOROVOD_CROSS_SIZE")
    rank = _parse_env_int("HOROVOD_RANK", "HOROVOD_CROSS_RANK")
    addr = os.environ.get("HOROVOD_COORDINATOR_ADDR") or os.environ.get(
        "HOROVOD_GLOO_RENDEZVOUS_ADDR"
    )
    if nproc is None or nproc <= 1:
        return
    # Must not touch the XLA backend before jax.distributed.initialize
    # (jax.process_count() would initialize it); inspect the coordination
    # client state directly.
    try:
        from jax._src import distributed as _jd

        if _jd.global_state.client is not None:
            return  # already initialized (e.g. by the TPU runtime itself)
    except Exception:
        if jax.process_count() >= nproc:
            return
    # The JAX coordination service needs its own port: the launcher's
    # HOROVOD_COORDINATOR_PORT is the rendezvous KV server, so rank 0 binds
    # KV+2 for the gRPC service unless HOROVOD_JAX_PORT says otherwise.
    jax_port = os.environ.get("HOROVOD_JAX_PORT")
    if jax_port is None:
        base = os.environ.get("HOROVOD_COORDINATOR_PORT")
        jax_port = str(int(base) + 2) if base else "9373"
    if addr is None:
        addr = f"127.0.0.1:{jax_port}"
    elif ":" not in addr:
        addr = f"{addr}:{jax_port}"
    # Older JAX gates cross-process CPU collectives behind a config
    # option (newer builds enable them by default; the option is gone).
    # Without it a multi-process CPU job fails at the first collective
    # with "Multiprocess computations aren't implemented on the CPU
    # backend" — enable gloo before the backend initializes.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=nproc, process_id=rank
    )


def _build_meshes(devices: Sequence[jax.Device], axis_name: str):
    """Build the flat worker mesh and, when the topology is homogeneous,
    the hierarchical ``(cross, local)`` mesh.

    Device order is (process, local-device) lexicographic so that worker
    rank = process_rank * local_size + local_index, matching the rank layout
    the reference computes in ``MPIController::Initialize``
    (``mpi/mpi_controller.cc:25-81``).
    """
    devs = sorted(devices, key=lambda d: (d.process_index, d.id))
    arr = np.array(devs, dtype=object)
    mesh = Mesh(arr, axis_names=(axis_name,))

    # Homogeneity check (reference: is_homogeneous_,
    # mpi/mpi_controller.cc — all nodes must have equal local_size for
    # hierarchical ops to be enabled).
    per_proc: dict[int, int] = {}
    for d in devs:
        per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
    sizes = set(per_proc.values())
    hier = None
    if len(sizes) == 1:
        local = sizes.pop()
        cross = len(per_proc)
        if cross * local == len(devs):
            hier = Mesh(
                arr.reshape(cross, local), axis_names=(CROSS_AXIS, LOCAL_AXIS)
            )
    return mesh, hier


def init(
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = AXIS,
) -> None:
    """Initialize horovod_tpu.  Idempotent.

    Analogue of ``hvd.init()`` → ``horovod_init`` → ``InitializeHorovodOnce``
    (``common/operations.cc:593-639``), except nothing asynchronous happens:
    there is no background thread to spawn because collective scheduling is
    done by XLA at compile time.  What remains is (1) optional multi-process
    bootstrap, (2) mesh construction, (3) auxiliary-subsystem setup
    (timeline, autotune) driven by the same ``HOROVOD_*`` env vars the
    reference parses in ``BackgroundThreadLoop``
    (``common/operations.cc:392-489``).
    """
    global _context
    if _context is not None:
        return
    _configure_logging()
    _bootstrap_distributed()
    if devices is None:
        try:
            devices = jax.devices()
        except RuntimeError as e:
            # A configured platform whose plugin is absent in THIS
            # process (e.g. an accelerator plugin selected by the parent
            # environment but not registered in launcher-spawned ranks)
            # should degrade to CPU with a warning, not kill the job.
            if "Unable to initialize backend" not in str(e):
                raise
            logger.warning(
                "configured JAX platform unavailable (%s); falling back "
                "to CPU", e)
            jax.config.update("jax_platforms", "cpu")
            devices = jax.devices()
    mesh, hier = _build_meshes(devices, axis_name)
    local = [d for d in devices if d.process_index == jax.process_index()]
    _context = _Context(
        mesh=mesh,
        hierarchical_mesh=hier,
        process_rank=jax.process_index(),
        num_processes=jax.process_count(),
        local_device_count=len(local) if local else len(devices),
        axis_name=axis_name,
    )

    # Native control-plane runtime (C++): negotiation/fusion/cache/stall/
    # timeline for the eager path.  Optional — without it eager ops run
    # directly in program order.
    native_rt = None
    try:
        from horovod_tpu import eager_runtime
        from horovod_tpu.timeline import expand_rank_path

        native_rt = eager_runtime.start(
            timeline_path=expand_rank_path(
                os.environ.get("HOROVOD_TIMELINE", ""))
        )
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("native runtime unavailable, using direct path: %s", e)

    # Auxiliary subsystems, env-gated exactly like the reference.  When the
    # native runtime is up it owns the HOROVOD_TIMELINE file (rank 0); the
    # Python Timeline otherwise.
    timeline_path = os.environ.get("HOROVOD_TIMELINE")
    if timeline_path and native_rt is None:
        from horovod_tpu.timeline import Timeline

        elastic = os.environ.get("HOROVOD_ELASTIC", "0") \
            not in ("", "0", "false")
        if "%r" in timeline_path:
            # Explicit per-rank substitution: every rank records its
            # own file (merge with `python -m horovod_tpu.obs.merge`).
            _context.timeline = Timeline(timeline_path)
        elif elastic and _context.num_processes > 1:
            # Elastic multi-process default: rank-suffix the path —
            # N respawning ranks all writing one literal path would
            # silently clobber each other's traces.
            root, ext = os.path.splitext(timeline_path)
            _context.timeline = Timeline(f"{root}.rank%r{ext or '.json'}")
        elif _context.process_rank == 0:  # rank 0 writes, like the reference
            _context.timeline = Timeline(timeline_path)
    if os.environ.get("HOROVOD_AUTOTUNE", "0") not in ("", "0", "false"):
        from horovod_tpu.autotune import Autotuner

        _context.autotuner = Autotuner.from_env()
    _register_process_metrics(_context)
    logger.debug(
        "horovod_tpu initialized: size=%d local_size=%d process=%d/%d",
        mesh.devices.size,
        _context.local_device_count,
        _context.process_rank,
        _context.num_processes,
    )


def _register_process_metrics(ctx: _Context) -> None:
    """Seed the process-wide observability registry at init: topology
    gauges plus the training and elastic metric FAMILIES (so a
    ``/metrics`` scrape always exposes them, zero-valued until used —
    probes should not have to special-case a cold process)."""
    try:
        from horovod_tpu.obs import registry as obs_registry

        r = obs_registry.default_registry()
        r.counter("horovod_inits_total",
                  "horovod_tpu.init() calls (re-inits included)",
                  exist_ok=True).inc()
        r.gauge("horovod_world_size", "Total workers (TPU chips)",
                exist_ok=True).set(ctx.mesh.devices.size)
        r.gauge("horovod_local_size", "Workers on this host",
                exist_ok=True).set(ctx.local_device_count)
        r.gauge("horovod_num_processes", "Processes in the job",
                exist_ok=True).set(ctx.num_processes)
        obs_registry.training_metrics()
        obs_registry.elastic_metrics()
        from horovod_tpu import timeline as _timeline_mod

        _timeline_mod._dropped_events_counter()
    except Exception as e:  # pragma: no cover - metrics never gate init
        logger.warning("observability registry unavailable: %s", e)


def shutdown() -> None:
    """Tear down runtime state (``horovod_shutdown``,
    ``common/operations.cc:652+``)."""
    global _context
    if _context is None:
        return
    try:
        from horovod_tpu import eager_runtime

        eager_runtime.stop()
    except Exception:  # pragma: no cover - defensive
        pass
    if _context.timeline is not None:
        _context.timeline.close()
    _context = None


def reinit(
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = AXIS,
) -> None:
    """Tear down and re-initialize the runtime (elastic restart path).

    Used by ``elastic.run`` after a membership change when the mesh can be
    rebuilt in-process: stops the eager runtime (closing its control-plane
    sockets), drops the context, and re-runs :func:`init` over the current
    environment/devices.  Multi-process jobs cannot re-rendezvous
    in-process (the JAX coordination service is bound to the dead world's
    membership) — the ElasticDriver respawns those ranks with fresh epoch
    env instead."""
    shutdown()
    init(devices=devices, axis_name=axis_name)
    try:
        from horovod_tpu.obs import tracing as obs_tracing
        from horovod_tpu.obs.registry import elastic_metrics

        elastic_metrics().rendezvous.inc()
        obs_tracing.instant("elastic_rerendezvous", {
            "epoch": os.environ.get("HOROVOD_ELASTIC_EPOCH"),
            "size": size()})
    except Exception:  # pragma: no cover - metrics never gate recovery
        pass


atexit.register(shutdown)


def is_initialized() -> bool:
    """``horovod_is_initialized`` equivalent."""
    return _context is not None


def _ctx() -> _Context:
    if _context is None:
        raise NotInitializedError()
    return _context


def mesh() -> Mesh:
    """The flat worker mesh (1-D, axis ``hvd``): the GLOBAL communicator."""
    return _ctx().mesh


def hierarchical_mesh() -> Optional[Mesh]:
    """The 2-D ``(cross, local)`` mesh, or None if hosts are heterogeneous.

    ``local`` maps to ICI within a host/slice and ``cross`` to DCN across
    hosts — the reference's LOCAL/CROSS communicators
    (``common/common.h:110-114``) realized as mesh axes.
    """
    return _ctx().hierarchical_mesh


def axis_name() -> str:
    return _ctx().axis_name


def size() -> int:
    """Total number of workers (TPU chips).  ``horovod_size``."""
    return int(_ctx().mesh.devices.size)


def local_size() -> int:
    """Workers on this host.  ``horovod_local_size``."""
    return _ctx().local_device_count


def cross_size() -> int:
    """Number of processes/hosts.  ``horovod_cross_size``."""
    return _ctx().num_processes


def rank() -> int:
    """Lowest global worker rank owned by this process.

    With one chip per process this equals the reference's ``horovod_rank``;
    with N local chips the process speaks for workers
    ``[rank(), rank() + local_size())``.  Inside compiled code use
    :func:`worker_index` for the per-chip rank.
    """
    c = _ctx()
    return c.process_rank * c.local_device_count


def local_rank() -> int:
    """Process-level local rank (0 for the first process on a host).

    The reference's ``horovod_local_rank`` identifies which GPU of the host a
    process drives; here a process drives all local chips, so this is 0 and
    the per-chip index lives in-graph (:func:`worker_index` modulo
    ``local_size``)."""
    return 0


def cross_rank() -> int:
    """Process index (host index).  ``horovod_cross_rank``."""
    return _ctx().process_rank


def process_rank() -> int:
    return _ctx().process_rank


def num_processes() -> int:
    return _ctx().num_processes


def is_homogeneous() -> bool:
    """True if all hosts drive the same number of chips
    (``horovod_is_homogeneous``, ``mpi/mpi_controller.cc``)."""
    return _ctx().hierarchical_mesh is not None


def worker_index(axis: Optional[str] = None):
    """Per-chip rank, traced: ``jax.lax.axis_index`` over the worker axis.

    Only valid inside ``shard_map``/``pmap`` where the axis is bound.
    """
    return jax.lax.axis_index(axis or _ctx().axis_name)


# --- build-capability introspection (reference: horovod/common/util.py &
# basics.py mpi_built/gloo_built/nccl_built/...) ------------------------------

def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def xla_built() -> bool:
    """The one true backend: XLA collectives over ICI/DCN."""
    return True


def mpi_threads_supported() -> bool:
    return False


def sharding_for(spec: PartitionSpec, *, hierarchical: bool = False) -> NamedSharding:
    """Convenience: a NamedSharding over the global (or hierarchical) mesh."""
    m = hierarchical_mesh() if hierarchical else mesh()
    if m is None:
        raise ValueError("hierarchical mesh unavailable (heterogeneous hosts)")
    return NamedSharding(m, spec)
