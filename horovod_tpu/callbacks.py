"""Training-loop callbacks and learning-rate schedules.

Reference: ``horovod/_keras/callbacks.py:20-185`` —
``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateScheduleCallback`` (with momentum correction),
``LearningRateWarmupCallback`` — re-exported for keras / tf.keras.

TPU re-design: two idiomatic forms are provided.  (1) Framework-neutral
callback objects with the Keras hook signature (``on_epoch_begin/end``,
``on_batch_begin/end``) usable with any loop, including
:class:`horovod_tpu.training.Loop`.  (2) Pure optax schedule factories
(:func:`warmup_schedule`, :func:`multiplier_schedule`) — on TPU the LR
schedule belongs inside the compiled step, not in a host callback, so these
are the recommended path.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

import horovod_tpu as hvd_mod  # resolved lazily to avoid cycles
from horovod_tpu import basics
from horovod_tpu.ops import collectives as C


class Callback:
    """Minimal Keras-compatible callback interface."""

    def set_model(self, model) -> None:
        self.model = model

    def on_train_begin(self, logs: Optional[Dict] = None) -> None: ...

    def on_epoch_begin(self, epoch: int, logs: Optional[Dict] = None) -> None: ...

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None) -> None: ...

    def on_batch_begin(self, batch: int, logs: Optional[Dict] = None) -> None: ...

    def on_batch_end(self, batch: int, logs: Optional[Dict] = None) -> None: ...


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast model/optimizer state from ``root_rank`` on train begin
    (``_keras/callbacks.py:20-43``).  The model object must expose
    ``params`` (and optionally ``opt_state``) attributes."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank

    def on_train_begin(self, logs=None) -> None:
        from horovod_tpu import state as S

        if hasattr(self, "model") and self.model is not None:
            if getattr(self.model, "params", None) is not None:
                self.model.params = S.broadcast_parameters(
                    self.model.params, self.root_rank
                )
            if getattr(self.model, "opt_state", None) is not None:
                self.model.opt_state = S.broadcast_optimizer_state(
                    self.model.opt_state, self.root_rank
                )


class MetricAverageCallback(Callback):
    """Allreduce-average numeric epoch metrics across workers
    (``_keras/callbacks.py:46-84``)."""

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        if not logs:
            return
        keys = sorted(
            k
            for k, v in logs.items()
            if isinstance(v, (int, float, np.floating, np.integer))
            and not isinstance(v, bool)
            or getattr(v, "ndim", None) == 0
        )
        if not keys:
            return
        vals = np.asarray([float(logs[k]) for k in keys], np.float64)
        avg = C.allreduce(vals.astype(np.float32), C.Average)
        for k, v in zip(keys, np.asarray(avg)):
            logs[k] = float(v)


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` within
    ``[start_epoch, end_epoch)`` (``_keras/callbacks.py:87-150``).

    ``model.lr`` (a float attribute or a 0-d array in
    ``model.hyperparams['learning_rate']``) is updated in place.  With
    ``staircase=False`` the multiplier is evaluated per batch at fractional
    epochs, matching the reference.  Momentum correction is not needed: on
    TPU the schedule feeds optax's ``inject_hyperparams`` and the optimizer
    state is scale-invariant in optax's formulation.
    """

    def __init__(
        self,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
        staircase: bool = True,
        steps_per_epoch: Optional[int] = None,
        initial_lr: Optional[float] = None,
    ) -> None:
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = initial_lr
        self.current_epoch = 0
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _in_range(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _base_lr(self) -> float:
        if self.initial_lr is None:
            raise ValueError(
                "initial_lr must be set (the reference reads it from the "
                "Keras optimizer; pass it explicitly here)"
            )
        return self.initial_lr

    def _apply(self, epoch: float) -> None:
        if not self._in_range(epoch):
            return
        lr = self._base_lr() * float(self.multiplier(epoch))
        if hasattr(self, "model") and self.model is not None:
            self.model.lr = lr
        self.last_lr = lr

    def on_epoch_begin(self, epoch: int, logs=None) -> None:
        self.current_epoch = epoch
        if self.staircase:
            self._apply(epoch)

    def on_batch_begin(self, batch: int, logs=None) -> None:
        if not self.staircase:
            if self.steps_per_epoch is None:
                raise ValueError("steps_per_epoch required when staircase=False")
            self._apply(self.current_epoch + batch / self.steps_per_epoch)

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        if logs is not None and hasattr(self, "last_lr"):
            logs["lr"] = self.last_lr


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup from base LR to base LR × size over
    ``warmup_epochs`` (``_keras/callbacks.py`` ``LearningRateWarmupCallback``;
    Goyal et al. 2017 recipe cited there)."""

    def __init__(
        self,
        warmup_epochs: int = 5,
        momentum_correction: Optional[bool] = None,
        steps_per_epoch: Optional[int] = None,
        verbose: int = 0,
        initial_lr: Optional[float] = None,
    ) -> None:
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        # momentum_correction is unnecessary on the optax path (the LR
        # multiplies the update AFTER the momentum trace, so mid-schedule
        # LR changes don't bake into the buffer the way torch/TF1-style
        # formulations do) and this framework-neutral callback has no
        # optimizer handle to rescale anyway.  The default (None) is
        # therefore a silent no-op; a caller who EXPLICITLY requests the
        # reference behavior gets told where it actually lives instead of
        # a silent drop.
        if momentum_correction:
            import warnings

            warnings.warn(
                "momentum_correction is not applied by the framework-"
                "neutral LearningRateWarmupCallback (optax optimizers "
                "don't need it: lr scales the post-momentum update). "
                "For Keras optimizers use horovod_tpu.keras."
                "LearningRateWarmupCallback, which rescales the momentum "
                "variable like the reference.",
                stacklevel=2)
        mult = lambda epoch: 1.0 / basics.size() * (
            epoch * (basics.size() - 1) / warmup_epochs + 1
        )
        super().__init__(
            multiplier=mult,
            start_epoch=0,
            end_epoch=warmup_epochs,
            staircase=False,
            steps_per_epoch=steps_per_epoch,
            initial_lr=initial_lr,
        )

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose and basics.rank() == 0:
            print(
                f"Epoch {epoch + 1}: finished gradual learning rate warmup to "
                f"{getattr(self, 'last_lr', None)}."
            )


# --- optax-native schedules (the TPU-idiomatic path) ------------------------


def warmup_schedule(
    base_lr: float,
    *,
    warmup_steps: int,
    size: Optional[int] = None,
) -> Callable[[int], float]:
    """optax schedule: linear warmup from ``base_lr`` to
    ``base_lr * size`` over ``warmup_steps``, then constant.  The compiled
    in-graph equivalent of ``LearningRateWarmupCallback``."""
    import jax.numpy as jnp

    def schedule(step):
        n = size if size is not None else basics.size()
        frac = jnp.minimum(step / max(warmup_steps, 1), 1.0)
        return base_lr * (1.0 + frac * (n - 1))

    return schedule


def multiplier_schedule(
    base_lr: float, boundaries_and_multipliers: Sequence[Tuple[int, float]]
) -> Callable[[int], float]:
    """Piecewise-constant LR, the in-graph ``LearningRateScheduleCallback``."""
    import jax.numpy as jnp

    bounds = [b for b, _ in boundaries_and_multipliers]
    mults = [m for _, m in boundaries_and_multipliers]

    def schedule(step):
        lr = jnp.asarray(base_lr)
        for b, m in zip(bounds, mults):
            lr = jnp.where(step >= b, base_lr * m, lr)
        return lr

    return schedule
