"""True pipeline parallelism: GPipe-style microbatched execution over the
``pp`` mesh axis inside ``shard_map``.

The reference framework has no pipelining (SURVEY.md §2.6 — DP only);
and GSPMD alone only gives *layer-stack sharding* (weights sharded over
``pp``, gathered on use).  This module adds the real thing: each device
owns one contiguous STAGE of layers, activations flow stage-to-stage
over the ICI ring via ``lax.ppermute``, and M microbatches keep every
stage busy outside the fill/drain bubble.

Schedule (GPipe, stored activations):

    tick t = 0 .. M+P-2
      stage 0   feeds microbatch t            (while t < M)
      stage s   computes what stage s-1 produced at tick t-1
      stage P-1 emits microbatch t-(P-1)      (from tick P-1 on)

Bubble fraction = (P-1)/(M+P-1): amortized away by raising M.  The whole
schedule is one ``lax.scan`` over ticks — compile time is constant in M
and P.  Backward is automatic: ``jax.grad`` differentiates through the
scan and the ``ppermute``s (the VJP of a ring shift is the reverse ring
shift), which yields exactly the reverse-order pipeline schedule without
writing it by hand.  Memory is GPipe-like (activations of all in-flight
microbatches are saved by autodiff); wrap ``stage_fn`` in
``jax.checkpoint`` to trade recompute for memory.

Requirements: ``stage_fn`` must be shape-preserving (activations in ==
activations out — true for transformer blocks), and the number of layers
must divide evenly into stages.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   *, axis_name: str = "pp", stage_aux: bool = False):
    """Run ``microbatches`` through the P pipeline stages.

    Args:
      stage_fn: ``(stage_params, x) -> y`` applying THIS device's stage to
        one microbatch; must preserve ``x.shape``.  With ``stage_aux``,
        ``(stage_params, x) -> (y, aux)`` where ``aux`` is a scalar
        side-loss (e.g. the MoE balance term) summed per stage over its
        REAL microbatches only (fill/drain ticks run on zero activations
        whose aux is meaningless and is masked out).
      stage_params: this device's stage parameters (inside ``shard_map``,
        pass the pp-sharded slice — e.g. a layer stack reshaped to
        ``(P, layers_per_stage, ...)`` and sharded on axis 0, squeezed).
      microbatches: ``(M, mb, ...)`` array, replicated over ``axis_name``
        (shard data over a separate ``dp`` axis, not ``pp``).
      axis_name: the pipeline mesh axis bound by ``shard_map``.

    Returns:
      ``(M, mb, ...)`` outputs of the LAST stage, broadcast to every
      stage member (one ``psum`` — lets the loss/readout be computed
      replicated, and keeps the return value meaningful on all devices).
      With ``stage_aux``, ``(outputs, aux_local)`` where ``aux_local`` is
      THIS stage's aux sum (``psum`` it over the axis for the total —
      keeping it local preserves per-stage gradient ownership).
    """
    P = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    right = [(i, (i + 1) % P) for i in range(P)]

    def tick(carry, t):
        # Stage 0 reads the schedule's fresh microbatch (zeros in the
        # drain phase — those ticks' outputs are discarded below);
        # other stages read what arrived from the left last tick.
        buf, aacc = carry
        mb = microbatches[jnp.clip(t, 0, M - 1)]
        mb = jnp.where(t < M, mb, jnp.zeros_like(mb))
        x = jnp.where(s == 0, mb, buf)
        if stage_aux:
            y, aux = stage_fn(stage_params, x)
            # Stage s computes real microbatch t-s only while 0 <= t-s < M.
            f_valid = (t >= s) & (t - s < M)
            aacc = aacc + jnp.where(f_valid, aux, 0.0)
        else:
            y = stage_fn(stage_params, x)
        return (lax.ppermute(y, axis_name, right), aacc), y

    # Derive the initial carry from axis_index so it is varying-over-axis
    # under shard_map (the ppermuted carry-out is; a plain replicated
    # zeros literal would mismatch the scan carry type).
    buf0 = jnp.zeros_like(microbatches[0]) + (s * 0).astype(
        microbatches.dtype)
    aacc0 = jnp.float32(0.0) + (s * 0).astype(jnp.float32)
    (_, aux_local), ys = lax.scan(
        tick, (buf0, aacc0), jnp.arange(M + P - 1))

    # Last stage's outputs for microbatch m appear at tick m + P - 1.
    out_last = lax.dynamic_slice_in_dim(ys, P - 1, M, axis=0)
    # Select the last stage's values and share them with the whole axis:
    # every other stage contributes zeros, so the psum IS a broadcast.
    out = lax.psum(jnp.where(s == P - 1, out_last, jnp.zeros_like(out_last)),
                   axis_name)
    return (out, aux_local) if stage_aux else out


def pipeline_value_and_grad(stage_fn: Callable, stage_params, microbatches,
                            targets, loss_fn: Callable, *,
                            axis_name: str = "pp",
                            schedule: str = "gpipe",
                            loss_params=None,
                            return_input_grads: bool = False,
                            aux_weight=None,
                            n_virtual: int = 2):
    """Microbatched pipeline training step: total loss and THIS stage's
    parameter gradients.

    Args:
      stage_fn: ``(stage_params, x) -> y`` (shape-preserving, like
        :func:`pipeline_apply`).
      stage_params: this device's stage parameters (pp-sharded slice).
      microbatches: ``(M, mb, ...)``, replicated over ``axis_name``.
      targets: ``(M, ...)`` per-microbatch loss targets, replicated.
      loss_fn: ``(y, target) -> scalar`` per-microbatch loss; the returned
        loss is the SUM over microbatches (scale inside ``loss_fn`` for a
        mean).
      schedule: ``"gpipe"``, ``"1f1b"``, or ``"interleaved"`` (virtual
        stages — ``stage_params`` stacked on a leading ``n_virtual``
        axis, ``stage_fn`` applying one chunk; see
        :func:`interleaved_apply`).
      loss_params: optional pytree of parameters the LOSS uses (readout
        head, final norm, ...).  When given, ``loss_fn`` is called as
        ``loss_fn(loss_params, y, target)`` and its parameter gradients
        are returned — accumulated at the last stage and ZERO on other
        stages (``psum`` over the axis outside, or rely on shard_map's
        replicated-output transpose, to get the true gradient).
      return_input_grads: also return ``d loss / d microbatches``
        (``(M, mb, ...)``), accumulated at stage 0 and zero elsewhere —
        what an embedding layer upstream of the pipeline backprops
        through.
      aux_weight: when not None, ``stage_fn`` returns ``(y, aux)`` (see
        :func:`pipeline_apply` ``stage_aux``) and the optimized loss
        becomes ``sum(loss_fn) + aux_weight * sum(aux over stages and
        microbatches)`` — both value and gradients.  In the 1f1b
        schedule the aux cotangent rides the SAME per-microbatch
        ``jax.vjp`` replay the backward wave already does, so the
        schedule's memory bound is unchanged.

    Returns:
      ``(loss, stage_grads)`` — loss replicated over the axis,
      ``stage_grads`` matching ``stage_params`` (per-stage, i.e. still
      pp-sharded from the caller's viewpoint).  With ``loss_params`` /
      ``return_input_grads``, ``(loss, stage_grads, extras)`` where
      ``extras`` holds ``loss_param_grads`` and/or ``input_grads``.

    Schedules:

    * ``"gpipe"`` — forward all M microbatches through
      :func:`pipeline_apply`, then let autodiff reverse the scan.  Simple,
      but the in-flight activation footprint grows with **M** (autodiff
      saves every tick's residuals; ``jax.checkpoint`` on ``stage_fn``
      reduces it to M stage-inputs).
    * ``"1f1b"`` — interleaved forward/backward wavefronts in ONE scan:
      at tick t, stage s runs the forward for microbatch ``t - s`` while
      the backward wave (cotangents flowing stage P-1 → 0 via the reverse
      ``ppermute``) runs microbatch ``t - (2P-2-s)``; the last stage
      starts a microbatch's backward on the same tick its forward
      completes (the 1F1B discipline — a microbatch drains before more
      fill in).  Each stage keeps a ring buffer of the **stage inputs**
      of in-flight microbatches only — at most ``2(P-1)`` of them, bound
      by the pipeline depth and INDEPENDENT of M — and rematerializes the
      stage forward inside ``jax.vjp`` at backward time (the trade the
      1F1B papers make on activation-scarce hardware; same remat the
      gpipe path needs ``jax.checkpoint`` for).  Raising M to amortize
      the ``2(P-1)/(M+2P-2)`` bubble therefore no longer raises memory.
    """
    P = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]

    has_lp = loss_params is not None
    if has_lp:
        # Make loss_params VARYING over the axis before any
        # differentiation: the VJP of a replicated (unvarying) operand
        # inside shard_map carries an implicit psum over the axis, which
        # would sum every stage's loss gradient — including the garbage
        # gradients non-last stages compute from their intermediate
        # activations.  As varying values each stage's gradient stays
        # LOCAL, and the last-stage gating keeps exactly the real one
        # (psum outside to collect it).
        loss_params = jax.tree_util.tree_map(
            lambda a: a + (s * 0).astype(a.dtype), loss_params)
    if return_input_grads:
        # Same reasoning for d loss / d microbatches.
        microbatches = microbatches + (s * 0).astype(microbatches.dtype)

    def _apply_loss(lp, y, tgt):
        return loss_fn(lp, y, tgt) if has_lp else loss_fn(y, tgt)

    if schedule in ("gpipe", "interleaved"):
        # Both schedules share the forward-then-autodiff-reverse
        # construction; "interleaved" runs the chunked virtual-stage
        # schedule (stage_params stacked on a leading n_virtual axis,
        # stage_fn applying ONE chunk) with the bubble divided by ~v.
        if schedule == "interleaved":
            def _apply(params, mbs, **akw):
                return interleaved_apply(stage_fn, params, mbs,
                                         axis_name=axis_name,
                                         n_virtual=n_virtual, **akw)
        else:
            def _apply(params, mbs, **akw):
                return pipeline_apply(stage_fn, params, mbs,
                                      axis_name=axis_name, **akw)

        def local_loss(params, lp, mbs):
            if aux_weight is not None:
                outs, aux_local = _apply(params, mbs, stage_aux=True)
            else:
                outs = _apply(params, mbs)
            losses = jax.vmap(lambda y, t: _apply_loss(lp, y, t))(
                outs, targets)
            # Gate the loss to the last stage and return THIS DEVICE's
            # contribution WITHOUT a psum: a psum's transpose is a psum,
            # so combining the loss inside the differentiated function
            # would broadcast every stage's seed cotangent back to every
            # other stage and multiply the gradients by the axis size
            # (the model-level _varying_value_and_grad documents the
            # same trap).  Gating keeps the backward cotangent nonzero
            # only on the last stage — loss_param grads land there and
            # input grads on stage 0, zero elsewhere: the SAME ownership
            # contract the 1f1b schedule produces.  The psum that
            # combines the VALUE happens outside the grad, below.
            local = jnp.where(s == P - 1, jnp.sum(losses), 0.0)
            if aux_weight is not None:
                # Each stage's aux is LOCAL too (gradient ownership);
                # the value-psum outside collects it across stages.
                local = local + aux_weight * aux_local
            return local

        argnums = [0] + ([1] if has_lp else []) + (
            [2] if return_input_grads else [])
        local, grads = jax.value_and_grad(local_loss, argnums=tuple(argnums))(
            stage_params, loss_params, microbatches)
        loss = lax.psum(local, axis_name)
        if not has_lp and not return_input_grads:
            return loss, grads[0]
        extras = {}
        rest = list(grads[1:])
        if has_lp:
            extras["loss_param_grads"] = rest.pop(0)
        if return_input_grads:
            extras["input_grads"] = rest.pop(0)
        return loss, grads[0], extras
    if schedule != "1f1b":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    right = [(i, (i + 1) % P) for i in range(P)]
    left = [((i + 1) % P, i) for i in range(P)]
    # Ring of in-flight stage inputs + one scratch slot that invalid-tick
    # writes land in (so they can never clobber a live entry).
    R = min(2 * P - 1, M)
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype
    T = M + 2 * P - 2
    is_last = s == P - 1

    has_aux = aux_weight is not None

    def tick(carry, t):
        fwd_in, bwd_in, xbuf, gacc, lacc, auxacc, lpacc, xgacc = carry

        # ---- forward wave: F(s, m) at tick t = s + m -------------------
        m_f = t - s
        f_valid = (m_f >= 0) & (m_f < M)
        mb = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(m_f, 0, M - 1), keepdims=False)
        x_in = jnp.where(f_valid, jnp.where(s == 0, mb, fwd_in),
                         jnp.zeros(mb_shape, dtype))
        slot = jnp.where(f_valid, jnp.clip(m_f, 0, M - 1) % R, R)
        xbuf = lax.dynamic_update_index_in_dim(xbuf, x_in, slot, axis=0)
        if has_aux:
            # Aux's VALUE and gradient are both taken from the backward
            # replay below; the forward wave only moves activations.
            y, _ = stage_fn(stage_params, x_in)
        else:
            y = stage_fn(stage_params, x_in)

        # ---- backward wave: B(s, m) at tick t = (2P-2-s) + m -----------
        m_b = t - (2 * P - 2 - s)
        b_valid = (m_b >= 0) & (m_b < M)
        x_b = lax.dynamic_index_in_dim(
            xbuf, jnp.where(b_valid, jnp.clip(m_b, 0, M - 1) % R, R),
            keepdims=False)
        if has_aux:
            (y_b, aux_b), pull = jax.vjp(stage_fn, stage_params, x_b)
            auxacc = auxacc + jnp.where(b_valid, aux_b, 0.0)
        else:
            y_b, pull = jax.vjp(stage_fn, stage_params, x_b)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(m_b, 0, M - 1), keepdims=False)
        if has_lp:
            loss_b, (glp, gy_loss) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(loss_params, y_b, tgt)
            lp_mask = b_valid & is_last
            glp = jax.tree_util.tree_map(
                lambda g: jnp.where(lp_mask, g, jnp.zeros_like(g)), glp)
            lpacc = jax.tree_util.tree_map(
                lambda a, g: a + g, lpacc, glp)
        else:
            loss_b, gy_loss = jax.value_and_grad(loss_fn)(y_b, tgt)
        # Cotangent source: the last stage seeds from its own loss; other
        # stages consume what their right neighbour emitted last tick.
        gy = jnp.where(b_valid, jnp.where(is_last, gy_loss, bwd_in),
                       jnp.zeros_like(y_b))
        if has_aux:
            # The aux term's cotangent is its weight, on valid ticks only
            # — it joins the xent cotangent in ONE pullback call.
            g_aux = jnp.where(b_valid, jnp.float32(aux_weight),
                              jnp.float32(0.0))
            gparams, gx = pull((gy, g_aux))
        else:
            gparams, gx = pull(gy)
        # Double-where guard: zeroing gy is not enough when stage_fn's
        # partials are non-finite at the zero fill/drain input (0 * inf =
        # nan would poison the accumulator), so mask the pullback outputs
        # on validity too.
        gparams = jax.tree_util.tree_map(
            lambda g: jnp.where(b_valid, g, jnp.zeros_like(g)), gparams)
        gx = jnp.where(b_valid, gx, jnp.zeros_like(gx))
        gacc = jax.tree_util.tree_map(lambda a, g: a + g, gacc, gparams)
        lacc = lacc + jnp.where(b_valid & is_last, loss_b, 0.0)
        if return_input_grads:
            # Stage 0's gx IS d loss / d microbatch m_b; other stages
            # write zeros (and invalid ticks land in the scratch slot).
            xg_slot = jnp.where(b_valid & (s == 0),
                                jnp.clip(m_b, 0, M - 1), M)
            xgacc = lax.dynamic_update_index_in_dim(
                xgacc, jnp.where(s == 0, gx, jnp.zeros_like(gx)),
                xg_slot, axis=0)

        return (lax.ppermute(y, axis_name, right),
                lax.ppermute(gx, axis_name, left),
                xbuf, gacc, lacc, auxacc, lpacc, xgacc), None

    # Device-varying zeros (see pipeline_apply): every carry leaf becomes
    # varying-over-pp inside the scan (permuted wires, per-stage grads),
    # so the initial carry must be too.
    def vzeros(shape, dt):
        return jnp.zeros(shape, dt) + (s * 0).astype(dt)

    fwd0 = vzeros(mb_shape, dtype)
    bwd0 = vzeros(mb_shape, dtype)
    xbuf0 = vzeros((R + 1,) + mb_shape, dtype)
    gacc0 = jax.tree_util.tree_map(
        lambda p: vzeros(p.shape, p.dtype), stage_params)
    lacc0 = vzeros((), jnp.float32)
    auxacc0 = vzeros((), jnp.float32)
    lpacc0 = jax.tree_util.tree_map(
        lambda p: vzeros(p.shape, p.dtype), loss_params) if has_lp else 0.0
    xgacc0 = (vzeros((M + 1,) + mb_shape, dtype)
              if return_input_grads else 0.0)

    (_, _, _, gacc, lacc, auxacc, lpacc, xgacc), _ = lax.scan(
        tick, (fwd0, bwd0, xbuf0, gacc0, lacc0, auxacc0, lpacc0, xgacc0),
        jnp.arange(T))
    # Only stage P-1 accumulated the xent loss; every stage accumulated
    # its own aux.  One psum broadcasts the total to the axis.
    contrib = lacc if not has_aux else lacc + jnp.float32(aux_weight) * auxacc
    loss = lax.psum(contrib, axis_name)
    if not has_lp and not return_input_grads:
        return loss, gacc
    extras = {}
    if has_lp:
        extras["loss_param_grads"] = lpacc
    if return_input_grads:
        extras["input_grads"] = xgacc[:M]
    return loss, gacc, extras


def interleaved_apply(stage_fn: Callable, chunk_params, microbatches,
                      *, axis_name: str = "pp", n_virtual: int,
                      stage_aux: bool = False):
    """Forward pass of the INTERLEAVED (virtual-stage) pipeline: the layer
    stack splits into ``L = n_virtual * P`` chunks laid round-robin on the
    P devices (chunk j lives on device ``j % P`` — Megatron-LM's
    interleaved assignment), so the fill/drain bubble shrinks to chunk
    granularity: ``P-1`` chunk-ticks instead of ``P-1`` full-stage ticks —
    bubble fraction ``(P-1)/(M·v + P-1)``, i.e. the non-interleaved
    bubble divided by ~v, at the price of ``v×`` the stage-boundary
    ppermute traffic.

    Microbatches are processed in groups of P (``M % P == 0`` required):
    device d's local step k runs chunk ``(k mod vP) // P`` on microbatch
    ``(k // vP)·P + (k mod P)``; every consecutive (chunk, microbatch)
    hand-off lands exactly one tick later on the right ring neighbour, so
    ONE ppermute wire carries all v virtual stages.

    Args:
      stage_fn: ``(one_chunk_params, x) -> y`` (shape-preserving), or
        ``-> (y, aux)`` with ``stage_aux``.
      chunk_params: this device's chunks, stacked on a leading ``v`` axis
        (chunk ``v_idx`` of device d is global chunk ``v_idx * P + d``).
      microbatches: ``(M, mb, ...)``, replicated over the axis.
      n_virtual: v, virtual stages (chunks) per device.

    Returns: like :func:`pipeline_apply` — last chunk's outputs broadcast
    to the axis (+ ``aux_local`` with ``stage_aux``).  Differentiable:
    ``jax.grad`` through the scan reverses the schedule, giving the
    interleaved backward automatically.
    """
    P = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    v = int(n_virtual)
    if v < 1:
        raise ValueError(f"n_virtual must be >= 1, got {v}")
    if M % P:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({M}) divisible by "
            f"the pipeline width ({P}) — microbatches run in groups of P")
    right = [(i, (i + 1) % P) for i in range(P)]
    T = M * v + P - 1
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    def tick(carry, t):
        buf, outbuf, aacc = carry
        k = t - s  # this device's local step
        valid = (k >= 0) & (k < M * v)
        kc = jnp.clip(k, 0, M * v - 1)
        g = kc // (v * P)          # microbatch group
        within = kc % (v * P)
        c = within // P            # which of my v chunks
        m = g * P + (within % P)   # microbatch index
        fresh = microbatches[jnp.clip(m, 0, M - 1)]
        # Chunk 0 on device 0 reads the schedule's fresh microbatch;
        # everything else consumes what arrived on the ring last tick.
        x = jnp.where((s == 0) & (c == 0), fresh, buf)
        x = jnp.where(valid, x, jnp.zeros(mb_shape, dtype))
        my_chunk = jax.tree_util.tree_map(
            lambda l: lax.dynamic_index_in_dim(l, c, keepdims=False),
            chunk_params)
        if stage_aux:
            y, aux = stage_fn(my_chunk, x)
            aacc = aacc + jnp.where(valid, aux, 0.0)
        else:
            y = stage_fn(my_chunk, x)
        # The LAST logical chunk (v-1 on device P-1) emits microbatch m.
        emit = valid & (s == P - 1) & (c == v - 1)
        slot = jnp.where(emit, m, M)  # scratch slot M for non-emitting
        outbuf = lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(emit, y, jnp.zeros_like(y)), slot, axis=0)
        return (lax.ppermute(y, axis_name, right), outbuf, aacc), None

    def vzeros(shape, dt):
        return jnp.zeros(shape, dt) + (s * 0).astype(dt)

    (_, outbuf, aux_local), _ = lax.scan(
        tick,
        (vzeros(mb_shape, dtype), vzeros((M + 1,) + mb_shape, dtype),
         vzeros((), jnp.float32)),
        jnp.arange(T))
    # Only device P-1 wrote real outputs; psum broadcasts them.
    out = lax.psum(outbuf[:M], axis_name)
    return (out, aux_local) if stage_aux else out


def stack_to_chunks(stacked, n_stages: int, n_virtual: int, stage_index):
    """Slice a ``(n_layers, ...)`` scanned-layer pytree into THIS device's
    ``(n_virtual, layers_per_chunk, ...)`` interleaved chunks (global
    chunk ``v_idx * n_stages + stage_index``; pass ``stage_index =
    lax.axis_index(axis)`` inside shard_map)."""
    L = n_stages * n_virtual

    def slice_chunks(leaf):
        n = leaf.shape[0]
        if n % L:
            raise ValueError(
                f"{n} layers do not divide into {L} interleaved chunks")
        per = n // L
        return jnp.stack([
            lax.dynamic_slice_in_dim(
                leaf, (vi * n_stages + stage_index) * per, per, 0)
            for vi in range(n_virtual)
        ])

    return jax.tree_util.tree_map(slice_chunks, stacked)


def stack_to_stages(stacked, n_stages: int):
    """Reshape a ``(n_layers, ...)`` scanned-layer pytree to
    ``(n_stages, n_layers/n_stages, ...)`` so axis 0 can be sharded over
    ``pp`` (one stage of layers per device)."""
    def reshape(leaf):
        n = leaf.shape[0]
        if n % n_stages:
            raise ValueError(
                f"{n} layers do not divide into {n_stages} pipeline stages")
        return leaf.reshape(n_stages, n // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked)
