"""True pipeline parallelism: GPipe-style microbatched execution over the
``pp`` mesh axis inside ``shard_map``.

The reference framework has no pipelining (SURVEY.md §2.6 — DP only);
and GSPMD alone only gives *layer-stack sharding* (weights sharded over
``pp``, gathered on use).  This module adds the real thing: each device
owns one contiguous STAGE of layers, activations flow stage-to-stage
over the ICI ring via ``lax.ppermute``, and M microbatches keep every
stage busy outside the fill/drain bubble.

Schedule (GPipe, stored activations):

    tick t = 0 .. M+P-2
      stage 0   feeds microbatch t            (while t < M)
      stage s   computes what stage s-1 produced at tick t-1
      stage P-1 emits microbatch t-(P-1)      (from tick P-1 on)

Bubble fraction = (P-1)/(M+P-1): amortized away by raising M.  The whole
schedule is one ``lax.scan`` over ticks — compile time is constant in M
and P.  Backward is automatic: ``jax.grad`` differentiates through the
scan and the ``ppermute``s (the VJP of a ring shift is the reverse ring
shift), which yields exactly the reverse-order pipeline schedule without
writing it by hand.  Memory is GPipe-like (activations of all in-flight
microbatches are saved by autodiff); wrap ``stage_fn`` in
``jax.checkpoint`` to trade recompute for memory.

Requirements: ``stage_fn`` must be shape-preserving (activations in ==
activations out — true for transformer blocks), and the number of layers
must divide evenly into stages.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   *, axis_name: str = "pp"):
    """Run ``microbatches`` through the P pipeline stages.

    Args:
      stage_fn: ``(stage_params, x) -> y`` applying THIS device's stage to
        one microbatch; must preserve ``x.shape``.
      stage_params: this device's stage parameters (inside ``shard_map``,
        pass the pp-sharded slice — e.g. a layer stack reshaped to
        ``(P, layers_per_stage, ...)`` and sharded on axis 0, squeezed).
      microbatches: ``(M, mb, ...)`` array, replicated over ``axis_name``
        (shard data over a separate ``dp`` axis, not ``pp``).
      axis_name: the pipeline mesh axis bound by ``shard_map``.

    Returns:
      ``(M, mb, ...)`` outputs of the LAST stage, broadcast to every
      stage member (one ``psum`` — lets the loss/readout be computed
      replicated, and keeps the return value meaningful on all devices).
    """
    P = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    right = [(i, (i + 1) % P) for i in range(P)]

    def tick(buf, t):
        # Stage 0 reads the schedule's fresh microbatch (zeros in the
        # drain phase — those ticks' outputs are discarded below);
        # other stages read what arrived from the left last tick.
        mb = microbatches[jnp.clip(t, 0, M - 1)]
        mb = jnp.where(t < M, mb, jnp.zeros_like(mb))
        x = jnp.where(s == 0, mb, buf)
        y = stage_fn(stage_params, x)
        return lax.ppermute(y, axis_name, right), y

    # Derive the initial carry from axis_index so it is varying-over-axis
    # under shard_map (the ppermuted carry-out is; a plain replicated
    # zeros literal would mismatch the scan carry type).
    buf0 = jnp.zeros_like(microbatches[0]) + (s * 0).astype(
        microbatches.dtype)
    _, ys = lax.scan(tick, buf0, jnp.arange(M + P - 1))

    # Last stage's outputs for microbatch m appear at tick m + P - 1.
    out_last = lax.dynamic_slice_in_dim(ys, P - 1, M, axis=0)
    # Select the last stage's values and share them with the whole axis:
    # every other stage contributes zeros, so the psum IS a broadcast.
    return lax.psum(jnp.where(s == P - 1, out_last, jnp.zeros_like(out_last)),
                    axis_name)


def stack_to_stages(stacked, n_stages: int):
    """Reshape a ``(n_layers, ...)`` scanned-layer pytree to
    ``(n_stages, n_layers/n_stages, ...)`` so axis 0 can be sharded over
    ``pp`` (one stage of layers per device)."""
    def reshape(leaf):
        n = leaf.shape[0]
        if n % n_stages:
            raise ValueError(
                f"{n} layers do not divide into {n_stages} pipeline stages")
        return leaf.reshape(n_stages, n // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked)
