"""TPU-idiomatic parallelism extensions beyond the reference's data
parallelism (SURVEY.md §2.6: TP/PP/SP/EP are extensions, not ports).

- :mod:`horovod_tpu.parallel.meshes` — multi-axis mesh construction
- :func:`ring_attention` (re-export of
  :func:`horovod_tpu.ops.attention.ring_attention`) — sequence/context
  parallelism over a mesh axis
- pipeline parallelism lives in the model sharding rules: the Transformer
  stacks layers on a scanned axis sharded over ``pp``
  (:func:`horovod_tpu.models.transformer.param_specs`)
"""

from horovod_tpu.parallel.meshes import MeshSpec, make_mesh  # noqa: F401
from horovod_tpu.ops.attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_value_and_grad,
    stack_to_stages,
)
