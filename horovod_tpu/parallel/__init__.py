"""TPU-idiomatic parallelism extensions beyond the reference's data
parallelism (SURVEY.md §2.6: TP/PP/SP/EP are extensions, not ports).

- :mod:`horovod_tpu.parallel.meshes` — multi-axis mesh construction
- :mod:`horovod_tpu.parallel.ring_attention` — sequence parallelism
- :mod:`horovod_tpu.parallel.pipeline` — pipeline parallelism
"""

from horovod_tpu.parallel.meshes import MeshSpec, make_mesh  # noqa: F401
