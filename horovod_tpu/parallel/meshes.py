"""Multi-axis mesh construction for dp/fsdp/tp/pp/sp/ep parallelism.

The reference's GLOBAL/LOCAL/CROSS communicator triple generalizes on TPU
to an N-D logical mesh laid onto the physical ICI torus.  Convention:
axes that carry the heaviest traffic (tp, sp) go innermost so they map to
ICI neighbors; dp/pp outermost so their lighter collectives can ride DCN
across slices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


# Canonical axis order, outermost → innermost (DCN-tolerant → ICI-hungry).
AXIS_ORDER = ("dp", "pp", "ep", "fsdp", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; unspecified axes default to 1."""

    dp: int = 1
    pp: int = 1
    ep: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))


def make_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh with axes (dp, pp, ep, fsdp, sp, tp).

    Uses ``mesh_utils.create_device_mesh`` when available so the logical
    mesh is laid out along the physical ICI torus (nearest-neighbor tp/sp).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if spec.size != n:
        raise ValueError(f"MeshSpec size {spec.size} != device count {n}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(spec.shape, devices=list(devices))
    except Exception:
        arr = np.array(
            sorted(devices, key=lambda d: (d.process_index, d.id)), dtype=object
        ).reshape(spec.shape)
    return Mesh(arr, axis_names=AXIS_ORDER)


def infer_spec(
    n_devices: int,
    *,
    tp: Optional[int] = None,
    sp: Optional[int] = None,
    pp: int = 1,
    ep: int = 1,
    fsdp: int = 1,
) -> MeshSpec:
    """Fill dp with whatever remains after the model axes are chosen."""
    tp = tp or 1
    sp = sp or 1
    model = tp * sp * pp * ep * fsdp
    if n_devices % model != 0:
        raise ValueError(f"{n_devices} devices not divisible by model axes {model}")
    return MeshSpec(dp=n_devices // model, pp=pp, ep=ep, fsdp=fsdp, sp=sp, tp=tp)
