"""Worker-side elastic machinery: failure notification + the retrying
``elastic.run`` wrapper.

Port of Horovod Elastic's ``WorkerNotificationManager`` /
``elastic.run`` pair onto the fixed-mesh XLA world.  The supervisor
(:class:`horovod_tpu.runner.elastic_driver.ElasticDriver`) and the
workers share the launcher's rendezvous KV:

* each worker publishes a wall-clock heartbeat under
  ``elastic/heartbeat.<epoch>.<rank>`` so the driver can detect a HUNG
  rank (a dead one is caught by its exit code);
* the driver publishes ``elastic/notice.<epoch>`` when membership
  changes; the notification thread converts that into
  :class:`HostsUpdatedInterrupt` at the next commit boundary
  (``State.commit`` → ``check_host_updates``).

``elastic.run(train_fn)`` then implements the recovery contract: a
committed step is never lost, an uncommitted one is cleanly replayed —
on :class:`HorovodInternalError` (peer died mid-collective) the state
rolls back to the last commit; on :class:`HostsUpdatedInterrupt` the
state is already committed-consistent.  Single-process jobs rebuild the
runtime in-process (``basics.reinit``); multi-process jobs exit with
``EXIT_CODE_RESTART`` so the driver respawns them over the surviving
mesh (re-``init()`` with the new world, fresh rendezvous epoch keys).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import List, Optional

from horovod_tpu.elastic.interrupts import (
    EXIT_CODE_RESTART,
    HorovodInternalError,
    HostsUpdatedInterrupt,
)

logger = logging.getLogger("horovod_tpu")

KV_SCOPE = "elastic"


def heartbeat_key(epoch: int, rank: int) -> str:
    return f"heartbeat.{epoch}.{rank}"


def metrics_key(epoch: int, rank: int) -> str:
    """Where a rank publishes its registry export for the driver's
    fleet aggregation (docs/observability.md "Fleet")."""
    return f"metrics.{epoch}.{rank}"


def notice_key(epoch: int) -> str:
    return f"notice.{epoch}"


def state_key(epoch: int) -> str:
    return f"state.{epoch}"


class WorkerNotificationManager:
    """Per-process singleton: heartbeat publisher + notice poller.

    ``init()`` is a no-op unless the launcher exported
    ``HOROVOD_ELASTIC=1`` (the ElasticDriver does), so non-elastic jobs
    pay nothing.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._listeners: List[object] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._notified = False

    def init(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            if os.environ.get("HOROVOD_ELASTIC", "0") in ("", "0", "false"):
                return
            addr = os.environ.get("HOROVOD_COORDINATOR_ADDR", "127.0.0.1")
            if ":" in addr:
                addr = addr.split(":")[0]
            port = os.environ.get("HOROVOD_COORDINATOR_PORT")
            if not port:
                return
            self._rank = int(os.environ.get("HOROVOD_RANK", "0"))
            self._epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
            self._interval = float(
                os.environ.get("HOROVOD_ELASTIC_HEARTBEAT", "1.0") or 0.0)
            from horovod_tpu.runner.rendezvous import KVClient

            self._kv = KVClient(addr, int(port), timeout=5.0)
            self._stop.clear()
            self._notified = False
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="hvd-elastic-notification")
            self._thread.start()

    def register_listener(self, listener: object) -> None:
        """``listener`` needs an ``on_hosts_updated()`` method (State)."""
        with self._lock:
            if listener not in self._listeners:
                self._listeners.append(listener)
            if self._notified:
                listener.on_hosts_updated()

    def remove_listener(self, listener: object) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def handle_hosts_updated(self) -> None:
        """Deliver a membership-change signal to every listener (also the
        test seam: callable directly to simulate a driver notice)."""
        with self._lock:
            self._notified = True
            listeners = list(self._listeners)
        for l in listeners:
            l.on_hosts_updated()

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    # ---- background thread ----------------------------------------------

    def _heartbeat_payload(self, now: float) -> bytes:
        """The structured heartbeat: wall clock plus the training-step
        telemetry the driver's straggler detector consumes (step count
        + last step duration, read off the default registry — the
        fields are simply absent before the first ``obs.training_step``
        completes).  Always JSON; the driver's staleness check only
        watches the raw value *change*, so legacy float payloads and
        this coexist."""
        payload = {"t": now}
        try:
            from horovod_tpu.obs.registry import training_metrics

            m = training_metrics()
            payload["steps"] = m.steps.value
            last = m.last_step.value
            if last > 0:
                payload["step_s"] = round(last, 6)
        except Exception:  # pragma: no cover - metrics never gate beats
            pass
        import json

        return json.dumps(payload).encode()

    def _export_payload(self) -> Optional[bytes]:
        """This rank's mergeable registry export for the driver's fleet
        aggregation (None when the registry is unavailable)."""
        try:
            from horovod_tpu.obs.registry import default_registry

            import json

            return json.dumps(default_registry().export()).encode()
        except Exception:  # pragma: no cover - metrics never gate beats
            return None

    def _loop(self) -> None:
        tick = max(0.1, min(self._interval or 1.0, 1.0))
        next_beat = 0.0
        while not self._stop.wait(tick):
            now = time.time()
            try:
                if self._interval > 0 and now >= next_beat:
                    self._kv.put(KV_SCOPE,
                                 heartbeat_key(self._epoch, self._rank),
                                 self._heartbeat_payload(now))
                    export = self._export_payload()
                    if export is not None:
                        self._kv.put(KV_SCOPE,
                                     metrics_key(self._epoch, self._rank),
                                     export)
                    next_beat = now + self._interval
                if not self._notified:
                    if self._kv.get(KV_SCOPE,
                                    notice_key(self._epoch)) is not None:
                        self.handle_hosts_updated()
            except Exception:
                # KV unreachable (driver tearing down / transient): the
                # driver's exit-code monitoring covers us; keep trying.
                continue


notification_manager = WorkerNotificationManager()


def _record_elastic_event(name: str, args=None, *,
                          count_restart: bool = True) -> None:
    """Mark a worker-side elastic recovery event on the active
    trace/timeline and, for FAILURE recoveries (``count_restart``),
    count it in ``elastic_restarts_total`` — a planned commit-boundary
    membership change is not a restart (it is already counted in
    ``elastic_rendezvous_total`` by the re-init), and conflating the
    two would fire failure alerts on routine scale events."""
    try:
        from horovod_tpu.obs import tracing as obs_tracing
        from horovod_tpu.obs.registry import elastic_metrics

        if count_restart:
            elastic_metrics().restarts.inc()
        obs_tracing.instant(name, args)
    except Exception:  # pragma: no cover - metrics never gate recovery
        pass


def _exit_for_respawn() -> None:
    """Leave the process for a driver-supervised respawn: attempt a clean
    runtime teardown (closing the native control-plane sockets promptly
    unblocks peers mid-negotiation) but never hang on it — the teardown
    runs on a daemon thread with a bounded join, then the process exits
    with ``EXIT_CODE_RESTART``."""
    from horovod_tpu import basics

    t = threading.Thread(target=basics.shutdown, daemon=True)
    t.start()
    t.join(timeout=5.0)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(EXIT_CODE_RESTART)


def _rebuild_in_process() -> bool:
    """Tear down and re-initialize the runtime inside this process.

    Only supported for single-process jobs: with multiple processes the
    JAX coordination service and the native control plane are bound to
    the dead world's ports/membership, so the honest recovery is a
    respawn by the ElasticDriver (which exports fresh epoch env)."""
    from horovod_tpu import basics

    try:
        if basics.is_initialized() and basics.num_processes() > 1:
            return False
    except Exception:
        return False
    basics.reinit()
    return True


def run(train_fn):
    """Decorator implementing Horovod Elastic's ``run`` contract.

    ``wrapped(state, *args, **kwargs)``:

    1. starts the notification manager and registers ``state``;
    2. ``state.sync()`` (broadcast from rank 0 — restart consistency);
    3. calls ``train_fn``; on a clean return, returns its value;
    4. on :class:`HostsUpdatedInterrupt` (commit-boundary membership
       change): state is committed-consistent — re-sync and retry;
    5. on :class:`HorovodInternalError` / eager ``CollectiveError``
       (peer died mid-step): ``state.rollback()`` to the last commit,
       then re-sync and retry;
    6. when the mesh cannot be rebuilt in-process (multi-process job),
       exits with ``EXIT_CODE_RESTART`` so the supervising ElasticDriver
       respawns this rank over the surviving hosts.
    """

    def wrapped(state, *args, **kwargs):
        from horovod_tpu.eager_runtime import CollectiveError

        notification_manager.init()
        notification_manager.register_listener(state)
        # In-process retries are bounded (a persistently failing step
        # must not loop forever); the driver-supervised respawn path has
        # its own reset_limit.  0/unset = unbounded, like Horovod.
        reset_limit = int(
            os.environ.get("HOROVOD_ELASTIC_RESET_LIMIT", "0") or 0)
        resets = 0
        try:
            while True:
                try:
                    # sync() is INSIDE the protected region: a peer can die
                    # while we are in the restart broadcast itself, and
                    # that failure must take the recovery path (respawn /
                    # retry), not crash this healthy rank with a plain
                    # exit 1 that the driver would blame on its host.
                    state.sync()
                    return train_fn(state, *args, **kwargs)
                except HostsUpdatedInterrupt:
                    logger.warning(
                        "elastic: hosts updated at commit boundary; "
                        "re-rendezvousing")
                    _record_elastic_event("elastic_hosts_updated",
                                          count_restart=False)
                except (HorovodInternalError, CollectiveError) as e:
                    logger.warning(
                        "elastic: collective failed mid-step (%s); rolling "
                        "back to last commit", e)
                    state.rollback()
                    resets += 1
                    _record_elastic_event("elastic_worker_rollback_retry",
                                          {"resets": resets})
                    if reset_limit and resets > reset_limit:
                        raise
                if not _rebuild_in_process():
                    logger.warning(
                        "elastic: cannot rebuild the mesh in-process; "
                        "exiting for supervised respawn (code %d)",
                        EXIT_CODE_RESTART)
                    _exit_for_respawn()
        finally:
            notification_manager.remove_listener(state)

    wrapped.__name__ = getattr(train_fn, "__name__", "wrapped")
    wrapped.__doc__ = train_fn.__doc__
    return wrapped
