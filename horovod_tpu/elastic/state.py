"""Elastic training state: sync, disk checkpoint, and in-memory
commit/rollback.

The reference (v0.19) predates Horovod Elastic; its fault-tolerance
primitive is Join (SURVEY.md §5.3) plus the convention that rank 0
checkpoints and broadcasts restored state (§5.4).  :class:`State` packages
that convention and extends it with the Elastic-mode contract (the v0.20
successor of this codebase): ``commit()`` takes an IN-MEMORY snapshot (plus
an optional durable save) and checks for membership-change notices;
``rollback()`` restores the last snapshot, so an uncommitted step wrecked
by a peer failure is cleanly replayed instead of corrupting training.

On TPU a membership change means a new mesh and recompilation — the
:class:`horovod_tpu.runner.elastic_driver.ElasticDriver` supervises that
(stop → re-rendezvous → rebuild mesh → recompile → resume); this object
guarantees the surviving state is consistent when training resumes.
"""

from __future__ import annotations

import copy
import os
import pickle
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu import state as S
from horovod_tpu.elastic.interrupts import HostsUpdatedInterrupt

_metrics = None


def _em():
    """Lazy create-or-fetch of the elastic metric family (commit and
    rollback run every step — resolve the registry once, never let it
    gate training)."""
    global _metrics
    if _metrics is None:
        try:
            from horovod_tpu.obs.registry import elastic_metrics

            _metrics = elastic_metrics()
        except Exception:  # pragma: no cover
            _metrics = False
    return _metrics or None


def _writable(v: Any) -> Any:
    """Re-own read-only numpy leaves.  Eager broadcasts hand back numpy
    VIEWS of XLA buffers (read-only); a training loop that updates its
    params in place (``w -= lr * g``) must keep working after ``sync()``
    replaced the fields."""

    def leaf(l):
        if isinstance(l, np.ndarray) and not l.flags.writeable:
            return l.copy()
        return l

    return jax.tree_util.tree_map(leaf, v)


def _copy_value(v: Any) -> Any:
    """Snapshot one state field.  ``jax.Array`` leaves are immutable but
    must still be COPIED: the repo's own train steps donate their input
    buffers (``spmd.make_train_step`` defaults ``donate=True``), so a
    snapshot held by reference would be deleted by the very next step and
    ``rollback()`` would restore dead buffers."""

    def leaf(l):
        if isinstance(l, np.ndarray):
            return l.copy()
        if isinstance(l, jax.Array):
            try:
                return l.copy()
            except Exception:  # already deleted / committed-to-disk only
                return l
        return copy.deepcopy(l)

    return jax.tree_util.tree_map(leaf, v)


class State:
    """Synchronizable training state (params, opt_state, epoch, step...).

    Construction takes an implicit first snapshot, so ``rollback()`` before
    any ``commit()`` restores the initial values."""

    def __init__(self, **kwargs: Any) -> None:
        self._keys = sorted(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._commit_lock = threading.Lock()
        self._host_updated = threading.Event()
        self._saved: Dict[str, Any] = {}
        self._warned_memory_only = False
        self.save_snapshot()

    # ---- membership-change notification (WorkerNotificationManager) ------

    def on_hosts_updated(self) -> None:
        """Called (from the notification thread) when the supervisor
        signals a membership change; surfaces as
        :class:`HostsUpdatedInterrupt` at the next commit boundary."""
        self._host_updated.set()

    def check_host_updates(self) -> None:
        """Raise :class:`HostsUpdatedInterrupt` if a membership change was
        signalled.  Called by :meth:`commit` so the interrupt only fires at
        a committed-consistent boundary."""
        if self._host_updated.is_set():
            self._host_updated.clear()
            raise HostsUpdatedInterrupt(
                "cluster membership changed; re-rendezvous required")

    # ---- in-memory snapshot ----------------------------------------------

    def save_snapshot(self) -> None:
        """Capture the current field values in memory (no disk IO)."""
        with self._commit_lock:
            self._saved = {k: _copy_value(getattr(self, k))
                           for k in self._keys}

    def rollback(self) -> None:
        """Restore every field from the last in-memory snapshot: the
        recovery half of the commit/rollback contract — an uncommitted
        step interrupted by a peer failure is discarded and replayed."""
        with self._commit_lock:
            for k in self._keys:
                setattr(self, k, _copy_value(self._saved[k]))
        m = _em()
        if m is not None:
            m.rollbacks.inc()

    def commit(self, path: Optional[str] = None) -> None:
        """Mark the current state as committed: snapshot in memory, write a
        durable rank-0 checkpoint when ``path`` is given, then surface any
        pending membership change (:class:`HostsUpdatedInterrupt`).

        A committed step is never lost: the driver restarts ranks from the
        last durable commit, and an in-process retry rolls back to the last
        in-memory commit.  NOTE that in a multi-process job recovery means
        a driver-supervised RESPAWN, and only a durable commit survives a
        respawn — committing without ``path`` there is warned once."""
        self.save_snapshot()
        m = _em()
        if m is not None:
            m.commits.inc()
        if path is not None:
            self.save(path)
        elif not self._warned_memory_only:
            try:
                multi = basics.is_initialized() and basics.num_processes() > 1
            except Exception:
                multi = False
            if multi:
                self._warned_memory_only = True
                import logging

                logging.getLogger("horovod_tpu").warning(
                    "elastic: State.commit() without a path only snapshots "
                    "in memory; a driver-supervised respawn restores from "
                    "the last DURABLE commit — pass a checkpoint path or "
                    "committed progress will not survive a rank failure")
        self.check_host_updates()

    # ---- cross-rank sync -------------------------------------------------

    def sync(self, root_rank: int = 0) -> None:
        """Broadcast every field from ``root_rank`` (restart consistency),
        then snapshot the synced values."""
        for k in self._keys:
            v = getattr(self, k)
            leaves = jax.tree_util.tree_leaves(v)
            if leaves and all(
                isinstance(l, (jax.Array, np.ndarray, float, int)) for l in leaves
            ):
                setattr(self, k, _writable(S.broadcast_parameters(v, root_rank)))
            else:
                setattr(self, k, S.broadcast_object(v, root_rank))
        self.save_snapshot()

    # ---- durable checkpoint ----------------------------------------------

    def save(self, path: str) -> None:
        """Rank-0 checkpoint (host pytree pickle; for large models prefer
        orbax — this covers the reference's convention, not a storage
        format)."""
        if basics.rank() == 0:
            tmp = path + ".tmp"
            host = {
                k: jax.tree_util.tree_map(
                    lambda l: np.asarray(l)
                    if isinstance(l, (jax.Array, np.ndarray))
                    else l,
                    getattr(self, k),
                )
                for k in self._keys
            }
            with open(tmp, "wb") as f:
                pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)

    def restore(self, path: str, root_rank: int = 0) -> bool:
        """Rank 0 loads, then broadcast to all.  Returns False if absent."""
        exists = os.path.exists(path) if basics.rank() == 0 else False
        exists = bool(S.broadcast_object(exists, root_rank))
        if not exists:
            return False
        if basics.rank() == 0:
            with open(path, "rb") as f:
                host = pickle.load(f)
        else:
            host = None
        host = S.broadcast_object(host, root_rank)
        for k in self._keys:
            if k in host:
                setattr(self, k, host[k])
        self.save_snapshot()
        return True
