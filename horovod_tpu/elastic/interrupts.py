"""Control-flow exceptions for elastic training (Horovod Elastic's
``horovod/common/exceptions.py`` equivalents, on the fixed-mesh XLA
world where a membership change means stop → re-rendezvous → rebuild
mesh → recompile → resume)."""

from __future__ import annotations

from typing import Optional, Sequence

# A worker that cannot rebuild the mesh in-process (multi-process jobs:
# the JAX coordination service is bound to the dead world) exits with
# this code to request a clean respawn from the ElasticDriver.  BSD
# EX_TEMPFAIL: "temporary failure, retry".
EXIT_CODE_RESTART = 75


class HorovodInternalError(RuntimeError):
    """A collective failed mid-step (peer death, coordination abort).

    ``elastic.run`` reacts by rolling the state back to the last commit
    and retrying — the uncommitted step is replayed, never half-applied.
    """


class HostsUpdatedInterrupt(Exception):
    """Cluster membership changed (peer failed / hosts added or removed).

    Raised at a COMMIT BOUNDARY by ``State.check_host_updates()``, so the
    state is committed-consistent when ``elastic.run`` re-rendezvouses; no
    rollback is needed.
    """

    def __init__(self, message: str = "hosts updated",
                 updated_hosts: Optional[Sequence[str]] = None) -> None:
        super().__init__(message)
        self.updated_hosts = list(updated_hosts or [])
