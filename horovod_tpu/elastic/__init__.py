"""Elastic training subsystem.

Three layers, ported from Horovod Elastic (the v0.20 successor of the
reference codebase) onto the fixed-mesh XLA world:

* :class:`State` — synchronizable training state with in-memory
  ``commit()``/``rollback()`` plus durable ``save()``/``restore()``
  (:mod:`horovod_tpu.elastic.state`);
* :func:`run` + :class:`WorkerNotificationManager` — the worker-side
  retry loop and failure-notice plumbing
  (:mod:`horovod_tpu.elastic.worker`);
* the supervisor lives in the runner layer:
  :class:`horovod_tpu.runner.elastic_driver.ElasticDriver` /
  :func:`horovod_tpu.runner.elastic_driver.run_elastic`.

See ``docs/elastic.md`` for the full recovery story.
"""

from horovod_tpu.elastic.interrupts import (  # noqa: F401
    EXIT_CODE_RESTART,
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.elastic.state import State  # noqa: F401
from horovod_tpu.elastic.worker import (  # noqa: F401
    WorkerNotificationManager,
    notification_manager,
    run,
)

__all__ = [
    "EXIT_CODE_RESTART",
    "HorovodInternalError",
    "HostsUpdatedInterrupt",
    "State",
    "WorkerNotificationManager",
    "notification_manager",
    "run",
]
