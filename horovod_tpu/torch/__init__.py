"""PyTorch frontend: the reference's ``horovod.torch`` API over the
horovod_tpu runtime.

Re-design of ``horovod/torch/__init__.py`` (v0.19): the same
``DistributedOptimizer`` contract — per-parameter hooks fire an async
allreduce the moment a gradient is accumulated, ``step()`` synchronizes
them all — with the C++ binding layer (``mpi_ops_v2.cc`` + HandleManager)
replaced by the native control-plane runtime shared with the JAX path.
Torch here is the CPU host frontend; the collectives themselves execute
as XLA programs.
"""

from __future__ import annotations

import io
import pickle
from contextlib import contextmanager

import torch

from horovod_tpu.basics import (  # noqa: F401 — re-exports (basics.py:22-211)
    cross_rank, cross_size, init, is_initialized, local_rank, local_size,
    rank, shutdown, size,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, Sum,
    allgather, allgather_async, allreduce, allreduce_, allreduce_async,
    allreduce_async_, alltoall, alltoall_async, broadcast, broadcast_,
    broadcast_async, broadcast_async_, poll, synchronize,
)


def join() -> int:
    from horovod_tpu.join import join as _join

    return _join()


def _resolve_parameter_names(param_groups, named_parameters, prefix):
    """Normalize ``named_parameters`` into a param->name dict; without
    names, number every parameter (across groups) ``{prefix}.noname.{i}``.
    Names must be unique and identical on every rank — the coordinator
    matches tensors by name."""
    if named_parameters is not None:
        named_parameters = list(named_parameters)
    else:
        named_parameters = [
            (f"{prefix}.noname.{i}", v)
            for i, v in enumerate(
                p for group in param_groups for p in group["params"])
        ]
    if len({n for n, _ in named_parameters}) < len(named_parameters):
        raise ValueError(
            "named_parameters contains duplicate parameter names")
    return {v: n for n, v in named_parameters}


class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer: gradients are allreduced asynchronously as
    autograd accumulates them, and ``step`` waits for all handles.

    Reference: ``torch/__init__.py:61-216`` — grad-accumulator hooks →
    ``allreduce_async_``, ``synchronize()`` before ``super().step()``,
    ``backward_passes_per_step`` local accumulation.
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, op=Average):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step

        self._parameter_names = _resolve_parameter_names(
            self.param_groups, named_parameters, "allreduce")
        self._handles: dict = {}
        self._grad_passes: dict = {}
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = []
        # Hooks register unconditionally (reference behavior): with one
        # worker the allreduce is an identity, so single-process runs
        # exercise the same code path they'll run distributed.
        self._register_hooks()

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    h = p.register_post_accumulate_grad_hook(
                        self._make_hook(p))
                    self._hook_handles.append(h)

    def _make_hook(self, p):
        def hook(param):
            # Local accumulation: only allreduce every
            # backward_passes_per_step-th pass (reference
            # torch/__init__.py:95-157).
            passes = self._grad_passes.get(p, 0) + 1
            self._grad_passes[p] = passes
            if passes % self.backward_passes_per_step != 0:
                return
            if p in self._handles:
                raise AssertionError(
                    "Gradient for parameter was reduced twice before "
                    "step(); call synchronize() or increase "
                    "backward_passes_per_step")
            name = self._parameter_names[p]
            self._handles[p] = allreduce_async_(
                p.grad, name=f"allreduce.{name}", op=self._op,
                compression=self._compression,
                prescale_factor=1.0 / self.backward_passes_per_step,
            )

        return hook

    def synchronize(self):
        """Wait for every outstanding gradient allreduce
        (``torch/__init__.py:159-207``)."""
        for p, h in list(self._handles.items()):
            synchronize(h)
        self._handles.clear()
        self._grad_passes.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Use when ``synchronize()`` was called manually before
        ``step()`` (e.g. for gradient clipping)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                pass  # user already synchronized explicitly
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize()")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Delta-model Adasum: combine LOCAL OPTIMIZER UPDATES, not gradients.

    The published Adasum usage mode (reference
    ``torch/__init__.py:219-407`` ``_DistributedAdasumOptimizer``,
    ``tensorflow/__init__.py:313-407``):

        start  = params at the last sync
        step() = local optimizer update (adaptive scaling included)
        delta  = params - start        (cumulative over k local steps)
        global = allreduce(delta, op=Adasum)
        start += global ; params = start

    The deltas are submitted as async native collectives per parameter
    (overlapping like the reference's hook-fired allreduces), then
    synchronized and applied.
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step

        self._parameter_names = _resolve_parameter_names(
            self.param_groups, named_parameters, "adasum")
        self._starting_models: dict = {}
        self._step_count = 0

    def _snapshot_starts(self):
        for group in self.param_groups:
            for p in group["params"]:
                self._starting_models[p] = p.detach().clone()

    def synchronize(self):
        """No-op for API parity: the delta allreduce happens inside
        ``step()`` (reference ``torch/__init__.py:350-352``)."""

    @contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using Adasum "
            "optimizer.")
        yield  # pragma: no cover

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        if self._step_count == 0:
            self._snapshot_starts()  # start = initial (broadcast) params
        super(self.__class__, self).step()  # LOCAL update
        self._step_count += 1
        if self._step_count % self.backward_passes_per_step != 0:
            return loss  # workers drift locally until the comm step

        handles = []
        for group in self.param_groups:
            for p in group["params"]:
                start = self._starting_models.get(p)
                if start is None:  # param added after construction
                    continue
                delta = p.detach() - start
                name = f"adasum.delta.{self._parameter_names.get(p, id(p))}"
                h = allreduce_async(delta, name=name, op=Adasum,
                                    compression=self._compression)
                handles.append((p, start, h))
        for p, start, h in handles:
            start.add_(synchronize(h))
            p.data.copy_(start)
        return loss

    def zero_grad(self, *args, **kwargs):
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average):
    """Factory mirroring ``hvd.DistributedOptimizer``
    (``torch/__init__.py`` factory): returns an instance of a dynamic
    subclass of the wrapped optimizer's type.  ``op=Adasum`` selects the
    delta-model optimizer (local update, Adasum-combined parameter
    deltas) exactly as the reference factory does; with one worker the
    plain gradient-averaging wrapper is an identity and is used instead.
    """
    if op == Adasum and size() > 1:
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedAdasumOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op)


# --- state broadcast ----------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a state_dict or list of (name, tensor) pairs from
    ``root_rank`` in place (``torch/__init__.py`` broadcast_parameters)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    for name, p in items:
        if torch.is_tensor(p):
            broadcast_(p, root_rank, name=f"broadcast.{name}")


def broadcast_object(obj, root_rank: int = 0, name: str = "broadcast.object"):
    """Pickle-broadcast an arbitrary object (reference broadcast_object,
    which uses cloudpickle; plain pickle covers optimizer state)."""
    if rank() == root_rank:
        buf = pickle.dumps(obj)
        arr = torch.ByteTensor(bytearray(buf))
        sz = torch.IntTensor([arr.numel()])
    else:
        arr = torch.ByteTensor()
        sz = torch.IntTensor([0])
    sz = broadcast(sz, root_rank, name=f"{name}.size")
    if rank() != root_rank:
        arr = torch.zeros(int(sz[0]), dtype=torch.uint8)
    arr = broadcast(arr, root_rank, name=f"{name}.data")
    return pickle.loads(bytes(arr.numpy().tobytes()))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state from root to all processes
    (``torch/__init__.py`` broadcast_optimizer_state: tensor state is
    broadcast as tensors, scalar state rides pickled)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()
    # Rank 0's structure (param groups + which state keys exist, with
    # tensor shapes/dtypes so ranks MISSING that state — e.g. after a
    # rank-0-only checkpoint restore — can materialize zero buffers and
    # participate; the reference auto-materializes missing state too).
    meta = broadcast_object(
        {
            "param_groups": state_dict["param_groups"],
            "state_keys": {
                pid: sorted(
                    (k, torch.is_tensor(v),
                     tuple(v.shape) if torch.is_tensor(v) else None,
                     str(v.dtype) if torch.is_tensor(v) else None)
                    for k, v in st.items()
                )
                for pid, st in state_dict["state"].items()
            },
        },
        root_rank,
        name="broadcast.opt.meta",
    )
    if rank() != root_rank:
        state_dict["param_groups"] = meta["param_groups"]
    scalars = {}
    if rank() == root_rank:
        scalars = {
            (pid, k): v
            for pid, st in state_dict["state"].items()
            for k, v in st.items()
            if not torch.is_tensor(v)
        }
    scalars = broadcast_object(scalars, root_rank, name="broadcast.opt.scalars")
    new_state: dict = {}
    for pid, keys in meta["state_keys"].items():
        st = state_dict["state"].get(pid, {})
        new_state[pid] = {}
        for k, is_tensor, shape, dtype_str in keys:
            if is_tensor:
                local = st.get(k)
                if local is None or not torch.is_tensor(local):
                    # materialize a same-shaped zero buffer so this rank
                    # submits a matching collective; the broadcast
                    # overwrites it with root's values
                    local = torch.zeros(
                        shape, dtype=getattr(torch, dtype_str.split(".")[-1]))
                new_state[pid][k] = broadcast(
                    local, root_rank, name=f"broadcast.opt.{pid}.{k}")
            else:
                new_state[pid][k] = scalars[(pid, k)]
    state_dict["state"] = new_state
    optimizer.load_state_dict(state_dict)
