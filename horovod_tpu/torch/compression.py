"""Gradient compression for the torch frontend.

Reference: ``horovod/torch/compression.py`` — ``Compression.none`` /
``Compression.fp16`` compressor classes whose ``compress`` returns
``(tensor, ctx)`` and ``decompress`` restores the original dtype.
"""

from __future__ import annotations

import torch


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    """Cast float tensors to fp16 before the wire, back after
    (``compression.py`` FP16Compressor)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
