"""Torch tensor collectives over the horovod_tpu runtime.

Reference: ``horovod/torch/mpi_ops.py:72-508`` (handle-based async API
backed by ``mpi_ops_v2.cc``'s HandleManager).  Here torch CPU tensors
bridge zero-copy to numpy, ride the same eager path as JAX arrays —
negotiated/fused/cached by the native control plane when it's running —
and come back as torch tensors.  ``op=Average`` divides in the collective
like the reference's completion callback (``mpi_ops_v2.cc:69-74``).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import torch

from horovod_tpu.ops import collectives as C

# Reduce-op constants re-exported under the reference's names.
Average = C.Average
Sum = C.Sum
Adasum = C.Adasum
Min = C.Min
Max = C.Max
Product = C.Product


_lock = threading.Lock()
_next_handle = 0
# handle -> (jax-level handle, postprocess(np.ndarray) -> torch.Tensor)
_inflight: dict = {}


def _to_numpy(t: torch.Tensor) -> np.ndarray:
    if t.requires_grad:
        t = t.detach()
    if t.dtype == torch.bfloat16:
        # numpy lacks native bf16; ml_dtypes provides it (jax dependency)
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _from_numpy(a: np.ndarray) -> torch.Tensor:
    if a.dtype.name == "bfloat16":
        return torch.from_numpy(np.ascontiguousarray(a).view(np.uint16)).view(
            torch.bfloat16
        )
    return torch.from_numpy(np.ascontiguousarray(a))


def _register(jax_handle: int, post) -> int:
    global _next_handle
    with _lock:
        h = _next_handle
        _next_handle += 1
        _inflight[h] = (jax_handle, post)
        return h


def synchronize(handle: int) -> torch.Tensor:
    """Wait for an async op and return its torch result
    (``torch/mpi_ops.py`` ``synchronize``)."""
    with _lock:
        jax_handle, post = _inflight.pop(handle)
    return post(C.synchronize(jax_handle))


def poll(handle: int) -> bool:
    with _lock:
        entry = _inflight.get(handle)
    if entry is None:
        return True
    return C.poll(entry[0])


# --- allreduce ----------------------------------------------------------------


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    compression=None) -> int:
    op = _resolve_op(average, op)
    arr = _to_numpy(tensor)
    ctx = None
    if compression is not None:
        tensor_c, ctx = compression.compress(_from_numpy(arr))
        arr = _to_numpy(tensor_c)
    jh = C.allreduce_async(arr, op, name=name,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)

    shape = tuple(tensor.shape)

    def post(a):
        out = _from_numpy(np.asarray(a))
        if compression is not None:
            out = compression.decompress(out, ctx)
        return out.reshape(shape)  # wire promotes 0-d to (1,)

    return _register(jh, post)


class _AllreduceGrad(torch.autograd.Function):
    """Autograd support for the sync allreduce (reference
    ``torch/mpi_ops.py:158-170`` ``HorovodAllreduce``).

    The eager forward is chip-weighted (docs/concepts.md):
    ``y = Σ_p ls_p·x_p`` (Sum) or the same over ``size()`` (Average), so
    the true VJP for process q is ``ls_q · Σ_p g_p`` — a process-level
    sum of cotangents scaled by the LOCAL chip count (and by
    ``1/size()`` for Average).  On homogeneous meshes this equals the
    same-op allreduce of the gradient; expressed this way it stays exact
    with heterogeneous per-process chip counts too."""

    @staticmethod
    def forward(ctx, tensor, op, name, prescale_factor, postscale_factor,
                compression):
        ctx.grad_op = op
        ctx.name = name
        ctx.scale = prescale_factor * postscale_factor
        return synchronize(allreduce_async(
            tensor.detach(), op=op, name=name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, compression=compression))

    @staticmethod
    def backward(ctx, grad):
        from horovod_tpu import basics

        gname = f"{ctx.name}.grad" if ctx.name else None
        g = C.process_sum(_to_numpy(grad), name=gname)
        g = g * np.asarray(basics.local_size() * ctx.scale, g.dtype)
        if ctx.grad_op == Average:
            g = g / np.asarray(basics.size(), g.dtype)
        return (_from_numpy(g).reshape(grad.shape),
                None, None, None, None, None)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              compression=None) -> torch.Tensor:
    if isinstance(tensor, torch.Tensor) and tensor.requires_grad:
        resolved = _resolve_op(average, op)
        if resolved not in (Average, Sum):
            # Min/Max/Product/Adasum have no meaningful linear VJP; a
            # silent Sum backward would train the wrong objective.
            raise RuntimeError(
                f"allreduce(op={resolved}) is not differentiable; call "
                "it on a detached tensor")
        return _AllreduceGrad.apply(
            tensor, resolved, name,
            prescale_factor, postscale_factor, compression)
    return synchronize(allreduce_async(
        tensor, average, name, op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, compression=compression))


def allreduce_async_(tensor, average=None, name=None, op=None, **kw) -> int:
    """In-place variant: the result is copied back into ``tensor`` at
    synchronize time (reference semantics of ``allreduce_async_``)."""
    h = allreduce_async(tensor, average, name, op, **kw)
    with _lock:
        jh, post = _inflight[h]

        def post_inplace(a, _post=post):
            out = _post(a)
            # the data plane promotes 0-d scalars to shape (1,) on the
            # wire (e.g. BatchNorm's num_batches_tracked) — restore
            tensor.data.copy_(out.to(tensor.dtype).reshape(tensor.shape))
            return tensor

        _inflight[h] = (jh, post_inplace)
    return h


def allreduce_(tensor, average=None, name=None, op=None, **kw) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average, name, op, **kw))


def _resolve_op(average, op):
    if op is not None:
        return op
    if average is None or average:
        return Average
    return Sum


# --- allgather / broadcast / alltoall ----------------------------------------


def allgather_async(tensor, name=None) -> int:
    jh = C.allgather_async(_to_numpy(tensor), name=name)
    return _register(jh, lambda a: _from_numpy(np.asarray(a)))


class _AllgatherGrad(torch.autograd.Function):
    """Reference ``HorovodAllgather`` autograd: backward sums the
    cotangent across processes and slices this process's rows.  The
    gather is process-level (one contribution per process), so the sum
    is a process_sum — no chip weighting (gradients stay finite-
    difference-correct)."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.name = name
        ctx.rows = int(tensor.shape[0])
        return synchronize(allgather_async(tensor.detach(), name))

    @staticmethod
    def backward(ctx, grad):
        from horovod_tpu import basics

        gname = f"{ctx.name}.grad" if ctx.name else None
        g = C.process_sum(_to_numpy(grad), name=gname)
        rows = np.asarray([ctx.rows], np.int64)
        sizes = C.allgather(rows,
                            name=f"{gname}.sizes" if gname else None)
        off = int(sizes[:basics.process_rank()].sum())
        return _from_numpy(g[off:off + ctx.rows]), None


def allgather(tensor, name=None) -> torch.Tensor:
    if isinstance(tensor, torch.Tensor) and tensor.requires_grad:
        return _AllgatherGrad.apply(tensor, name)
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor, root_rank, name=None) -> int:
    jh = C.broadcast_async(_to_numpy(tensor), root_rank, name=name)
    shape = tuple(tensor.shape)
    # wire promotes 0-d to (1,): restore the caller's shape
    return _register(
        jh, lambda a: _from_numpy(np.asarray(a)).reshape(shape))


class _BroadcastGrad(torch.autograd.Function):
    """Reference ``HorovodBroadcast`` autograd: backward process-sums the
    cotangent to the root and is zero elsewhere."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.name = name
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor.detach(), root_rank, name))

    @staticmethod
    def backward(ctx, grad):
        from horovod_tpu import basics

        gname = f"{ctx.name}.grad" if ctx.name else None
        g = C.process_sum(_to_numpy(grad), name=gname)
        # root_rank is a worker (chip) rank; this process owns it iff it
        # falls in [rank(), rank() + local_size()).
        lo = basics.rank()
        if not (lo <= ctx.root_rank < lo + basics.local_size()):
            g = np.zeros_like(g)
        return _from_numpy(g).reshape(grad.shape), None, None


def broadcast(tensor, root_rank, name=None) -> torch.Tensor:
    if isinstance(tensor, torch.Tensor) and tensor.requires_grad:
        return _BroadcastGrad.apply(tensor, root_rank, name)
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_async_(tensor, root_rank, name=None) -> int:
    h = broadcast_async(tensor, root_rank, name)
    with _lock:
        jh, post = _inflight[h]

        def post_inplace(a, _post=post):
            out = _post(a)
            # the data plane promotes 0-d scalars to shape (1,) on the
            # wire (e.g. BatchNorm's num_batches_tracked) — restore
            tensor.data.copy_(out.to(tensor.dtype).reshape(tensor.shape))
            return tensor

        _inflight[h] = (jh, post_inplace)
    return h


def broadcast_(tensor, root_rank, name=None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall_async(tensor, splits=None, name=None) -> int:
    jh = C.alltoall_async(_to_numpy(tensor), splits, name=name)
    return _register(jh, lambda a: _from_numpy(np.asarray(a)))


def alltoall(tensor, splits=None, name=None) -> torch.Tensor:
    return synchronize(alltoall_async(tensor, splits, name))
