"""ElasticDriver: supervised restart instead of kill-all.

The reference launcher's only fault policy is any-failure-kills-all
(``gloo_run.py:162-259``, mirrored by :func:`launch_job`).  This module
ports Horovod Elastic's driver (the v0.20 successor of that codebase)
onto the fixed-mesh XLA world, where a membership change means
**stop → re-rendezvous → rebuild mesh → recompile → resume from the
last committed state**:

* per-rank monitoring: exit codes from the spawn watchers, plus optional
  heartbeat staleness over the rendezvous KV (a dead rank exits; a HUNG
  rank only stops heartbeating);
* failed-host blacklisting with an expiring cooldown
  (:class:`horovod_tpu.runner.hosts.Blacklist`);
* :class:`HostDiscovery` (static list or periodically polled script) to
  admit replacement hosts between rendezvous epochs;
* bounded restart: ``min_np`` / ``max_np`` / ``reset_limit`` knobs and
  exponential backoff between epochs;
* clean teardown/restart of the per-rank runtime: on failure the driver
  publishes an ``elastic/notice.<epoch>`` key so surviving ranks exit at
  their next commit boundary (``EXIT_CODE_RESTART``), waits a grace
  period, then terminates stragglers; the next epoch gets distinct
  rendezvous epoch keys and fresh coordination-service ports, and ranks
  re-``init()`` over the surviving mesh, restoring the last durable
  ``State.commit()``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Set

from horovod_tpu.elastic.interrupts import EXIT_CODE_RESTART
from horovod_tpu.elastic.worker import (
    KV_SCOPE,
    heartbeat_key,
    metrics_key,
    notice_key,
    state_key,
)
from horovod_tpu.obs.fleet import FleetMonitor, FleetServer, parse_heartbeat
from horovod_tpu.runner import safe_shell_exec
from horovod_tpu.runner.discovery import FixedHostDiscovery, HostDiscovery
from horovod_tpu.runner.hosts import Blacklist, HostSpec, allocate, parse_hosts
from horovod_tpu.runner.launch import spawn_ranks
from horovod_tpu.runner.rendezvous import RendezvousServer
from horovod_tpu.runner.run_func import _free_port

logger = logging.getLogger("horovod_tpu")


class ElasticJobError(RuntimeError):
    """The elastic job cannot continue (below ``min_np`` or over
    ``reset_limit``) — raised with a clear reason instead of hanging."""


class ElasticDriver:
    """Supervise an elastic job: launch, monitor, re-rendezvous, restart.

    Parameters mirror ``horovodrun --min-np/--max-np`` (Horovod Elastic):

    * ``min_np`` — abort (clearly) when fewer hosts remain available;
    * ``max_np`` — cap the hosts used per epoch;
    * ``reset_limit`` — abort after this many restarts (None = unbounded);
    * ``blacklist_cooldown`` — seconds a failed host stays excluded
      (None = forever);
    * ``heartbeat_timeout`` — treat a rank as failed when its KV
      heartbeat stops changing for this long, on the driver's clock
      (None disables; exit codes are always monitored);
    * ``startup_timeout`` — bound on a spawned rank never heartbeating
      at all (hung inside startup); defaults to
      ``max(60, 10 * heartbeat_timeout)`` when heartbeats are on;
    * ``discovery_timeout`` — how long to keep polling discovery for
      enough hosts before aborting below ``min_np``.  Default 0 =
      fail-fast (right for a static ``-H`` list); with a discovery
      script use a nonzero timeout so one transient script failure
      (which legitimately yields the empty set) does not abort a
      healthy job — the horovodrun CLI defaults it to 60 s there.
    * ``metrics_port`` — serve the fleet observability endpoints
      (``GET /metrics`` Prometheus + ``GET /fleet`` JSON, aggregated
      across ranks with ``rank``/``host`` labels) on this port
      (0 = ephemeral; see :attr:`fleet_address`).  None (default)
      disables the HTTP listener; the :attr:`fleet` monitor — and its
      straggler detection — runs either way.
    * ``straggler_threshold`` / ``straggler_patience`` — a rank whose
      heartbeat-reported step duration exceeds ``threshold`` × the
      fleet median for ``patience`` consecutive step reports is flagged
      (warning + ``elastic_straggler_total{rank=}`` + timeline
      instant).  Report-only: the driver never evicts on slowness.
    """

    def __init__(
        self,
        command: List[str],
        discovery: HostDiscovery,
        *,
        min_np: int = 1,
        max_np: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        reset_limit: Optional[int] = None,
        blacklist_cooldown: Optional[float] = 600.0,
        backoff_initial: float = 1.0,
        backoff_max: float = 30.0,
        shutdown_grace: float = safe_shell_exec.GRACEFUL_TERMINATION_TIME_S + 5.0,
        heartbeat_interval: float = 1.0,
        heartbeat_timeout: Optional[float] = None,
        startup_timeout: Optional[float] = None,
        discovery_timeout: float = 0.0,
        discovery_interval: float = 1.0,
        output_filename: Optional[str] = None,
        coordinator_port: int = 0,
        metrics_port: Optional[int] = None,
        straggler_threshold: float = 2.0,
        straggler_patience: int = 3,
        _executor=safe_shell_exec.execute,
        _sleep=time.sleep,
    ) -> None:
        if min_np < 1:
            raise ValueError("min_np must be >= 1")
        if max_np is not None and max_np < min_np:
            raise ValueError("max_np must be >= min_np")
        self._command = list(command)
        self._discovery = discovery
        self._min_np = min_np
        self._max_np = max_np
        self._env = env
        self._reset_limit = reset_limit
        self.blacklist = Blacklist(cooldown=blacklist_cooldown)
        self._backoff_initial = backoff_initial
        self._backoff_max = backoff_max
        self._shutdown_grace = shutdown_grace
        self._heartbeat_interval = heartbeat_interval
        self._heartbeat_timeout = heartbeat_timeout
        # Bound on "spawned but never heartbeated" (hung inside startup,
        # before the worker can publish): generous, because a cold rank
        # pays imports + mesh compile before its first beat.
        if startup_timeout is None and heartbeat_timeout is not None:
            startup_timeout = max(60.0, 10.0 * heartbeat_timeout)
        self._startup_timeout = startup_timeout
        self._discovery_timeout = discovery_timeout
        self._discovery_interval = discovery_interval
        self._output_filename = output_filename
        self._coordinator_port = coordinator_port
        self._executor = _executor
        self._sleep = _sleep
        self.epoch = 0
        self.resets = 0
        self.epoch_sizes: List[int] = []  # world size used per epoch
        # Fleet observability (docs/observability.md "Fleet"): the
        # monitor aggregates worker registry exports + step durations
        # off the rendezvous KV and runs straggler detection; the HTTP
        # listener (metrics_port) exposes /metrics + /fleet.
        self.fleet = FleetMonitor(
            straggler_threshold=straggler_threshold,
            straggler_patience=straggler_patience)
        self._metrics_port = metrics_port
        self._fleet_server: Optional[FleetServer] = None
        self._fleet_raw: Dict[int, tuple] = {}  # rank -> (hb, metrics)

    @property
    def fleet_address(self):
        """(host, port) of the fleet metrics endpoint, or None when
        ``metrics_port`` was not given or the job is not running."""
        if self._fleet_server is None:
            return None
        return self._fleet_server.address

    # ---- public ----------------------------------------------------------

    def run(self) -> int:
        """Run the job to completion; returns 0 on success.  Raises
        :class:`ElasticJobError` when the job cannot continue."""
        env = dict(self._env if self._env is not None else os.environ)
        if "HOROVOD_SECRET_KEY" not in env:
            from horovod_tpu.runner import secret

            env["HOROVOD_SECRET_KEY"] = secret.make_secret_key()
        server = RendezvousServer(
            self._coordinator_port,
            secret_key=env["HOROVOD_SECRET_KEY"].encode())
        port = server.start()
        addr = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")
        try:
            if self._metrics_port is not None:
                try:
                    # 0.0.0.0 like the rendezvous server: the scrape
                    # endpoint exists to be reached from OFF this host.
                    self._fleet_server = FleetServer(
                        self.fleet, host="0.0.0.0",
                        port=self._metrics_port).start()
                except OSError as e:
                    # Observability failing must not fail training —
                    # the job runs on, just without the scrape endpoint.
                    logger.warning(
                        "fleet: metrics endpoint unavailable "
                        "(port %s: %s); continuing without it",
                        self._metrics_port, e)
            while True:
                specs = self._wait_for_available_hosts()
                ok, culprits, restart_requested = self._run_epoch(
                    specs, env, addr, port, server)
                if ok:
                    return 0
                for h in sorted(culprits):
                    logger.warning(
                        "elastic: blacklisting host %s (failure #%d)",
                        h, self.blacklist.failure_count(h) + 1)
                    self.blacklist.add(h)
                self._register_reset(culprits, restart_requested)
                self.epoch += 1
        finally:
            server.stop()
            if self._fleet_server is not None:
                self._fleet_server.stop()
                self._fleet_server = None

    # ---- membership ------------------------------------------------------

    def _wait_for_available_hosts(self) -> List[HostSpec]:
        """Poll discovery until at least ``min_np`` non-blacklisted hosts
        are available, or ``discovery_timeout`` elapses — then abort with
        a clear error instead of hanging."""
        deadline = time.monotonic() + self._discovery_timeout
        while True:
            discovered = self._discovery.find_available_hosts()
            specs = self.blacklist.filter(discovered)
            if len(specs) >= self._min_np:
                if self._max_np is not None:
                    specs = specs[: self._max_np]
                return specs
            if time.monotonic() >= deadline:
                raise ElasticJobError(
                    f"elastic job cannot continue: {len(specs)} host(s) "
                    f"available, below min_np={self._min_np} "
                    f"(discovered={[s.hostname for s in discovered]}, "
                    f"blacklisted={self.blacklist.hosts()})")
            self._sleep(self._discovery_interval)

    def _register_reset(self, culprits: Set[str], restart_requested: bool) -> None:
        self.resets += 1
        try:
            from horovod_tpu.obs.registry import elastic_metrics

            elastic_metrics().restarts.inc()
        except Exception:  # pragma: no cover - metrics never gate recovery
            pass
        if self._reset_limit is not None and self.resets > self._reset_limit:
            raise ElasticJobError(
                f"elastic job aborted: reset_limit={self._reset_limit} "
                f"exceeded after {self.resets} restarts "
                f"(last failure: hosts={sorted(culprits)}, "
                f"restart_requested={restart_requested})")
        backoff = min(self._backoff_initial * (2.0 ** (self.resets - 1)),
                      self._backoff_max)
        logger.warning(
            "elastic: restart #%d (epoch %d -> %d) in %.1fs",
            self.resets, self.epoch, self.epoch + 1, backoff)
        self._sleep(backoff)

    # ---- one rendezvous epoch --------------------------------------------

    def _epoch_env(self, env: Dict[str, str]) -> Dict[str, str]:
        eenv = dict(env)
        eenv["HOROVOD_ELASTIC"] = "1"
        eenv["HOROVOD_ELASTIC_EPOCH"] = str(self.epoch)
        eenv["HOROVOD_ELASTIC_MIN_NP"] = str(self._min_np)
        eenv.setdefault("HOROVOD_ELASTIC_HEARTBEAT",
                        repr(self._heartbeat_interval))
        # The dead epoch's coordination sockets (JAX gRPC service, native
        # control plane) may linger in TIME_WAIT; every epoch gets fresh
        # ports.  A user-provided port becomes the epoch-0 base.
        for var in ("HOROVOD_JAX_PORT", "HOROVOD_NATIVE_PORT"):
            if env.get(var):
                eenv[var] = str(int(env[var]) + 2 * self.epoch)
            else:
                eenv[var] = str(_free_port())
        return eenv

    def _run_epoch(self, specs, env, addr, port, server):
        """Returns ``(success, culprit_hosts, restart_requested)``."""
        slots = allocate(specs)
        eenv = self._epoch_env(env)
        # Stale NIC-discovery reports from the dead world must not leak
        # into this rendezvous.
        server.clear_scope("discovery")
        server.put(KV_SCOPE, state_key(self.epoch), json.dumps({
            "epoch": self.epoch,
            "size": len(slots),
            "hosts": [s.hostname for s in specs],
        }).encode())
        self.epoch_sizes.append(len(slots))
        try:
            from horovod_tpu.obs import tracing as obs_tracing
            from horovod_tpu.obs.registry import elastic_metrics

            m = elastic_metrics()
            m.rendezvous.inc()
            m.rendezvous_epoch.set(self.epoch)
            obs_tracing.instant("elastic_rendezvous", {
                "epoch": self.epoch, "size": len(slots),
                "hosts": [s.hostname for s in specs]})
        except Exception:  # pragma: no cover - metrics never gate the epoch
            pass
        logger.warning(
            "elastic: epoch %d starting with %d host(s): %s",
            self.epoch, len(specs), [s.hostname for s in specs])

        out_dir = None
        if self._output_filename:
            out_dir = os.path.join(self._output_filename,
                                   f"epoch.{self.epoch}")

        failure = threading.Event()
        lock = threading.Lock()
        culprits: Set[str] = set()
        restart_requested = False
        first_failure: List[Optional[float]] = [None]
        notice_sent = [False]

        def _notify_failure(reason: str) -> None:
            # Publish the membership-change notice so surviving ranks
            # exit at their next commit boundary instead of being killed
            # mid-step; stragglers are terminated after the grace period.
            if not notice_sent[0]:
                notice_sent[0] = True
                server.put(KV_SCOPE, notice_key(self.epoch),
                           json.dumps({"reason": reason}).encode())
            if first_failure[0] is None:
                first_failure[0] = time.monotonic()

        def _on_exit(i: int, slot, rc: int) -> None:
            nonlocal restart_requested
            with lock:
                if rc == 0:
                    return
                if rc == EXIT_CODE_RESTART:
                    restart_requested = True
                    _notify_failure(f"rank {slot.rank} requested restart")
                elif rc < 0 or rc in (128 + 15, 128 + 9):
                    # A signal death AFTER another failure is (almost
                    # always) the driver's own TERM/KILL escalation — not
                    # the culprit.  As the FIRST failure it is the real
                    # event (OOM killer, external kill): blame the host,
                    # or a persistently dying host would never blacklist
                    # and the job would crash-loop on it forever.
                    if first_failure[0] is None:
                        culprits.add(slot.hostname)
                        logger.warning(
                            "elastic: rank %d on %s killed by signal "
                            "(code %d)", slot.rank, slot.hostname, rc)
                    _notify_failure(f"rank {slot.rank} terminated")
                else:
                    culprits.add(slot.hostname)
                    logger.warning(
                        "elastic: rank %d on %s exited with code %d",
                        slot.rank, slot.hostname, rc)
                    _notify_failure(
                        f"rank {slot.rank} on {slot.hostname} failed ({rc})")

        threads, exit_codes = spawn_ranks(
            self._command, slots, eenv, addr, port,
            output_filename=out_dir, failure=failure,
            on_rank_exit=_on_exit, _executor=self._executor)

        self.fleet.begin_epoch(self.epoch)
        self._fleet_raw.clear()
        epoch_start = time.monotonic()
        next_fleet_poll = 0.0
        hb_seen: Dict[int, tuple] = {}  # rank -> (value, driver mono time)
        while any(rc is None for rc in exit_codes):
            self._sleep(0.1)
            now = time.monotonic()
            if self._heartbeat_timeout is not None:
                self._check_heartbeats(server, slots, exit_codes, lock,
                                       culprits, _notify_failure,
                                       hb_seen, epoch_start)
            if now >= next_fleet_poll:
                next_fleet_poll = now + self._heartbeat_interval
                self._poll_fleet(server, slots, exit_codes)
            with lock:
                expired = (first_failure[0] is not None
                           and now - first_failure[0] >= self._shutdown_grace)
            if expired:
                failure.set()
        for t in threads:
            t.join()

        success = all(rc == 0 for rc in exit_codes)
        return success, culprits, restart_requested

    def _check_heartbeats(self, server, slots, exit_codes, lock, culprits,
                          notify, hb_seen, epoch_start) -> None:
        """A rank whose KV heartbeat went stale is HUNG (it would never
        produce an exit code): mark its host as the culprit and trigger
        the notice → grace → terminate sequence.

        Staleness is measured on the DRIVER's monotonic clock from when
        each heartbeat VALUE was first observed to change — immune to
        worker-host wall-clock skew.  A rank that never heartbeats at all
        (hung inside startup, before the notification manager runs) goes
        stale ``startup_timeout`` after the epoch began."""
        now = time.monotonic()

        def _stale(slot, age):
            with lock:
                if slot.hostname not in culprits:
                    logger.warning(
                        "elastic: rank %d on %s heartbeat stale (%.1fs); "
                        "treating as failed", slot.rank, slot.hostname, age)
                    culprits.add(slot.hostname)
                    notify(f"rank {slot.rank} on {slot.hostname} "
                           "heartbeat stale")

        for i, slot in enumerate(slots):
            if exit_codes[i] is not None:
                continue
            raw = server.get(KV_SCOPE, heartbeat_key(self.epoch, slot.rank))
            if raw is None:
                # Never heartbeated: hung before the worker-side manager
                # started (e.g. wedged inside init()).
                if now - epoch_start >= self._startup_timeout:
                    _stale(slot, now - epoch_start)
                continue
            prev = hb_seen.get(slot.rank)
            if prev is None or prev[0] != raw:
                hb_seen[slot.rank] = (raw, now)
                continue
            if now - prev[1] >= self._heartbeat_timeout:
                _stale(slot, now - prev[1])

    def _poll_fleet(self, server, slots, exit_codes) -> None:
        """Feed the fleet monitor from the rendezvous KV: each live
        rank's heartbeat payload (step durations → straggler
        detection) and registry export (→ the aggregated /metrics).
        Never gates the epoch — fleet observability failing must not
        fail training."""
        for i, slot in enumerate(slots):
            if exit_codes[i] is not None:
                continue
            try:
                hb = server.get(KV_SCOPE,
                                heartbeat_key(self.epoch, slot.rank))
                mx = server.get(KV_SCOPE,
                                metrics_key(self.epoch, slot.rank))
                prev_hb, prev_mx = self._fleet_raw.get(slot.rank,
                                                       (None, None))
                if hb is not None and hb != prev_hb:
                    self.fleet.heartbeat(slot.rank, slot.hostname,
                                         parse_heartbeat(hb))
                if mx is not None and mx != prev_mx:
                    self.fleet.snapshot(slot.rank, slot.hostname,
                                        json.loads(mx))
                self._fleet_raw[slot.rank] = (hb, mx)
            except Exception as e:  # pragma: no cover - defensive
                logger.debug("fleet: poll failed for rank %d: %s",
                             slot.rank, e)


def run_elastic(
    command: List[str],
    *,
    discovery: Optional[HostDiscovery] = None,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    min_np: int = 1,
    max_np: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
    **driver_kwargs,
) -> int:
    """Programmatic / CLI entry point: build a :class:`HostDiscovery`
    from a static host list unless one is given, then supervise the job
    with an :class:`ElasticDriver`.  Returns the job's exit code; raises
    :class:`ElasticJobError` when the job cannot continue."""
    if discovery is None:
        discovery = FixedHostDiscovery(parse_hosts(hosts, hostfile))
    driver = ElasticDriver(command, discovery, min_np=min_np, max_np=max_np,
                           env=env, **driver_kwargs)
    return driver.run()
