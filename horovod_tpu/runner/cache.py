"""Launcher check cache (reference ``horovod/run/util/cache.py``: a
``~/.horovod`` JSON cache that remembers expensive pre-flight results —
ssh reachability, build checks — so repeated launches skip them).

Entries carry timestamps and expire after ``ttl_seconds``; corrupt or
unreadable cache files are treated as empty, never fatal (a cache must
not be able to break a launch).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

DEFAULT_PATH = os.path.join("~", ".horovod_tpu", "cache.json")
DEFAULT_TTL = 60 * 60  # reference uses a fixed per-parameter cache; 1h here


class Cache:
    def __init__(self, path: Optional[str] = None,
                 ttl_seconds: float = DEFAULT_TTL) -> None:
        # DEFAULT_PATH read at call time so tests (and users) can point
        # the module-level default elsewhere.
        self.path = os.path.expanduser(path or DEFAULT_PATH)
        self.ttl = ttl_seconds

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except Exception:
            return {}

    def get(self, key: str) -> Optional[Any]:
        ent = self._load().get(key)
        if not isinstance(ent, dict):
            return None
        if time.time() - float(ent.get("ts", 0)) > self.ttl:
            return None
        return ent.get("value")

    def put(self, key: str, value: Any) -> None:
        data = self._load()
        data[key] = {"value": value, "ts": time.time()}
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self.path)
        except Exception:
            pass  # never let the cache break a launch
