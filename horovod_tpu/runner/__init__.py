"""Launcher layer (reference layer 5): ``horovodrun``-equivalent CLI,
host parsing, config-file/env normalization, rendezvous, process spawn.

Reference: ``horovod/run/run.py:395-960``, ``run/gloo_run.py``,
``run/common/util/config_parser.py``, ``run/http/http_server.py``.
"""

from horovod_tpu.runner.hosts import HostSpec, SlotInfo, allocate, parse_hosts  # noqa: F401
from horovod_tpu.runner.launch import launch_job  # noqa: F401
from horovod_tpu.runner.run_func import run  # noqa: F401 — the
# programmatic API (reference ``horovod.run.run()``):
# runner.run(fn, args, num_proc=N) -> per-rank results.
