"""Job launch: spawn one process per host with the rank/rendezvous env.

Reference: ``run/gloo_run.py`` (rank allocation → RendezvousServer → per
slot ssh/local spawn with HOROVOD_* env → output capture → kill-all on any
failure).  The mpirun path (``run/mpi_run.py``) has no TPU analogue: there
is no external runtime to delegate to, so this module IS the process
manager.
"""

from __future__ import annotations

import os
import shlex
import signal
import sys
import threading
from typing import Dict, List, Optional, Tuple

from horovod_tpu.runner import safe_shell_exec
from horovod_tpu.runner.hosts import HostSpec, SlotInfo, allocate
from horovod_tpu.runner.rendezvous import RendezvousServer

SSH_COMMAND_PREFIX = "ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no"


def _is_local(hostname: str) -> bool:
    # "localhost-<suffix>" names are also local: distinct LOGICAL hosts on
    # one machine, used by elastic fault-injection drills and
    # single-machine simulation where host-level blacklisting must
    # distinguish the "hosts".  The dash is deliberate — a real cluster
    # host named e.g. "localhost2" must still go over ssh.
    return (hostname in ("localhost", "localhost.localdomain", "127.0.0.1",
                         os.uname().nodename)
            or hostname.startswith("localhost-"))


def build_command(
    slot: SlotInfo,
    command: List[str],
    env: Dict[str, str],
    coordinator_addr: str,
    coordinator_port: int,
) -> (List[str], Dict[str, str], Optional[bytes]):
    """The env contract every rank receives (reference
    ``gloo_run.py:262-288``).  Returns (argv, env, stdin_bytes): for
    remote slots the per-job HMAC secret travels over the ssh channel's
    stdin, never on the command line where any local user could read it
    from /proc/<pid>/cmdline."""
    slot_env = dict(env)
    slot_env.update(slot.to_env())
    slot_env["HOROVOD_COORDINATOR_ADDR"] = coordinator_addr
    slot_env["HOROVOD_COORDINATOR_PORT"] = str(coordinator_port)
    slot_env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = coordinator_addr  # compat name
    slot_env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(coordinator_port)
    if _is_local(slot.hostname):
        # Local spawn: env travels through Popen(env=...), not argv — safe.
        return command, slot_env, None
    secret_val = slot_env.get("HOROVOD_SECRET_KEY")
    exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in slot_env.items()
        if k != "HOROVOD_SECRET_KEY"
        and k.startswith(("HOROVOD_", "PYTHON", "PATH", "JAX_", "XLA_"))
    )
    cmd_str = " ".join(shlex.quote(c) for c in command)
    stdin_data = None
    if secret_val:
        remote = (
            f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; "
            f"IFS= read -r HOROVOD_SECRET_KEY ; export HOROVOD_SECRET_KEY ; "
            f"env {exports} HOROVOD_SECRET_KEY=\"$HOROVOD_SECRET_KEY\" {cmd_str}"
        )
        stdin_data = (secret_val + "\n").encode()
    else:
        remote = (
            f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; "
            f"env {exports} {cmd_str}"
        )
    return (shlex.split(SSH_COMMAND_PREFIX) + [slot.hostname, remote], env,
            stdin_data)


def spawn_ranks(
    command: List[str],
    slots: List[SlotInfo],
    env: Dict[str, str],
    coordinator_addr: str,
    coordinator_port: int,
    *,
    output_filename: Optional[str] = None,
    failure: Optional[threading.Event] = None,
    on_rank_exit=None,
    _executor=safe_shell_exec.execute,
) -> Tuple[List[threading.Thread], List[Optional[int]]]:
    """Start one supervised spawn thread per slot; returns the (started)
    threads and the shared exit-code list they fill in.

    The per-epoch core shared by :func:`launch_job` (single round,
    kill-all) and the ElasticDriver (round per rendezvous epoch,
    supervised restart).  ``failure`` set → every rank's process group is
    terminated (TERM → grace → KILL); ``on_rank_exit(index, slot, rc)``
    fires as each rank exits, from that rank's watcher thread."""
    exit_codes: List[Optional[int]] = [None] * len(slots)

    def _run(i: int, slot: SlotInfo) -> None:
        # EVERY exit path must record an exit code: a None left behind
        # would wedge supervisors polling this list (the ElasticDriver's
        # epoch monitor) and read as success in launch_job's rollup.
        out = err = None
        try:
            try:
                cmd, slot_env, stdin_data = build_command(
                    slot, command, env, coordinator_addr, coordinator_port)
                if output_filename:
                    os.makedirs(output_filename, exist_ok=True)
                    out = open(os.path.join(
                        output_filename, f"rank.{slot.rank}.stdout"), "w")
                    err = open(os.path.join(
                        output_filename, f"rank.{slot.rank}.stderr"), "w")
                prefix = (f"[{slot.rank}]<stdout>:"
                          if len(slots) > 1 else None)
                rc = _executor(
                    cmd,
                    env=slot_env,
                    stdout=out or sys.stdout,
                    stderr=err or sys.stderr,
                    prefix=prefix,
                    events=[failure] if failure is not None else [],
                    stdin_data=stdin_data,
                )
            except Exception:
                import traceback

                try:
                    traceback.print_exc(file=err or sys.stderr)
                except OSError:
                    pass
                rc = 1
        finally:
            for f in (out, err):
                if f:
                    try:
                        f.close()
                    except OSError:  # e.g. ENOSPC on the buffered flush
                        pass
            exit_codes[i] = rc
            if on_rank_exit is not None:
                on_rank_exit(i, slot, rc)

    threads = []
    for i, slot in enumerate(slots):
        t = threading.Thread(target=_run, args=(i, slot), daemon=True)
        t.start()
        threads.append(t)
    return threads, exit_codes


def launch_job(
    command: List[str],
    host_specs: List[HostSpec],
    *,
    env: Optional[Dict[str, str]] = None,
    output_filename: Optional[str] = None,
    coordinator_port: int = 0,
    _executor=safe_shell_exec.execute,
) -> int:
    """Launch ``command`` on every host; returns first nonzero exit code
    (and terminates all other ranks when any rank fails — the reference's
    any-failure-kills-all policy, ``gloo_run.py:162-259``).  For
    supervised restart instead of kill-all, see
    :mod:`horovod_tpu.runner.elastic_driver`."""
    env = dict(env if env is not None else os.environ)
    # Per-job HMAC secret so only this job's ranks can write rendezvous
    # state (reference run/common/util/secret.py usage in gloo_run).
    if "HOROVOD_SECRET_KEY" not in env:
        from horovod_tpu.runner import secret

        env["HOROVOD_SECRET_KEY"] = secret.make_secret_key()
    slots = allocate(host_specs)
    server = RendezvousServer(
        coordinator_port, secret_key=env["HOROVOD_SECRET_KEY"].encode())
    port = server.start()
    addr = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")

    failure = threading.Event()
    # Pre-sized so the signal handler's "wait for the watchers" loop is
    # correct even if a signal lands before spawn_ranks rebinds it.
    exit_codes: List[Optional[int]] = [None] * len(slots)

    def _on_exit(i: int, slot: SlotInfo, rc: int) -> None:
        if rc != 0:
            failure.set()

    # Terminating the launcher must terminate every rank (the reference's
    # SIGTERM path, gloo_run.py:201): ranks run in their own sessions, so
    # without this a killed launcher orphans them mid-collective.
    prev_handlers = {}

    def _on_signal(signum, frame):
        import time

        failure.set()
        # Stay alive until the per-rank watchers finish their TERM ->
        # (grace) -> KILL escalation: ranks may swallow SIGTERM (JAX
        # installs a preemption notifier that catches it), so dying after
        # a token sleep would leave them orphaned mid-escalation.
        deadline = (time.time() + safe_shell_exec.GRACEFUL_TERMINATION_TIME_S
                    + 2.0)
        while time.time() < deadline and any(rc is None for rc in exit_codes):
            time.sleep(0.2)
        prev = prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    in_main = threading.current_thread() is threading.main_thread()
    if in_main:
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # pragma: no cover
                pass

    try:
        threads, exit_codes = spawn_ranks(
            command, slots, env, addr, port,
            output_filename=output_filename, failure=failure,
            on_rank_exit=_on_exit, _executor=_executor)
        for t in threads:
            t.join()
    finally:
        server.stop()
        if in_main:
            for sig, prev in prev_handlers.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):  # pragma: no cover
                    pass
    bad = [rc for rc in exit_codes if rc]
    return bad[0] if bad else 0
