"""Job launch: spawn one process per host with the rank/rendezvous env.

Reference: ``run/gloo_run.py`` (rank allocation → RendezvousServer → per
slot ssh/local spawn with HOROVOD_* env → output capture → kill-all on any
failure).  The mpirun path (``run/mpi_run.py``) has no TPU analogue: there
is no external runtime to delegate to, so this module IS the process
manager.
"""

from __future__ import annotations

import os
import shlex
import sys
import threading
from typing import Dict, List, Optional

from horovod_tpu.runner import safe_shell_exec
from horovod_tpu.runner.hosts import HostSpec, SlotInfo, allocate
from horovod_tpu.runner.rendezvous import RendezvousServer

SSH_COMMAND_PREFIX = "ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no"


def _is_local(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", os.uname().nodename)


def build_command(
    slot: SlotInfo,
    command: List[str],
    env: Dict[str, str],
    coordinator_addr: str,
    coordinator_port: int,
) -> (List[str], Dict[str, str]):
    """The env contract every rank receives (reference
    ``gloo_run.py:262-288``)."""
    slot_env = dict(env)
    slot_env.update(slot.to_env())
    slot_env["HOROVOD_COORDINATOR_ADDR"] = coordinator_addr
    slot_env["HOROVOD_COORDINATOR_PORT"] = str(coordinator_port)
    slot_env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = coordinator_addr  # compat name
    slot_env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(coordinator_port)
    if _is_local(slot.hostname):
        return command, slot_env
    exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in slot_env.items()
        if k.startswith(("HOROVOD_", "PYTHON", "PATH", "JAX_", "XLA_"))
    )
    remote = f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; env {exports} {' '.join(shlex.quote(c) for c in command)}"
    return shlex.split(SSH_COMMAND_PREFIX) + [slot.hostname, remote], env


def launch_job(
    command: List[str],
    host_specs: List[HostSpec],
    *,
    env: Optional[Dict[str, str]] = None,
    output_filename: Optional[str] = None,
    coordinator_port: int = 0,
    _executor=safe_shell_exec.execute,
) -> int:
    """Launch ``command`` on every host; returns first nonzero exit code
    (and terminates all other ranks when any rank fails — the reference's
    any-failure-kills-all policy, ``gloo_run.py:162-259``)."""
    env = dict(env if env is not None else os.environ)
    slots = allocate(host_specs)
    server = RendezvousServer(coordinator_port)
    port = server.start()
    addr = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")

    exit_codes: List[Optional[int]] = [None] * len(slots)
    failure = threading.Event()
    threads = []

    def _run(i: int, slot: SlotInfo) -> None:
        cmd, slot_env = build_command(slot, command, env, addr, port)
        out = err = None
        if output_filename:
            os.makedirs(output_filename, exist_ok=True)
            out = open(os.path.join(output_filename, f"rank.{slot.rank}.stdout"), "w")
            err = open(os.path.join(output_filename, f"rank.{slot.rank}.stderr"), "w")
        prefix = f"[{slot.rank}]<stdout>:" if len(slots) > 1 else None
        try:
            rc = _executor(
                cmd,
                env=slot_env,
                stdout=out or sys.stdout,
                stderr=err or sys.stderr,
                prefix=prefix,
                events=[failure],
            )
        finally:
            for f in (out, err):
                if f:
                    f.close()
        exit_codes[i] = rc
        if rc != 0:
            failure.set()

    try:
        for i, slot in enumerate(slots):
            t = threading.Thread(target=_run, args=(i, slot), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    finally:
        server.stop()
    bad = [rc for rc in exit_codes if rc]
    return bad[0] if bad else 0
