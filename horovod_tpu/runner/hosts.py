"""Host string / hostfile parsing and rank allocation.

Reference: host parsing in ``run/run.py:679-694`` (``-H host1:4,host2:4``
and ``--hostfile``) and the slot allocation that computes
rank/local_rank/cross_rank per process (``run/gloo_run.py:53-111``
``_allocate``).  On TPU one process drives all of a host's chips, so a
"slot" is a host process, not a chip.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class HostSpec:
    hostname: str
    slots: int  # chips on this host


@dataclass(frozen=True)
class SlotInfo:
    """Env contract for one launched process (the reference exports
    HOROVOD_RANK / SIZE / LOCAL_RANK / LOCAL_SIZE / CROSS_RANK / CROSS_SIZE
    per slot, gloo_run.py:262-288)."""

    hostname: str
    rank: int          # process rank (== cross rank here)
    size: int          # number of processes
    local_size: int    # chips driven by this process
    world_chips: int   # total chips

    def to_env(self) -> dict:
        return {
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_NUM_PROC": str(self.size),
            "HOROVOD_LOCAL_RANK": "0",
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.rank),
            "HOROVOD_CROSS_SIZE": str(self.size),
            "HOROVOD_WORLD_CHIPS": str(self.world_chips),
        }


def parse_hosts(hosts: Optional[str] = None, hostfile: Optional[str] = None) -> List[HostSpec]:
    """``-H h1:4,h2:4`` or a hostfile with ``hostname slots=N`` lines."""
    specs: List[HostSpec] = []
    if hosts and hostfile:
        raise ValueError("specify either hosts or hostfile, not both")
    if hostfile:
        with open(hostfile) as f:
            for line in f:
                line = line.split("#")[0].strip()
                if not line:
                    continue
                parts = line.split()
                name = parts[0]
                slots = 1
                for p in parts[1:]:
                    if p.startswith("slots="):
                        slots = int(p[len("slots="):])
                specs.append(HostSpec(name, slots))
        return specs
    if not hosts:
        return [HostSpec("localhost", 0)]  # 0 = use all local chips
    for item in hosts.split(","):
        item = item.strip()
        if not item:
            continue
        if ":" in item:
            name, slots = item.rsplit(":", 1)
            specs.append(HostSpec(name, int(slots)))
        else:
            specs.append(HostSpec(item, 1))
    return specs


class Blacklist:
    """Failed-host blacklist with an expiring cooldown (Horovod Elastic's
    ``HostManager`` blacklist semantics): a host that killed a rank is
    excluded from re-rendezvous for ``cooldown`` seconds, then allowed
    back (transient failures — a rebooting machine, a flaky NIC — heal;
    a persistently bad host re-blacklists itself on the next failure).
    ``cooldown=None`` blacklists forever."""

    def __init__(self, cooldown: Optional[float] = 600.0,
                 _clock=time.monotonic) -> None:
        self._cooldown = cooldown
        self._clock = _clock
        self._entries: Dict[str, float] = {}  # hostname -> blacklist time
        self._counts: Dict[str, int] = {}

    def add(self, hostname: str) -> None:
        self._entries[hostname] = self._clock()
        self._counts[hostname] = self._counts.get(hostname, 0) + 1

    def __contains__(self, hostname: str) -> bool:
        t = self._entries.get(hostname)
        if t is None:
            return False
        if self._cooldown is not None and self._clock() - t >= self._cooldown:
            del self._entries[hostname]  # cooldown expired: host may retry
            return False
        return True

    def hosts(self) -> List[str]:
        """Currently-blacklisted hostnames (expired entries dropped)."""
        return [h for h in list(self._entries) if h in self]

    def failure_count(self, hostname: str) -> int:
        return self._counts.get(hostname, 0)

    def filter(self, specs: List[HostSpec]) -> List[HostSpec]:
        return [s for s in specs if s.hostname not in self]


def allocate(specs: List[HostSpec]) -> List[SlotInfo]:
    """One process per host; ranks in host order (gloo_run _allocate)."""
    size = len(specs)
    world = sum(s.slots for s in specs)
    return [
        SlotInfo(
            hostname=s.hostname,
            rank=i,
            size=size,
            local_size=s.slots,
            world_chips=world,
        )
        for i, s in enumerate(specs)
    ]
