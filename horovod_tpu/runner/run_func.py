"""Programmatic in-process launch: ``run(fn, ...)`` executes ``fn`` on
every rank of a freshly launched job and returns each rank's result.

Reference: ``horovod.run.run()`` (``run/run.py:870-956``) and its
run-func plumbing (``run/run_task.py`` / ``run/task_fn.py``): the
launcher cloudpickles ``fn``, every rank fetches + executes it, and
results come back through the KV store.  Here the pickle and results
travel through a shared scratch directory (single host or shared fs) —
the transport the reference's KV server provided — while rank/rendezvous
env wiring reuses the standard launcher.
"""

from __future__ import annotations

import os
import pickle
import socket
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from horovod_tpu.runner import launch
from horovod_tpu.runner.hosts import HostSpec


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict] = None,
    *,
    num_proc: int = 2,
    hosts: Optional[List[HostSpec]] = None,
    env: Optional[Dict[str, str]] = None,
    use_jax_platform: str = "cpu",
    output_dir: Optional[str] = None,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` ranks; returns the list
    of per-rank return values (reference ``horovod.run.run`` contract).

    Each rank gets the full ``HOROVOD_*`` env from the launcher and is
    expected to call ``horovod_tpu.init()`` itself (typically via the
    frontend it uses) — exactly like a script started by ``horovodrun``.
    """
    if hosts is not None:
        total_slots = sum(h.slots for h in hosts)
        if total_slots != num_proc:
            raise ValueError(
                f"hosts provide {total_slots} slots but num_proc="
                f"{num_proc}; they must match")
    scratch = tempfile.mkdtemp(prefix="hvd_runfunc_")
    payload = os.path.join(scratch, "fn.pkl")
    # Pickle caller-module functions BY VALUE: the module that defines fn
    # (a script, a test file) is usually not importable inside a freshly
    # launched rank.  Package code (horovod_tpu.*) stays by-reference.
    registered = []

    def _collect(obj, depth=0):
        if depth > 4:
            return
        if isinstance(obj, dict):
            for v in obj.values():
                _collect(v, depth + 1)
            return
        if isinstance(obj, (list, tuple, set)):
            for v in obj:
                _collect(v, depth + 1)
            return
        mod_name = getattr(obj, "__module__", None)
        if (callable(obj) and mod_name and mod_name != "__main__"
                and not mod_name.startswith(("horovod_tpu", "builtins",
                                             "numpy", "torch", "jax",
                                             "optax"))):
            mod = sys.modules.get(mod_name)
            if mod is not None and mod not in registered:
                cloudpickle.register_pickle_by_value(mod)
                registered.append(mod)

    _collect((fn, args, kwargs or {}))
    try:
        with open(payload, "wb") as f:
            cloudpickle.dump((fn, args, kwargs or {}), f)
    finally:
        for mod in registered:
            cloudpickle.unregister_pickle_by_value(mod)

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    run_env = {
        "PATH": os.environ.get("PATH", ""),
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "PALLAS_AXON_POOL_IPS": os.environ.get("PALLAS_AXON_POOL_IPS", ""),
        "HOROVOD_NUM_PROC": str(num_proc),
        "HOROVOD_JAX_PORT": str(_free_port()),
        "HOROVOD_NATIVE_PORT": str(_free_port()),
        "HVD_RUN_FUNC_PAYLOAD": payload,
        "HVD_RUN_FUNC_SCRATCH": scratch,
        "HVD_RUN_FUNC_PLATFORM": use_jax_platform,
    }
    run_env.update(env or {})

    try:
        rc = launch.launch_job(
            [sys.executable, "-m", "horovod_tpu.runner.run_task"],
            hosts or [HostSpec("localhost", 1)] * num_proc,
            env=run_env,
            output_filename=output_dir,
        )
        results: List[Any] = []
        errors: List[str] = []
        for r in range(num_proc):
            path = os.path.join(scratch, f"result.{r}.pkl")
            if not os.path.exists(path):
                errors.append(f"rank {r}: no result written (crashed?)")
                continue
            with open(path, "rb") as f:
                kind, value = pickle.load(f)
            if kind == "error":
                errors.append(f"rank {r} raised:\n{value}")
            else:
                results.append(value)
        if rc != 0 or errors:
            raise RuntimeError(
                "run(fn) failed"
                + (f" (exit code {rc})" if rc else "")
                + (f"; per-rank logs in {output_dir}" if output_dir else "")
                + ("\n" + "\n".join(errors) if errors else ""))
        return results
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)
