"""HTTP KV rendezvous server + client.

Reference: ``run/http/http_server.py:33-222`` (``KVStoreHandler`` GET/PUT,
``RendezvousServer``, scope finalization via DELETE) and the client side
``gloo/http_store.cc`` / ``run/http/http_client.py``.

Role on TPU: the launcher starts this server; worker processes use it to
exchange the coordinator address, publish per-host results, and as the
KV behind run-function mode.  (The JAX distributed runtime does collective
bootstrap; this store is the transport-agnostic side channel the reference
kept for the same purpose.)
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    def _key(self) -> Tuple[str, str]:
        parts = self.path.strip("/").split("/", 1)
        scope = parts[0] if parts else ""
        key = parts[1] if len(parts) > 1 else ""
        return scope, key

    def do_PUT(self):
        scope, key = self._key()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        # Enforce write auth at the server (reference RendezvousHandler):
        # with a job secret configured, unsigned or mis-signed PUTs are
        # rejected here, so a stray writer can neither inject state nor
        # crash readers with garbage.
        if self.server._secret_key:
            from horovod_tpu.runner import secret

            try:
                secret.verify(value, self.server._secret_key)
            except ValueError:
                self.send_response(403)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
        with self.server._lock:
            self.server._store.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        scope, key = self._key()
        if key == "":
            # Scope listing (GET /scope/): JSON array of keys.  Lets the
            # elastic driver scan per-rank heartbeat keys without knowing
            # the live rank set in advance.
            import json

            with self.server._lock:
                keys = sorted(self.server._store.get(scope, {}))
            body = json.dumps(keys).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.server._lock:
            value = self.server._store.get(scope, {}).get(key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):  # scope finalization (RendezvousHandler:105)
        scope, _ = self._key()
        if self.server._secret_key:
            from horovod_tpu.runner import secret

            length = int(self.headers.get("Content-Length", 0))
            token = self.rfile.read(length)
            try:
                secret.verify(token, self.server._secret_key)
            except ValueError:
                self.send_response(403)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
        with self.server._lock:
            self.server._store.pop(scope, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    """Threaded HTTP KV store (``KVStoreServer`` / ``RendezvousServer``).

    With ``secret_key`` set (or ``HOROVOD_SECRET_KEY`` in the
    environment), writes must carry a valid HMAC."""

    def __init__(self, port: int = 0,
                 secret_key: Optional[bytes] = None) -> None:
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self._httpd._store: Dict[str, Dict[str, bytes]] = {}
        self._httpd._lock = threading.Lock()
        if secret_key is None:
            from horovod_tpu.runner import secret

            secret_key = secret.get_key()
        self._httpd._secret_key = secret_key
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    # ---- in-process access (supervisor side) ----------------------------
    # The ElasticDriver owns this server, so it reads/writes the store
    # directly instead of looping through HTTP.  Values written by
    # clients are stored signed; these helpers sign/verify symmetrically.

    def put(self, scope: str, key: str, value: bytes) -> None:
        from horovod_tpu.runner import secret

        signed = secret.sign(value, self._httpd._secret_key)
        with self._httpd._lock:
            self._httpd._store.setdefault(scope, {})[key] = signed

    def get(self, scope: str, key: str) -> Optional[bytes]:
        from horovod_tpu.runner import secret

        with self._httpd._lock:
            value = self._httpd._store.get(scope, {}).get(key)
        if value is None:
            return None
        try:
            return secret.verify(value, self._httpd._secret_key)
        except ValueError:
            return None

    def keys(self, scope: str) -> list:
        with self._httpd._lock:
            return sorted(self._httpd._store.get(scope, {}))

    def clear_scope(self, scope: str) -> None:
        """Drop a scope's keys (epoch turnover: stale NIC-discovery or
        run-function results from a dead world must not leak into the
        next rendezvous)."""
        with self._httpd._lock:
            self._httpd._store.pop(scope, None)


class KVClient:
    """Blocking KV client (``run/http/http_client.py`` equivalents)."""

    def __init__(self, addr: str, port: int, timeout: float = 30.0) -> None:
        self._base = f"http://{addr}:{port}"
        self._timeout = timeout

    def put(self, scope: str, key: str, value: bytes) -> None:
        # Per-job HMAC signing when HOROVOD_SECRET_KEY is set (reference
        # secret.py/codec.py: signed control-plane payloads).
        from horovod_tpu.runner import secret

        req = urlrequest.Request(
            f"{self._base}/{scope}/{key}", data=secret.sign(value),
            method="PUT"
        )
        urlrequest.urlopen(req, timeout=self._timeout).read()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        from horovod_tpu.runner import secret

        try:
            payload = urlrequest.urlopen(
                f"{self._base}/{scope}/{key}", timeout=self._timeout
            ).read()
            return secret.verify(payload)
        except urlerror.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def wait(self, scope: str, key: str, timeout: float = 60.0) -> bytes:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(scope, key)
            if v is not None:
                return v
            time.sleep(0.1)
        raise TimeoutError(f"rendezvous key {scope}/{key} not published")

    def keys(self, scope: str) -> list:
        """List a scope's keys (GET /scope/)."""
        import json

        payload = urlrequest.urlopen(
            f"{self._base}/{scope}/", timeout=self._timeout
        ).read()
        return json.loads(payload)

    def delete_scope(self, scope: str) -> None:
        from horovod_tpu.runner import secret

        req = urlrequest.Request(
            f"{self._base}/{scope}/", data=secret.sign(b"delete"),
            method="DELETE"
        )
        urlrequest.urlopen(req, timeout=self._timeout).read()
