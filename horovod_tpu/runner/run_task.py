"""Per-rank entry for run-func mode (reference: ``run/run_task.py`` —
fetch the pickled fn, execute, post the result)."""

import os
import pickle
import sys
import traceback


def main() -> int:
    platform = os.environ.get("HVD_RUN_FUNC_PLATFORM", "cpu")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    import cloudpickle

    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    scratch = os.environ["HVD_RUN_FUNC_SCRATCH"]
    with open(os.environ["HVD_RUN_FUNC_PAYLOAD"], "rb") as f:
        fn, args, kwargs = cloudpickle.load(f)

    out = os.path.join(scratch, f"result.{rank}.pkl")
    try:
        value = fn(*args, **kwargs)
        payload = ("ok", value)
        code = 0
    except Exception:
        payload = ("error", traceback.format_exc())
        code = 1
    with open(out + ".tmp", "wb") as f:
        pickle.dump(payload, f)
    os.replace(out + ".tmp", out)
    return code


if __name__ == "__main__":
    sys.exit(main())
