"""HMAC signing for launcher control-plane payloads.

Reference: ``run/common/util/secret.py`` + ``codec.py`` — every
driver/task RPC and KV payload is HMAC-signed with a per-job secret so a
stray process on the network can't inject rendezvous state.  Same scheme:
a random per-job key exported as ``HOROVOD_SECRET_KEY``, payloads carried
as ``hmac_digest || body``.
"""

from __future__ import annotations

import hmac
import hashlib
import os
import secrets as _secrets

ENV_KEY = "HOROVOD_SECRET_KEY"
DIGEST_BYTES = 32  # sha256
# Signed payloads are self-describing so a keyless reader can tell a
# signed blob from raw bytes (and fail loudly instead of handing back
# digest||body garbage).
MAGIC = b"HVDSIG1\x00"


def make_secret_key() -> str:
    return _secrets.token_hex(16)


def get_key() -> bytes | None:
    v = os.environ.get(ENV_KEY)
    return v.encode() if v else None


def sign(body: bytes, key: bytes | None = None) -> bytes:
    key = key if key is not None else get_key()
    if not key:
        return body  # signing disabled (no per-job secret exported)
    digest = hmac.new(key, body, hashlib.sha256).digest()
    return MAGIC + digest + body


def verify(payload: bytes, key: bytes | None = None) -> bytes:
    """Return the body; raises ValueError on a bad or missing signature."""
    key = key if key is not None else get_key()
    is_signed = payload.startswith(MAGIC)
    if not key:
        if is_signed:
            raise ValueError(
                "payload is HMAC-signed but this process has no "
                f"{ENV_KEY}; export the job's secret to read it")
        return payload
    if not is_signed:
        raise ValueError(
            "HMAC verification failed: payload is unsigned but this job "
            "requires signed control-plane messages")
    rest = payload[len(MAGIC):]
    if len(rest) < DIGEST_BYTES:
        raise ValueError("payload shorter than HMAC digest")
    digest, body = rest[:DIGEST_BYTES], rest[DIGEST_BYTES:]
    expect = hmac.new(key, body, hashlib.sha256).digest()
    if not hmac.compare_digest(digest, expect):
        raise ValueError("HMAC verification failed: payload rejected")
    return body
