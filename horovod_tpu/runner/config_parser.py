"""CLI flags ⇄ YAML config file ⇄ ``HOROVOD_*`` env vars.

Reference: ``run/common/util/config_parser.py`` (names kept), the flag
groups of ``run/run.py:451-617``, and the override-precedence rule
(CLI beats config file, ``run/run.py:337-393``; tested by
``test_run.py:176-233``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# env var names (reference config_parser.py constants)
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"


def _set(env: Dict[str, str], name: str, value: Any) -> None:
    if value is not None:
        env[name] = str(int(value) if isinstance(value, bool) else value)


def set_env_from_args(env: Dict[str, str], args) -> Dict[str, str]:
    """Translate parsed args into HOROVOD_* env (reference
    ``config_parser.set_env_from_args``, run/common/util/config_parser.py:
    140-180)."""
    _set(env, HOROVOD_FUSION_THRESHOLD, getattr(args, "fusion_threshold_mb", None) and int(args.fusion_threshold_mb * 1024 * 1024))
    _set(env, HOROVOD_CYCLE_TIME, getattr(args, "cycle_time_ms", None))
    _set(env, HOROVOD_CACHE_CAPACITY, getattr(args, "cache_capacity", None))
    _set(env, HOROVOD_HIERARCHICAL_ALLREDUCE, getattr(args, "hierarchical_allreduce", None))
    _set(env, HOROVOD_HIERARCHICAL_ALLGATHER, getattr(args, "hierarchical_allgather", None))
    if getattr(args, "autotune", False):
        _set(env, HOROVOD_AUTOTUNE, 1)
        _set(env, HOROVOD_AUTOTUNE_LOG, getattr(args, "autotune_log_file", None))
        _set(env, HOROVOD_AUTOTUNE_WARMUP_SAMPLES, getattr(args, "autotune_warmup_samples", None))
        _set(env, HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, getattr(args, "autotune_steps_per_sample", None))
    _set(env, HOROVOD_TIMELINE, getattr(args, "timeline_filename", None))
    if getattr(args, "timeline_mark_cycles", False):
        _set(env, HOROVOD_TIMELINE_MARK_CYCLES, 1)
    if getattr(args, "no_stall_check", False):
        _set(env, HOROVOD_STALL_CHECK_DISABLE, 1)
    else:
        _set(env, HOROVOD_STALL_CHECK_TIME_SECONDS, getattr(args, "stall_check_warning_time_seconds", None))
        _set(env, HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, getattr(args, "stall_check_shutdown_time_seconds", None))
    _set(env, HOROVOD_LOG_LEVEL, getattr(args, "log_level", None))
    return env


# config-file key → argparse dest (reference config.test.yaml layout)
_CONFIG_MAP = {
    ("params", "fusion-threshold-mb"): "fusion_threshold_mb",
    ("params", "cycle-time-ms"): "cycle_time_ms",
    ("params", "cache-capacity"): "cache_capacity",
    ("params", "hierarchical-allreduce"): "hierarchical_allreduce",
    ("params", "hierarchical-allgather"): "hierarchical_allgather",
    ("autotune", "enabled"): "autotune",
    ("autotune", "log-file"): "autotune_log_file",
    ("autotune", "warmup-samples"): "autotune_warmup_samples",
    ("autotune", "steps-per-sample"): "autotune_steps_per_sample",
    ("timeline", "filename"): "timeline_filename",
    ("timeline", "mark-cycles"): "timeline_mark_cycles",
    ("stall-check", "disable"): "no_stall_check",
    ("stall-check", "warning-time-seconds"): "stall_check_warning_time_seconds",
    ("stall-check", "shutdown-time-seconds"): "stall_check_shutdown_time_seconds",
}


def read_config_file(path: str) -> Dict[str, Any]:
    """Parse the YAML config file into {argparse_dest: value}.  Uses a
    minimal hand parser (two-level maps of scalars) so the launcher has no
    YAML dependency — the reference's config surface is exactly this shape
    (``test/data/config.test.yaml``)."""
    values: Dict[str, Any] = {}
    section = None
    with open(path) as f:
        for raw in f:
            line = raw.split("#")[0].rstrip()
            if not line.strip():
                continue
            indent = len(line) - len(line.lstrip())
            key, _, val = line.strip().partition(":")
            key = key.strip()
            val = val.strip()
            if indent == 0:
                section = key
                continue
            dest = _CONFIG_MAP.get((section, key))
            if dest is None:
                continue
            values[dest] = _parse_scalar(val)
    return values


def _parse_scalar(val: str) -> Any:
    low = val.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    try:
        return int(val)
    except ValueError:
        pass
    try:
        return float(val)
    except ValueError:
        pass
    return val


def apply_config_file(args, path: Optional[str]) -> None:
    """Apply config-file values for args the CLI did not override
    (CLI > config file > defaults; reference override-actions
    run/run.py:337-393)."""
    if not path:
        return
    overridden = getattr(args, "_explicit_args", set())
    for dest, val in read_config_file(path).items():
        if dest not in overridden:
            setattr(args, dest, val)
