"""Host and NIC discovery.

Two subsystems share this module:

* **Host discovery** (elastic membership): where does the job's host set
  come from?  :class:`FixedHostDiscovery` wraps a static ``-H``/hostfile
  list; :class:`ScriptHostDiscovery` re-runs a user script each poll
  (Horovod Elastic's ``--host-discovery-script`` contract: one
  ``hostname:slots`` line per available host) so the ElasticDriver can
  admit replacement hosts between restarts.

* **NIC / route discovery**: find addresses every host can actually reach.

Reference: the driver/task service handshake (``run/run.py:118-270``,
``run/driver/driver_service.py``, ``run/task/task_service.py``): each task
starts a server, registers its candidate addresses with the driver, then
probes the *next* task's candidates in a ring; the driver intersects the
working interfaces so ``mpirun``/gloo bind the right NICs.

TPU re-design over the rendezvous KV instead of pickled-RPC services:

1. every rank binds a throwaway TCP listener and publishes its candidate
   ``(address, port)`` list under ``discovery/addrs.<rank>``;
2. each rank dials rank ``(r+1) % n``'s candidates and publishes which
   succeeded under ``discovery/reach.<rank>``;
3. :func:`discover` intersects the reachable-address reports into one
   routable address per rank (the launcher can pass rank 0's to
   ``HOROVOD_COORDINATOR_ADDR``).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, List, Optional, Tuple

from horovod_tpu.runner.hosts import HostSpec
from horovod_tpu.runner.rendezvous import KVClient

SCOPE = "discovery"


# ---- host discovery (elastic membership) ------------------------------------


class HostDiscovery:
    """Source of the currently-available host set (Horovod Elastic's
    ``HostDiscovery`` interface)."""

    def find_available_hosts(self) -> List[HostSpec]:
        raise NotImplementedError


class FixedHostDiscovery(HostDiscovery):
    """A static host list (``-H``/``--hostfile``): membership can only
    shrink (by blacklisting) and recover (by cooldown expiry)."""

    def __init__(self, specs: List[HostSpec]) -> None:
        self._specs = list(specs)

    def find_available_hosts(self) -> List[HostSpec]:
        return list(self._specs)


class ScriptHostDiscovery(HostDiscovery):
    """Polls a user script for the live host set (Horovod Elastic's
    ``--host-discovery-script``).  The script prints one host per line as
    ``hostname`` or ``hostname:slots``; exit code 0 with no output means
    "no hosts currently available".  A failing or hanging script yields
    the empty set (the driver treats that as below ``min_np`` and keeps
    polling until its discovery timeout)."""

    def __init__(self, script: str, timeout: float = 10.0) -> None:
        self._script = script
        self._timeout = timeout

    def find_available_hosts(self) -> List[HostSpec]:
        import subprocess

        try:
            out = subprocess.run(
                self._script, shell=True, capture_output=True,
                timeout=self._timeout)
        except subprocess.TimeoutExpired:
            return []
        if out.returncode != 0:
            return []
        import logging

        specs: List[HostSpec] = []
        for line in out.stdout.decode(errors="replace").splitlines():
            line = line.split("#")[0].strip()
            if not line:
                continue
            name, slots = line, 1
            if ":" in line:
                head, tail = line.rsplit(":", 1)
                head = head.strip()
                # Only a digit tail after a non-":"-terminated head is a
                # slot count; anything else ("::1", "fe80::1", malformed
                # text) is a whole hostname — a bad line must not crash
                # the supervising driver.
                if head and not head.endswith(":") and tail.isdigit():
                    name, slots = head, int(tail)
                elif not tail.isdigit():
                    logging.getLogger("horovod_tpu").warning(
                        "host discovery: no slot count in line %r; "
                        "assuming 1 slot", line)
            specs.append(HostSpec(name, slots))
        return specs


def local_addresses() -> List[str]:
    """Candidate non-loopback IPv4 addresses of this host (reference:
    get_local_host_addresses / psutil net_if_addrs, without psutil)."""
    addrs = set()
    try:
        hostname = socket.gethostname()
        for info in socket.getaddrinfo(hostname, None, socket.AF_INET):
            addrs.add(info[4][0])
    except socket.gaierror:
        pass
    # The UDP-connect trick finds the default-route interface address
    # without sending a packet.
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            addrs.add(s.getsockname()[0])
    except OSError:
        pass
    addrs.discard("127.0.0.1")
    return sorted(addrs) or ["127.0.0.1"]


class _ProbeListener:
    """Accept-and-close TCP listener used as the probe target."""

    def __init__(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
                conn.close()
            except (socket.timeout, OSError):
                continue

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._sock.close()


def _probe(addr: str, port: int, timeout: float = 2.0) -> bool:
    try:
        with socket.create_connection((addr, port), timeout=timeout):
            return True
    except OSError:
        return False


def run_task_discovery(kv: KVClient, rank: int, size: int,
                       timeout: float = 60.0) -> None:
    """Per-rank side of the handshake (reference task_service role)."""
    listener = _ProbeListener()
    try:
        kv.put(SCOPE, f"addrs.{rank}", json.dumps(
            {"addrs": local_addresses(), "port": listener.port}).encode())
        nxt = (rank + 1) % size
        peer = json.loads(kv.wait(SCOPE, f"addrs.{nxt}", timeout=timeout))
        reachable = [a for a in peer["addrs"] if _probe(a, peer["port"])]
        kv.put(SCOPE, f"reach.{rank}", json.dumps(
            {"peer": nxt, "reachable": reachable}).encode())
        # hold the listener until every rank has reported, so probes from
        # our predecessor don't race our teardown
        for r in range(size):
            kv.wait(SCOPE, f"reach.{r}", timeout=timeout)
    finally:
        listener.close()


def discover(kv: KVClient, size: int, timeout: float = 60.0
             ) -> Dict[int, str]:
    """Driver side: one verified-routable address per rank (reference
    driver_service intersection of common interfaces)."""
    routable: Dict[int, str] = {}
    for r in range(size):
        report = json.loads(kv.wait(SCOPE, f"reach.{r}", timeout=timeout))
        peer = report["peer"]
        if report["reachable"]:
            routable[peer] = report["reachable"][0]
    missing = [r for r in range(size) if r not in routable]
    if missing:
        raise RuntimeError(
            f"NIC discovery: no routable address found for ranks {missing} "
            "(ring probes all failed — check firewalls/interfaces)")
    return routable
