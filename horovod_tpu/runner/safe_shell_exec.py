"""Process-group spawn with guaranteed cleanup.

Reference: ``run/common/util/safe_shell_exec.py`` — spawn in a fresh
process group, forward signals, kill the whole group on termination so no
orphan ranks survive a failed launch (``gloo_run.py:201`` SIGTERM path).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
from typing import Dict, IO, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5


def _tee(stream: IO[bytes], sinks: List[IO], prefix: bytes) -> None:
    for line in iter(stream.readline, b""):
        for sink in sinks:
            try:
                buf = getattr(sink, "buffer", sink)
                buf.write(prefix + line)
                sink.flush()
            except Exception:
                pass
    stream.close()


def execute(
    command,
    env: Optional[Dict[str, str]] = None,
    stdout: Optional[IO] = None,
    stderr: Optional[IO] = None,
    prefix: Optional[str] = None,
    events: Optional[List[threading.Event]] = None,
    stdin_data: Optional[bytes] = None,
) -> int:
    """Run command in its own process group; tee output with an optional
    rank prefix (the reference's ``--tag-output`` behavior); kill the group
    if any event in ``events`` fires."""
    # Keep CPython on the posix_spawn fast path (forking a JAX-laden,
    # heavily threaded parent via fork_exec can deadlock on snapshotted
    # locks).  posix_spawn requires: no preexec_fn, no start_new_session,
    # close_fds=False, and an absolute executable path — so the new session
    # comes from a setsid(1) wrapper and the executable is resolved here.
    import shutil

    use_shell = isinstance(command, str)
    setsid = shutil.which("setsid")
    if not use_shell and setsid:
        argv = list(command)
        resolved = shutil.which(argv[0])
        if resolved:
            argv[0] = resolved
        cmd = [setsid] + argv
        popen_kw = dict(close_fds=False)
    else:  # fallback: fork path with its own session
        cmd = command
        popen_kw = dict(start_new_session=True)
    proc = subprocess.Popen(
        cmd,
        env=env,
        shell=use_shell,
        stdin=subprocess.PIPE if stdin_data is not None else None,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        **popen_kw,
    )
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass
        finally:
            proc.stdin.close()

    p = (prefix.encode() if prefix else b"")
    threads = [
        threading.Thread(
            target=_tee, args=(proc.stdout, [stdout or sys.stdout], p), daemon=True
        ),
        threading.Thread(
            target=_tee, args=(proc.stderr, [stderr or sys.stderr], p), daemon=True
        ),
    ]
    for t in threads:
        t.start()

    stop = threading.Event()

    def _watch():
        while not stop.wait(0.1):
            if any(e.is_set() for e in (events or [])):
                terminate_process_group(proc)
                return

    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()
    try:
        ret = proc.wait()
    finally:
        stop.set()
        watcher.join(timeout=1)
        for t in threads:
            t.join(timeout=1)
        if proc.poll() is None:
            terminate_process_group(proc)
    return ret


def terminate_process_group(proc: subprocess.Popen) -> None:
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
        try:
            proc.wait(timeout=GRACEFUL_TERMINATION_TIME_S)
            return
        except subprocess.TimeoutExpired:
            pass
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
