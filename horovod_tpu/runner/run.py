"""``horovodrun`` CLI for TPU jobs.

Reference: ``run/run.py:395-960`` — same flag groups (job size/hosts,
tuneable params, autotune, timeline, stall check, logging, config file with
CLI-override precedence), translated to the TPU launch model: one process
per host, JAX coordination service instead of mpirun/ssh-orted, chips
discovered from the TPU runtime.

Usage:
    horovodrun -np 2 -H host1:4,host2:4 python train.py
    horovodrun --config-file cfg.yaml python train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from horovod_tpu.runner import config_parser
from horovod_tpu.runner.hosts import parse_hosts
from horovod_tpu.runner.launch import launch_job


class _RecordAction(argparse.Action):
    """Track explicitly-passed flags so config-file values don't override
    them (reference override-actions, ``run/run.py:337-393``)."""

    def __call__(self, parser, namespace, values, option_string=None):
        if not hasattr(namespace, "_explicit_args"):
            namespace._explicit_args = set()
        namespace._explicit_args.add(self.dest)
        setattr(
            namespace,
            self.dest,
            True if self.nargs == 0 and values in (None, []) else values,
        )


class _RecordStore(_RecordAction):
    pass


class _RecordTrue(_RecordAction):
    def __init__(self, *a, **kw):
        kw["nargs"] = 0
        super().__init__(*a, **kw)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="horovodrun", description="Launch a horovod_tpu training job."
    )
    p.add_argument("-v", "--version", action="store_true", dest="version")
    p.add_argument("-cb", "--check-build", action="store_true",
                   dest="check_build",
                   help="show available frontends / control plane / data "
                        "plane and exit (reference --check-build)")
    p.add_argument("-np", "--num-proc", type=int, dest="np", default=None,
                   help="number of host processes (defaults to number of -H hosts)")
    group_hosts = p.add_mutually_exclusive_group()
    group_hosts.add_argument("-H", "--hosts", dest="hosts", default=None,
                             help="host1:chips,host2:chips")
    group_hosts.add_argument("--hostfile", dest="hostfile", default=None)
    p.add_argument("--output-filename", dest="output_filename", default=None,
                   help="per-rank stdout/stderr capture directory")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file", dest="config_file", default=None)
    p.add_argument("--start-port", type=int, dest="start_port", default=0,
                   help="rendezvous port (0 = ephemeral)")
    p.add_argument("--disable-cache", action="store_true",
                   dest="disable_cache",
                   help="re-run pre-flight checks (ssh reachability) "
                        "instead of using cached results")

    tune = p.add_argument_group("tuneable parameter arguments")
    tune.add_argument("--fusion-threshold-mb", type=float, action=_RecordStore,
                      dest="fusion_threshold_mb", default=None)
    tune.add_argument("--cycle-time-ms", type=float, action=_RecordStore,
                      dest="cycle_time_ms", default=None)
    tune.add_argument("--cache-capacity", type=int, action=_RecordStore,
                      dest="cache_capacity", default=None)
    tune.add_argument("--hierarchical-allreduce", action=_RecordTrue,
                      dest="hierarchical_allreduce", default=None)
    tune.add_argument("--hierarchical-allgather", action=_RecordTrue,
                      dest="hierarchical_allgather", default=None)

    at = p.add_argument_group("autotune arguments")
    at.add_argument("--autotune", action=_RecordTrue, dest="autotune", default=False)
    at.add_argument("--autotune-log-file", action=_RecordStore,
                    dest="autotune_log_file", default=None)
    at.add_argument("--autotune-warmup-samples", type=int, action=_RecordStore,
                    dest="autotune_warmup_samples", default=None)
    at.add_argument("--autotune-steps-per-sample", type=int, action=_RecordStore,
                    dest="autotune_steps_per_sample", default=None)

    tl = p.add_argument_group("timeline arguments")
    tl.add_argument("--timeline-filename", action=_RecordStore,
                    dest="timeline_filename", default=None)
    tl.add_argument("--timeline-mark-cycles", action=_RecordTrue,
                    dest="timeline_mark_cycles", default=False)

    st = p.add_argument_group("stall check arguments")
    st.add_argument("--no-stall-check", action=_RecordTrue,
                    dest="no_stall_check", default=False)
    st.add_argument("--stall-check-warning-time-seconds", type=int,
                    action=_RecordStore,
                    dest="stall_check_warning_time_seconds", default=None)
    st.add_argument("--stall-check-shutdown-time-seconds", type=int,
                    action=_RecordStore,
                    dest="stall_check_shutdown_time_seconds", default=None)

    lg = p.add_argument_group("logging arguments")
    lg.add_argument("--log-level", action=_RecordStore, dest="log_level",
                    default=None,
                    choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR", "FATAL"])

    el = p.add_argument_group(
        "elastic arguments",
        "supervised restart instead of kill-all: survive rank failure by "
        "re-rendezvousing over the remaining (non-blacklisted) hosts and "
        "resuming from the last committed elastic.State")
    el.add_argument("--min-np", type=int, dest="min_np", default=None,
                    help="minimum hosts to keep the job alive; enables "
                         "elastic mode")
    el.add_argument("--max-np", type=int, dest="max_np", default=None,
                    help="maximum hosts to use per rendezvous epoch")
    el.add_argument("--reset-limit", type=int, dest="reset_limit",
                    default=None,
                    help="abort after this many supervised restarts")
    el.add_argument("--blacklist-cooldown", type=float,
                    dest="blacklist_cooldown", default=600.0,
                    help="seconds a failed host stays blacklisted "
                         "(0 = forever)")
    el.add_argument("--host-discovery-script", dest="host_discovery_script",
                    default=None,
                    help="script printing one available host per line as "
                         "hostname[:slots]; polled before each epoch; "
                         "enables elastic mode")
    el.add_argument("--discovery-timeout", type=float,
                    dest="discovery_timeout", default=None,
                    help="seconds to keep polling discovery for min-np "
                         "hosts before aborting (default: 60 with a "
                         "discovery script — one transient script failure "
                         "must not kill the job — else 0)")
    el.add_argument("--metrics-port", type=int, dest="metrics_port",
                    default=None,
                    help="serve the fleet observability endpoints "
                         "(GET /metrics Prometheus + GET /fleet JSON, "
                         "aggregated across ranks) on this port "
                         "(0 = ephemeral; docs/observability.md)")
    el.add_argument("--straggler-threshold", type=float,
                    dest="straggler_threshold", default=2.0,
                    help="flag a rank as a straggler when its step time "
                         "exceeds this multiple of the fleet median "
                         "(report-only)")
    el.add_argument("--straggler-patience", type=int,
                    dest="straggler_patience", default=3,
                    help="consecutive slow step reports before flagging")

    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command to launch")
    args = p.parse_args(argv)
    if not hasattr(args, "_explicit_args"):
        args._explicit_args = set()
    return args


def check_hosts_ssh(hostnames, timeout: float = 15.0,
                    use_cache: bool = True) -> None:
    """Pre-flight: every remote host must accept a non-interactive ssh
    (reference ``_check_all_hosts_ssh_successful``, ``run/run.py:63-116``
    — run in parallel, fail fast naming the unreachable hosts).
    Successes are remembered in the launcher cache
    (:mod:`horovod_tpu.runner.cache`) so repeated launches skip the
    round-trips, like the reference's ``~/.horovod`` cache."""
    import concurrent.futures
    import shlex
    import subprocess

    from horovod_tpu.runner import cache as cache_mod
    from horovod_tpu.runner.launch import SSH_COMMAND_PREFIX, _is_local

    remote = sorted({h for h in hostnames if not _is_local(h)})
    c = cache_mod.Cache()
    if use_cache:
        remote = [h for h in remote if c.get(f"ssh.{h}") != "ok"]
    if not remote:
        return

    def probe(host):
        try:
            r = subprocess.run(
                shlex.split(SSH_COMMAND_PREFIX) + [host, "true"],
                capture_output=True, timeout=timeout)
            return host, r.returncode == 0
        except Exception:
            return host, False

    with concurrent.futures.ThreadPoolExecutor(len(remote)) as ex:
        results = list(ex.map(probe, remote))
    failed = [h for h, ok in results if not ok]
    if failed:
        raise SystemExit(
            "horovodrun: non-interactive ssh failed for host(s): "
            + ", ".join(failed)
            + " — ensure passwordless ssh (key-based) works to every host")
    if use_cache:
        for h, ok in results:
            if ok:
                c.put(f"ssh.{h}", "ok")


def check_build() -> int:
    """Print the capability matrix (reference ``horovodrun --check-build``,
    ``run/run.py:289-326`` — frameworks / controllers / tensor ops, with
    [X] marks).  Here the controller is always the native TCP star and
    the data plane is XLA; what varies is which frontends import and
    which XLA backends are visible."""
    import importlib.util

    import horovod_tpu

    def mark(ok):
        return "X" if ok else " "

    def importable(mod):
        try:
            return importlib.util.find_spec(mod) is not None
        except Exception:
            return False

    def xla_backend(name):
        try:
            import jax

            return len(jax.devices(name)) > 0
        except Exception:
            return False

    native_ok = True
    try:
        from horovod_tpu import native  # noqa: F401
    except Exception:
        native_ok = False

    print(f"""\
horovod_tpu v{horovod_tpu.__version__}:

Available Frontends:
    [X] JAX (native)
    [{mark(importable('tensorflow'))}] TensorFlow
    [{mark(importable('torch'))}] PyTorch
    [{mark(importable('tensorflow'))}] Keras
    [{mark(importable('mxnet'))}] MXNet

Available Control Planes:
    [{mark(native_ok)}] native TCP star (eager negotiation/fusion/cache)
    [X] compiled SPMD (no runtime controller needed under jit)

Available Data Planes (XLA backends visible from this process):
    [{mark(xla_backend('tpu'))}] TPU (ICI/DCN collectives)
    [{mark(xla_backend('cpu'))}] CPU

Cluster Integrations:
    [X] horovodrun / run_func launcher
    [{mark(importable('pyspark'))}] Spark""")
    return 0


def _run(args: argparse.Namespace) -> int:
    if args.version:
        import horovod_tpu

        print(horovod_tpu.__version__)
        return 0
    if args.check_build:
        return check_build()
    if not args.command:
        raise SystemExit("horovodrun: no command specified")
    config_parser.apply_config_file(args, args.config_file)
    host_specs = parse_hosts(args.hosts, args.hostfile)
    if args.np is not None:
        if args.hosts is None and args.hostfile is None:
            host_specs = [host_specs[0]] * 0 or [
                type(host_specs[0])("localhost", 0)
            ]
        if len(host_specs) not in (args.np, 1):
            raise SystemExit(
                f"horovodrun: -np {args.np} does not match {len(host_specs)} hosts"
            )
        if len(host_specs) == 1 and args.np > 1:
            host_specs = host_specs * args.np
    env = dict(os.environ)
    config_parser.set_env_from_args(env, args)
    check_hosts_ssh([h.hostname for h in host_specs],
                    use_cache=not args.disable_cache)
    elastic = (args.min_np is not None
               or args.host_discovery_script is not None)
    if elastic:
        from horovod_tpu.runner.discovery import (
            FixedHostDiscovery, ScriptHostDiscovery)
        from horovod_tpu.runner.elastic_driver import (
            ElasticJobError, run_elastic)

        if args.host_discovery_script:
            discovery = ScriptHostDiscovery(args.host_discovery_script)
            discovery_timeout = (args.discovery_timeout
                                 if args.discovery_timeout is not None
                                 else 60.0)
        else:
            discovery = FixedHostDiscovery(host_specs)
            discovery_timeout = args.discovery_timeout or 0.0
        if args.verbose:
            print(f"horovodrun: elastic launch "
                  f"(min_np={args.min_np or 1}, max_np={args.max_np})")
        try:
            return run_elastic(
                args.command,
                discovery=discovery,
                min_np=args.min_np or 1,
                max_np=args.max_np,
                env=env,
                reset_limit=args.reset_limit,
                blacklist_cooldown=args.blacklist_cooldown or None,
                discovery_timeout=discovery_timeout,
                output_filename=args.output_filename,
                coordinator_port=args.start_port,
                metrics_port=args.metrics_port,
                straggler_threshold=args.straggler_threshold,
                straggler_patience=args.straggler_patience,
            )
        except ElasticJobError as e:
            raise SystemExit(f"horovodrun: {e}")
    if args.verbose:
        print(f"horovodrun: launching on {len(host_specs)} host(s)")
    return launch_job(
        args.command,
        host_specs,
        env=env,
        output_filename=args.output_filename,
        coordinator_port=args.start_port,
    )


def run_commandline(argv: Optional[List[str]] = None) -> None:
    sys.exit(_run(parse_args(argv)))


if __name__ == "__main__":
    run_commandline()
