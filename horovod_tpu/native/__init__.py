"""ctypes bindings for the native control-plane runtime (libhvd_native.so).

The native library is the TPU re-design of the reference's C++ core
(``horovod/common/*`` — background thread, controller/negotiation, tensor
queue, response cache, stall inspector, timeline).  It owns *coordination*:
which eager collectives are globally ready, in what order, fused how.  It
never touches tensor bytes — execution of each negotiated (fused) response
is delegated back to Python through :func:`set_executor`, where the
collective runs as an XLA program on the TPU data plane.

Loading mirrors the reference's ctypes extension loading
(``horovod/common/util.py:check_extension``): the shared library is built
from the in-tree sources with ``make`` on first use if missing or stale.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libhvd_native.so")

# --- enums, mirroring src/common.h -------------------------------------------

ALLREDUCE, ALLGATHER, BROADCAST, JOIN, ALLTOALL, BARRIER, REDUCESCATTER = range(7)
RESP_ERROR = 6
# RespType diverges from ReqType past ERROR (common.h): reducescatter
# responses arrive as 7 while requests enqueue as REDUCESCATTER (6).
RESP_REDUCESCATTER = 7

OP_AVERAGE, OP_SUM, OP_ADASUM, OP_MIN, OP_MAX, OP_PRODUCT = range(6)

_DTYPE_NAMES = [
    "uint8", "int8", "uint16", "int16", "int32", "int64",
    "float16", "float32", "float64", "bool", "bfloat16",
]
_DTYPE_TO_ENUM = {n: i for i, n in enumerate(_DTYPE_NAMES)}

STATUS_OK = 0
STATUS_ABORTED = 1
STATUS_INVALID = 2
STATUS_SHUTDOWN = 3
STATUS_DUPLICATE = 4


def dtype_enum(np_dtype) -> int:
    name = str(np_dtype)
    if name not in _DTYPE_TO_ENUM:
        raise TypeError(f"dtype {name!r} is not supported by the native runtime")
    return _DTYPE_TO_ENUM[name]


def dtype_name(enum_val: int) -> str:
    return _DTYPE_NAMES[enum_val]


# --- build + load ------------------------------------------------------------

_load_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    src_dir = os.path.join(_DIR, "src")
    for f in os.listdir(src_dir):
        if os.path.getmtime(os.path.join(src_dir, f)) > lib_mtime:
            return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _load_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        if _needs_build():
            # Serialize across processes: N ranks launched together must not
            # race `make` rewriting the .so while others dlopen it.
            import fcntl

            lock_path = os.path.join(_DIR, ".build.lock")
            try:
                with open(lock_path, "w") as lock_f:
                    fcntl.flock(lock_f, fcntl.LOCK_EX)
                    try:
                        if _needs_build():  # re-check under the lock
                            subprocess.run(
                                ["make", "-s"], cwd=_DIR, check=True,
                                capture_output=True, text=True,
                            )
                    finally:
                        fcntl.flock(lock_f, fcntl.LOCK_UN)
            except (subprocess.CalledProcessError, OSError) as e:
                _build_error = getattr(e, "stderr", str(e)) or str(e)
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            _build_error = str(e)
            return None
        _declare(lib)
        _lib = lib
        return lib


_EXECUTE_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int
)


def _declare(lib: ctypes.CDLL) -> None:
    lib.hvd_init.restype = ctypes.c_int
    lib.hvd_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_longlong, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_shutdown.restype = None
    lib.hvd_is_initialized.restype = ctypes.c_int
    lib.hvd_set_execute_callback.restype = None
    lib.hvd_set_execute_callback.argtypes = [_EXECUTE_FN]
    lib.hvd_enqueue.restype = ctypes.c_longlong
    lib.hvd_enqueue.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_double,
    ]
    lib.hvd_enqueue_join.restype = ctypes.c_longlong
    lib.hvd_poll.restype = ctypes.c_int
    lib.hvd_poll.argtypes = [ctypes.c_longlong]
    lib.hvd_wait.restype = ctypes.c_int
    lib.hvd_wait.argtypes = [ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int]
    lib.hvd_cycles.restype = ctypes.c_longlong
    lib.hvd_last_joined_rank.restype = ctypes.c_int
    lib.hvd_joined_count.restype = ctypes.c_int
    lib.hvd_cache_hits.restype = ctypes.c_longlong
    lib.hvd_cache_entries.restype = ctypes.c_longlong
    lib.hvd_set_fusion_bytes.restype = None
    lib.hvd_set_fusion_bytes.argtypes = [ctypes.c_longlong]
    lib.hvd_set_cycle_us.restype = None
    lib.hvd_set_cycle_us.argtypes = [ctypes.c_longlong]
    lib.hvd_set_cache_capacity.restype = None
    lib.hvd_set_cache_capacity.argtypes = [ctypes.c_int]


def native_built() -> bool:
    """True if the native library is available (built or buildable)."""
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


# --- response wire parsing (src/message.cc Response::Serialize) --------------


@dataclass
class Response:
    type: int
    op: int
    dtype: int
    tensor_names: List[str] = field(default_factory=list)
    shapes: List[tuple] = field(default_factory=list)
    root_rank: int = 0
    prescale: float = 1.0
    postscale: float = 1.0
    error: str = ""
    joined_ranks: List[int] = field(default_factory=list)


class _Reader:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise ValueError("truncated native response")
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.take(8))[0]

    def str_(self) -> str:
        return self.take(self.u32()).decode("utf-8")

    def shape(self) -> tuple:
        return tuple(self.i64() for _ in range(self.u32()))


def parse_response(buf: bytes) -> Response:
    r = _Reader(buf)
    resp = Response(type=r.u8(), op=r.u8(), dtype=r.u8())
    n = r.u32()
    for _ in range(n):
        resp.tensor_names.append(r.str_())
        resp.shapes.append(r.shape())
    resp.root_rank = r.i32()
    resp.prescale = r.f64()
    resp.postscale = r.f64()
    resp.error = r.str_()
    nj = r.u32()
    resp.joined_ranks = [r.i32() for _ in range(nj)]
    return resp


# --- runtime wrapper ----------------------------------------------------------


class NativeError(RuntimeError):
    def __init__(self, code: int, reason: str) -> None:
        super().__init__(reason or f"native status {code}")
        self.code = code


class NativeRuntime:
    """Owns the native runtime lifecycle for this process."""

    def __init__(self) -> None:
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError(
                f"native runtime unavailable: {_build_error}"
            )
        self._cb_ref = None  # keep the CFUNCTYPE object alive
        self._initialized = False

    def init(
        self,
        rank: int,
        size: int,
        coordinator_addr: str = "127.0.0.1",
        coordinator_port: int = 9374,
        *,
        connect_timeout_sec: float = 60.0,
        cycle_time_ms: Optional[float] = None,
        fusion_threshold_bytes: Optional[int] = None,
        cache_capacity: Optional[int] = None,
        stall_warn_sec: Optional[float] = None,
        stall_shutdown_sec: Optional[float] = None,
        timeline_path: Optional[str] = None,
        timeline_mark_cycles: Optional[bool] = None,
    ) -> None:
        """Start the background runtime.  Unset knobs fall back to the same
        ``HOROVOD_*`` env vars the reference parses in BackgroundThreadLoop
        (``common/operations.cc:392-489``)."""
        env = os.environ.get

        def _f(v, env_name, default, cast):
            if v is not None:
                return v
            raw = env(env_name)
            return cast(raw) if raw not in (None, "") else default

        cycle_time_ms = _f(cycle_time_ms, "HOROVOD_CYCLE_TIME", 1.0, float)
        fusion_threshold_bytes = _f(
            fusion_threshold_bytes, "HOROVOD_FUSION_THRESHOLD", 64 << 20, int
        )
        cache_capacity = _f(cache_capacity, "HOROVOD_CACHE_CAPACITY", 1024, int)
        stall_warn_sec = _f(
            stall_warn_sec, "HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0, float
        )
        stall_shutdown_sec = _f(
            stall_shutdown_sec, "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0, float
        )
        if timeline_path is None:
            timeline_path = env("HOROVOD_TIMELINE", "")
        if timeline_mark_cycles is None:
            timeline_mark_cycles = env("HOROVOD_TIMELINE_MARK_CYCLES", "0") not in (
                "", "0", "false",
            )

        err = ctypes.create_string_buffer(1024)
        rc = self._lib.hvd_init(
            rank, size, coordinator_addr.encode(), coordinator_port,
            connect_timeout_sec, cycle_time_ms, fusion_threshold_bytes,
            cache_capacity, stall_warn_sec, stall_shutdown_sec,
            timeline_path.encode(), 1 if timeline_mark_cycles else 0,
            err, len(err),
        )
        if rc != 0:
            raise RuntimeError(
                f"native init failed: {err.value.decode(errors='replace')}"
            )
        self._initialized = True

    def set_executor(self, fn: Callable[[Response], int]) -> None:
        """Register the Python executor.  ``fn`` receives a parsed
        :class:`Response` and returns a STATUS_* code; it runs on the native
        background thread."""

        def _trampoline(buf_ptr, length):
            try:
                raw = bytes(
                    ctypes.cast(
                        buf_ptr, ctypes.POINTER(ctypes.c_ubyte * length)
                    ).contents
                )
                return int(fn(parse_response(raw)))
            except Exception:
                import traceback

                traceback.print_exc()
                return STATUS_INVALID

        self._cb_ref = _EXECUTE_FN(_trampoline)
        self._lib.hvd_set_execute_callback(self._cb_ref)

    def enqueue(
        self,
        name: str,
        op_type: int,
        shape: tuple,
        np_dtype,
        *,
        reduce_op: int = OP_SUM,
        root_rank: int = 0,
        prescale: float = 1.0,
        postscale: float = 1.0,
    ) -> int:
        arr = (ctypes.c_longlong * len(shape))(*shape)
        h = self._lib.hvd_enqueue(
            name.encode(), op_type, reduce_op, dtype_enum(np_dtype), arr,
            len(shape), root_rank, prescale, postscale,
        )
        if h == -1:
            raise NativeError(
                STATUS_DUPLICATE,
                f"A tensor named {name!r} was already submitted and is "
                "pending — this indicates two concurrent collective calls "
                "reused a name (reference DUPLICATE_NAME_ERROR).",
            )
        if h < 0:
            raise NativeError(STATUS_ABORTED, "native runtime not initialized")
        return int(h)

    def enqueue_join(self) -> int:
        h = self._lib.hvd_enqueue_join()
        if h < 0:
            raise NativeError(STATUS_ABORTED, "join enqueue failed")
        return int(h)

    def last_joined_rank(self) -> int:
        """Rank that joined LAST in the most recent completed join round
        (reference DoJoin output); -1 before any round completes."""
        return int(self._lib.hvd_last_joined_rank())

    def joined_count(self) -> int:
        """Coordinator-observed count of currently-joined ranks (always 0
        on non-coordinator ranks) — an event gauge for join ordering."""
        return int(self._lib.hvd_joined_count())

    def poll(self, handle: int) -> bool:
        return bool(self._lib.hvd_poll(handle))

    def wait(self, handle: int) -> None:
        """Block until completion; raise NativeError on failure."""
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.hvd_wait(handle, err, len(err))
        if rc != STATUS_OK:
            raise NativeError(rc, err.value.decode(errors="replace"))

    # introspection (used by tests and the autotuner)
    def cycles(self) -> int:
        return int(self._lib.hvd_cycles())

    def cache_hits(self) -> int:
        return int(self._lib.hvd_cache_hits())

    def cache_entries(self) -> int:
        return int(self._lib.hvd_cache_entries())

    def set_fusion_bytes(self, b: int) -> None:
        self._lib.hvd_set_fusion_bytes(b)

    def set_cycle_us(self, us: int) -> None:
        self._lib.hvd_set_cycle_us(int(us))

    def set_cache_capacity(self, n: int) -> None:
        self._lib.hvd_set_cache_capacity(int(n))

    def shutdown(self) -> None:
        if self._initialized:
            self._lib.hvd_shutdown()
            self._initialized = False
