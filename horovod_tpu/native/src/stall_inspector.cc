#include "stall_inspector.h"

#include <cstdio>

namespace hvd {

void StallInspector::RecordRank(const std::string& tensor, int rank) {
  auto it = pending_.find(tensor);
  if (it == pending_.end()) {
    Pending p;
    p.first_seen = std::chrono::steady_clock::now();
    p.ranks.insert(rank);
    pending_.emplace(tensor, std::move(p));
  } else {
    it->second.ranks.insert(rank);
  }
}

void StallInspector::RemoveTensor(const std::string& tensor) {
  pending_.erase(tensor);
}

bool StallInspector::CheckForStalls(int world_size) {
  bool shutdown = false;
  auto now = std::chrono::steady_clock::now();
  for (auto& kv : pending_) {
    double age =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (age > warn_sec_ && !kv.second.warned) {
      std::string missing;
      for (int r = 0; r < world_size; ++r)
        if (!kv.second.ranks.count(r))
          missing += (missing.empty() ? "" : ", ") + std::to_string(r);
      std::fprintf(stderr,
                   "[horovod_tpu] WARNING: One or more tensors were submitted "
                   "to be reduced/gathered but some ranks never did: tensor "
                   "'%s' is missing ranks [%s] after %.0fs. This may hang.\n",
                   kv.first.c_str(), missing.c_str(), age);
      kv.second.warned = true;
    }
    if (shutdown_sec_ > 0 && age > shutdown_sec_) {
      std::fprintf(stderr,
                   "[horovod_tpu] ERROR: tensor '%s' stalled beyond the "
                   "shutdown bound (%.0fs); aborting the job.\n",
                   kv.first.c_str(), shutdown_sec_);
      shutdown = true;
    }
  }
  return shutdown;
}

}  // namespace hvd
