#include "response_cache.h"

#include <algorithm>

namespace hvd {

// Bit positions must be identical on every rank: they are only mutated by
// Put/Touch, which every rank performs in the same order (responses are
// processed in broadcast order), so assignments and LRU evictions stay in
// lockstep — the same invariant the reference maintains by processing the
// bcast ResponseList identically everywhere.

bool ResponseCache::Matches(const Request& a, const Request& b) const {
  return a.type == b.type && a.op == b.op && a.dtype == b.dtype &&
         a.shape == b.shape && a.root_rank == b.root_rank &&
         a.prescale == b.prescale && a.postscale == b.postscale;
}

size_t ResponseCache::Lookup(const Request& req) {
  auto it = by_name_.find(req.name);
  if (it == by_name_.end()) return kNotCached;
  // Metadata changed (e.g. tensor re-registered with a new shape): force a
  // full renegotiation; the fresh Put will overwrite this bit in place on
  // every rank, keeping positions aligned.
  if (!Matches(entries_[it->second].request, req)) return kNotCached;
  return it->second;
}

void ResponseCache::Put(const Request& req, const Response& resp) {
  if (capacity_ == 0) return;
  auto it = by_name_.find(req.name);
  if (it != by_name_.end()) {
    size_t bit = it->second;
    entries_[bit].request = req;
    entries_[bit].response = resp;
    Touch(bit);
    return;
  }
  size_t bit;
  if (entries_.size() < capacity_) {
    bit = entries_.size();
    lru_.push_front(bit);
    entries_.push_back(Entry{req, resp, lru_.begin()});
  } else {
    bit = lru_.back();  // evict least-recently-executed
    by_name_.erase(entries_[bit].request.name);
    entries_[bit].request = req;
    entries_[bit].response = resp;
    Touch(bit);
  }
  by_name_[req.name] = bit;
}

void ResponseCache::Touch(size_t bit) {
  // O(1): splice this entry's node to the front.
  lru_.splice(lru_.begin(), lru_, entries_[bit].lru_it);
  entries_[bit].lru_it = lru_.begin();
}

void ResponseCache::Erase(const std::string& name) {
  auto it = by_name_.find(name);
  if (it == by_name_.end()) return;
  // Keep the slot (bit positions of other entries must not shift); mark it
  // unreachable by name so Lookup misses, and park it at the LRU tail so
  // Put reuses it first.
  size_t bit = it->second;
  lru_.splice(lru_.end(), lru_, entries_[bit].lru_it);
  entries_[bit].lru_it = std::prev(lru_.end());
  entries_[bit].request.name.clear();
  by_name_.erase(it);
}

void ResponseCache::Clear() {
  entries_.clear();
  by_name_.clear();
  lru_.clear();
}

}  // namespace hvd
