#include "response_cache.h"

#include <algorithm>

namespace hvd {

// Bit positions must be identical on every rank: they are only mutated by
// Put/Touch, which every rank performs in the same order (responses are
// processed in broadcast order), so assignments and LRU evictions stay in
// lockstep — the same invariant the reference maintains by processing the
// bcast ResponseList identically everywhere.

bool ResponseCache::Matches(const Request& a, const Request& b) const {
  return a.type == b.type && a.op == b.op && a.dtype == b.dtype &&
         a.shape == b.shape && a.root_rank == b.root_rank &&
         a.prescale == b.prescale && a.postscale == b.postscale;
}

size_t ResponseCache::Lookup(const Request& req) {
  auto it = by_name_.find(req.name);
  if (it == by_name_.end()) return kNotCached;
  // Metadata changed (e.g. tensor re-registered with a new shape): force a
  // full renegotiation; the fresh Put will overwrite this bit in place on
  // every rank, keeping positions aligned.
  if (!Matches(entries_[it->second].request, req)) return kNotCached;
  return it->second;
}

void ResponseCache::Put(const Request& req, const Response& resp) {
  if (capacity_ == 0) return;
  auto it = by_name_.find(req.name);
  if (it != by_name_.end()) {
    entries_[it->second] = Entry{req, resp};
    lru_.remove(it->second);
    lru_.push_front(it->second);
    return;
  }
  size_t bit;
  if (entries_.size() < capacity_) {
    bit = entries_.size();
    entries_.push_back(Entry{req, resp});
  } else {
    bit = lru_.back();  // evict least-recently-executed
    lru_.pop_back();
    by_name_.erase(entries_[bit].request.name);
    entries_[bit] = Entry{req, resp};
  }
  by_name_[req.name] = bit;
  lru_.push_front(bit);
}

void ResponseCache::Touch(size_t bit) {
  lru_.remove(bit);
  lru_.push_front(bit);
}

void ResponseCache::Erase(const std::string& name) {
  auto it = by_name_.find(name);
  if (it == by_name_.end()) return;
  // Keep the slot (bit positions of other entries must not shift); mark it
  // unreachable by name so Lookup misses and Put may reuse it via LRU.
  lru_.remove(it->second);
  lru_.push_back(it->second);
  entries_[it->second].request.name.clear();
  by_name_.erase(it);
}

void ResponseCache::Clear() {
  entries_.clear();
  by_name_.clear();
  lru_.clear();
}

}  // namespace hvd
