// Coordination controller: decides, each cycle, which tensors are globally
// ready and packages them into (fused) responses.
//
// Re-design of the reference's Controller (horovod/common/controller.cc:
// ComputeResponseList 55-347, ConstructResponse 369-602, FuseResponses
// 631-752, IncrementTensorCount 780-803) over the TCP star communicator
// instead of MPI/Gloo.  Differences by design:
//   * The steady-state fast path uses ONE bit-vector AND per cycle with two
//     reserved flag bits (bit0 = "I have no uncached work", bit1 = "I am
//     not joined/joining"), so a fully-cached cycle costs a single
//     coordination round and a join anywhere safely disables the fast path.
//   * Responses carry the joined-rank set so the executor (host language)
//     can substitute zeros — the reference allocates zero tensors inside
//     the C++ op layer (global_state.h:104-107); on TPU the zero tensor is
//     a constant in the executing XLA program.
#ifndef HVD_NATIVE_CONTROLLER_H
#define HVD_NATIVE_CONTROLLER_H

#include <atomic>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "comm.h"
#include "common.h"
#include "message.h"
#include "response_cache.h"
#include "stall_inspector.h"

namespace hvd {

class Controller {
 public:
  Controller(SocketComm* comm, size_t cache_capacity, int64_t fusion_bytes,
             double stall_warn_sec, double stall_shutdown_sec)
      : comm_(comm),
        cache_(cache_capacity),
        fusion_bytes_(fusion_bytes),
        stall_(stall_warn_sec, stall_shutdown_sec) {}

  // One negotiation round.  `pending` are this rank's freshly-popped
  // requests; `local_join` marks that this rank has an outstanding Join;
  // `want_shutdown` rides to the coordinator (reference
  // message.h:112-114).  Returns false on a communication failure
  // (`err` filled), in which case the job must abort.
  bool ComputeResponseList(std::vector<Request> pending, bool local_join,
                           bool want_shutdown, ResponseList* out,
                           std::string* err);

  // Fuse a response list for execution: adjacent single-tensor ALLREDUCE
  // responses with identical (op, dtype, joined set, scales) merge until
  // fusion_bytes_ is reached (reference FuseResponses).
  std::vector<Response> Fuse(const std::vector<Response>& responses) const;

  int64_t cache_hits() const { return cache_.hits(); }
  size_t cache_entries() const { return cache_.NumEntries(); }
  // Coordinator-observed count of currently-joined ranks (0 on workers).
  // Lets rank 0 wait on "the stragglers have demonstrably joined" as an
  // event instead of a sleep (tests) — updated on the background thread,
  // read from the application thread.
  int joined_count() const { return joined_count_.load(); }
  // Written from the application thread (autotuner), read by the
  // background thread's Fuse() — atomic for data-race freedom.  Cross-rank
  // consistency is the caller's contract: apply only behind a barrier
  // flush so no two ranks fuse the same response stream with different
  // thresholds (see Autotuner._apply).
  void set_fusion_bytes(int64_t b) { fusion_bytes_.store(b); }
  void set_cache_capacity(size_t n) { cache_.set_capacity(n); }
  int64_t fusion_bytes() const { return fusion_bytes_.load(); }

 private:
  // Coordinator-only (rank 0) slow path: ingest gathered request lists,
  // emit single-tensor responses for tensors now ready on all non-joined
  // ranks, plus ERROR responses for metadata mismatches.
  void CoordinatorIngest(const std::vector<RequestList>& lists,
                         ResponseList* out);
  Response ConstructResponse(const std::string& name);
  static bool CheckConsistency(const std::vector<Request>& reqs,
                               std::string* error);

  SocketComm* comm_;
  ResponseCache cache_;
  // This rank's requests submitted through the slow path whose responses
  // have not arrived yet (readiness may lag submission by many cycles
  // while other ranks catch up).  Response processing uses these as the
  // cache KEYS — the local metadata, not the coordinator's, is what the
  // next Lookup compares against (allgather/alltoall first dims vary per
  // rank).  Background-thread-only.
  std::unordered_map<std::string, Request> local_pending_;
  std::atomic<int64_t> fusion_bytes_;
  StallInspector stall_;

  // Coordinator state (rank 0 only), reference MessageTable.
  struct TableEntry {
    std::vector<Request> requests;  // one per submitting rank
    std::set<int> ranks;
  };
  std::map<std::string, TableEntry> message_table_;  // ordered => determinism
  std::set<int> joined_ranks_;
  // Arrival order of joins at negotiation granularity (reference
  // operations.cc:919-943 tracks the same so hvd.join() can return the
  // rank holding the most-advanced state); carried to every rank in the
  // JOIN response's root_rank field.
  int last_joined_rank_ = -1;
  std::atomic<int> joined_count_{0};
  bool stall_abort_ = false;  // rank 0: stall exceeded the shutdown bound

  // Cache-divergence DEFERRAL: when this rank's cached bit fails the
  // global AND (a peer popped the same tensor a cycle later — routine
  // submission skew), the request is HELD for up to kMaxDeferCycles
  // cycles instead of forcing a slow renegotiation round: the laggard
  // usually sets the bit next cycle and the tensor completes on the
  // fast path.  Entries exceeding the bound are marked for forced
  // renegotiation, which lands them in next cycle's uncached list — so
  // the resulting slow round is triggered through bit0, i.e. agreed
  // GLOBALLY (a mid-cycle local trigger could not be: the slow gather
  // is collective).  Background-thread-only.
  std::vector<Request> carryover_;
  std::unordered_map<std::string, int> defer_counts_;
  std::set<std::string> renegotiate_names_;
  static constexpr int kMaxDeferCycles = 3;
};

}  // namespace hvd

#endif  // HVD_NATIVE_CONTROLLER_H
