// Control-plane communicator: length-prefixed messages over TCP in a star
// topology rooted at rank 0.
//
// Replaces the roles MPI / Gloo play for the reference's *controller*
// (horovod/common/mpi/mpi_controller.cc:107-199 — gatherv of ready-tensor
// requests to rank 0 and bcast of final responses;
// gloo/gloo_context.cc:113-160 — TCP bootstrap).  Only coordination
// metadata flows here (tensor names/shapes, bit-vectors); tensor data rides
// ICI/DCN inside XLA programs and never touches these sockets, so a simple
// star is the right topology: one RTT per negotiation round, no fan-in
// tree needed at control-plane message sizes.
#ifndef HVD_NATIVE_COMM_H
#define HVD_NATIVE_COMM_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

class SocketComm {
 public:
  SocketComm() = default;
  ~SocketComm();

  // Establish the full star: rank 0 binds/listens on port and accepts
  // size-1 identified connections; other ranks dial addr:port with
  // retry/backoff (the launcher may start workers before the coordinator).
  // Returns false (with reason) on failure.  size==1 is a no-op.
  bool Init(int rank, int size, const std::string& addr, int port,
            double timeout_sec, std::string* err);
  void Shutdown();

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Collect one byte-buffer per rank at rank 0 (reference:
  // MPIController::RecvReadyTensors' gatherv).  At rank 0 `out` holds
  // size entries indexed by rank (own payload included); at workers `out`
  // is left empty.
  bool Gather(const std::vector<uint8_t>& payload,
              std::vector<std::vector<uint8_t>>* out, std::string* err);

  // Broadcast a byte-buffer from rank 0 to everyone (reference:
  // SendFinalTensors' bcast).  At workers `payload` is replaced by the
  // received buffer.
  bool Bcast(std::vector<uint8_t>* payload, std::string* err);

  // Bit-vector allreduce (AND or OR) — the response-cache fast path's
  // primitive (reference: MPIController::CrossRankBitwiseAnd/Or,
  // mpi/mpi_controller.cc:87-105).  Implemented as gather+combine+bcast.
  bool AllreduceBits(std::vector<uint64_t>* bits, bool is_and, std::string* err);

  // Combined AND + OR of the same local vector in ONE round (the
  // reference needs both to detect cache-bit divergence — a tensor some
  // ranks submitted-cached and others haven't submitted at all — see
  // CacheCoordinator::sync, response_cache.h:107-167).  On return,
  // bits_and/bits_or hold the global AND/OR of every rank's `bits`.
  bool AllreduceBitsAndOr(const std::vector<uint64_t>& bits,
                          std::vector<uint64_t>* bits_and,
                          std::vector<uint64_t>* bits_or, std::string* err);

  bool Barrier(std::string* err);

 private:
  bool SendFrame(int fd, const std::vector<uint8_t>& payload, std::string* err);
  bool RecvFrame(int fd, std::vector<uint8_t>* payload, std::string* err);

  int rank_ = 0;
  int size_ = 1;
  int listen_fd_ = -1;
  // rank 0: peer_fds_[r] = socket to rank r (index 0 unused).
  // workers: peer_fds_[0] = socket to rank 0.
  std::vector<int> peer_fds_;
};

}  // namespace hvd

#endif  // HVD_NATIVE_COMM_H
