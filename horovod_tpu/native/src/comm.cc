#include "comm.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <thread>

namespace hvd {

namespace {

bool SendAll(int fd, const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

SocketComm::~SocketComm() { Shutdown(); }

void SocketComm::Shutdown() {
  for (int fd : peer_fds_)
    if (fd >= 0) ::close(fd);
  peer_fds_.clear();
  if (listen_fd_ >= 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
}

bool SocketComm::Init(int rank, int size, const std::string& addr, int port,
                      double timeout_sec, std::string* err) {
  rank_ = rank;
  size_ = size;
  if (size <= 1) return true;

  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::duration<double>(timeout_sec);

  if (rank == 0) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) {
      *err = std::string("socket(): ") + strerror(errno);
      return false;
    }
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
      *err = std::string("bind(:") + std::to_string(port) + "): " + strerror(errno);
      return false;
    }
    if (::listen(listen_fd_, size) < 0) {
      *err = std::string("listen(): ") + strerror(errno);
      return false;
    }
    peer_fds_.assign(size, -1);
    for (int i = 1; i < size; ++i) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        *err = std::string("accept(): ") + strerror(errno);
        return false;
      }
      SetNoDelay(fd);
      int32_t peer_rank = -1;
      if (!RecvAll(fd, &peer_rank, 4) || peer_rank < 1 || peer_rank >= size ||
          peer_fds_[peer_rank] != -1) {
        *err = "coordinator: bad rank handshake";
        ::close(fd);
        return false;
      }
      peer_fds_[peer_rank] = fd;
    }
  } else {
    // Resolve coordinator address.
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (::getaddrinfo(addr.c_str(), port_s.c_str(), &hints, &res) != 0 || !res) {
      *err = "getaddrinfo(" + addr + ") failed";
      return false;
    }
    int fd = -1;
    // Retry with backoff: the coordinator may not be listening yet.
    for (;;) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
      if (fd >= 0) ::close(fd);
      fd = -1;
      if (std::chrono::steady_clock::now() > deadline) {
        ::freeaddrinfo(res);
        *err = "connect(" + addr + ":" + port_s + ") timed out";
        return false;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    SetNoDelay(fd);
    int32_t my_rank = rank;
    if (!SendAll(fd, &my_rank, 4)) {
      *err = "rank handshake send failed";
      ::close(fd);
      return false;
    }
    peer_fds_.assign(1, fd);
  }
  return true;
}

bool SocketComm::SendFrame(int fd, const std::vector<uint8_t>& payload,
                           std::string* err) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!SendAll(fd, &len, 4) ||
      (len > 0 && !SendAll(fd, payload.data(), payload.size()))) {
    *err = std::string("send: ") + strerror(errno);
    return false;
  }
  return true;
}

bool SocketComm::RecvFrame(int fd, std::vector<uint8_t>* payload, std::string* err) {
  uint32_t len = 0;
  if (!RecvAll(fd, &len, 4)) {
    *err = "recv: peer closed";
    return false;
  }
  payload->resize(len);
  if (len > 0 && !RecvAll(fd, payload->data(), len)) {
    *err = "recv: truncated frame";
    return false;
  }
  return true;
}

bool SocketComm::Gather(const std::vector<uint8_t>& payload,
                        std::vector<std::vector<uint8_t>>* out, std::string* err) {
  out->clear();
  if (size_ <= 1) {
    out->push_back(payload);
    return true;
  }
  if (rank_ == 0) {
    out->resize(size_);
    (*out)[0] = payload;
    for (int r = 1; r < size_; ++r)
      if (!RecvFrame(peer_fds_[r], &(*out)[r], err)) return false;
    return true;
  }
  return SendFrame(peer_fds_[0], payload, err);
}

bool SocketComm::Bcast(std::vector<uint8_t>* payload, std::string* err) {
  if (size_ <= 1) return true;
  if (rank_ == 0) {
    for (int r = 1; r < size_; ++r)
      if (!SendFrame(peer_fds_[r], *payload, err)) return false;
    return true;
  }
  return RecvFrame(peer_fds_[0], payload, err);
}

bool SocketComm::AllreduceBits(std::vector<uint64_t>* bits, bool is_and,
                               std::string* err) {
  if (size_ <= 1) return true;
  std::vector<uint8_t> payload(bits->size() * 8);
  std::memcpy(payload.data(), bits->data(), payload.size());
  std::vector<std::vector<uint8_t>> gathered;
  if (!Gather(payload, &gathered, err)) return false;
  if (rank_ == 0) {
    std::vector<uint64_t> acc = *bits;
    for (int r = 1; r < size_; ++r) {
      if (gathered[r].size() != payload.size()) {
        *err = "bit-vector size mismatch across ranks";
        return false;
      }
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(gathered[r].data());
      for (size_t i = 0; i < acc.size(); ++i)
        acc[i] = is_and ? (acc[i] & peer[i]) : (acc[i] | peer[i]);
    }
    std::memcpy(payload.data(), acc.data(), payload.size());
  }
  if (!Bcast(&payload, err)) return false;
  std::memcpy(bits->data(), payload.data(), payload.size());
  return true;
}

bool SocketComm::AllreduceBitsAndOr(const std::vector<uint64_t>& bits,
                                    std::vector<uint64_t>* bits_and,
                                    std::vector<uint64_t>* bits_or,
                                    std::string* err) {
  *bits_and = bits;
  *bits_or = bits;
  if (size_ <= 1) return true;
  size_t nbytes = bits.size() * 8;
  std::vector<uint8_t> payload(nbytes);
  std::memcpy(payload.data(), bits.data(), nbytes);
  std::vector<std::vector<uint8_t>> gathered;
  if (!Gather(payload, &gathered, err)) return false;
  std::vector<uint8_t> wire;
  if (rank_ == 0) {
    // Ranks may briefly disagree on bit-vector length while a cache
    // capacity change (autotuner) propagates.  Treat missing words as 0:
    // AND clears those cache slots, which re-enter negotiation via the
    // divergence slow path — self-healing instead of a hard error.
    size_t max_words = bits.size();
    for (int r = 1; r < size_; ++r)
      max_words = std::max(max_words, gathered[r].size() / 8);
    std::vector<uint64_t> all_and(max_words, 0), all_or(max_words, 0);
    std::memcpy(all_and.data(), bits.data(), nbytes);
    std::memcpy(all_or.data(), bits.data(), nbytes);
    for (int r = 1; r < size_; ++r) {
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(gathered[r].data());
      size_t peer_words = gathered[r].size() / 8;
      for (size_t i = 0; i < max_words; ++i) {
        uint64_t w = i < peer_words ? peer[i] : 0;
        all_and[i] &= w;
        all_or[i] |= w;
      }
    }
    wire.resize(2 * max_words * 8);
    std::memcpy(wire.data(), all_and.data(), max_words * 8);
    std::memcpy(wire.data() + max_words * 8, all_or.data(), max_words * 8);
  }
  if (!Bcast(&wire, err)) return false;
  // Adopt the coordinator's (max) length rather than truncating to the
  // local one: divergence beyond this rank's current capacity must still
  // force the slow-path round on EVERY rank, or ranks disagree on whether
  // a Gather/Bcast round happens and the stream desynchronizes.  The
  // controller's divergence scan iterates whatever length arrives here.
  size_t wire_words = wire.size() / 16;
  bits_and->assign(wire_words, 0);
  bits_or->assign(wire_words, 0);
  std::memcpy(bits_and->data(), wire.data(), wire_words * 8);
  std::memcpy(bits_or->data(), wire.data() + wire_words * 8, wire_words * 8);
  return true;
}

bool SocketComm::Barrier(std::string* err) {
  std::vector<uint64_t> bits(1, 0);
  return AllreduceBits(&bits, /*is_and=*/true, err);
}

}  // namespace hvd
