#include "comm.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <random>
#include <thread>

#include "logging.h"

namespace hvd {

namespace {

bool SendAll(int fd, const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// ms == 0 restores blocking I/O (tv {0,0} disables the socket timeouts).
void SetIoTimeoutMs(int fd, int64_t ms) {
  timeval tv{};
  tv.tv_sec = static_cast<time_t>(ms / 1000);
  tv.tv_usec = static_cast<suseconds_t>((ms % 1000) * 1000);
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool SendVerdict(int fd, bool accepted) {
  uint8_t v = accepted ? 1 : 0;
  ssize_t n;
  do {
    n = ::send(fd, &v, 1, MSG_NOSIGNAL);
  } while (n < 0 && errno == EINTR);
  return n == 1;
}

// RecvAll with an ABSOLUTE deadline (poll + nonblocking-style recv
// budgeting): per-recv SO_RCVTIMEO alone would let a peer dribble one
// byte per timeout window and hold the coordinator's single-threaded
// accept loop far past its handshake budget.
bool RecvAllBy(int fd, void* buf, size_t len,
               std::chrono::steady_clock::time_point deadline) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
        deadline - std::chrono::steady_clock::now());
    if (left.count() <= 0) return false;
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1,
                    static_cast<int>(std::min<int64_t>(left.count(), 1000)));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (pr == 0 || !(pfd.revents & (POLLIN | POLLHUP | POLLERR))) continue;
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR || errno == EAGAIN ||
                    errno == EWOULDBLOCK))
        continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// --- connect-time authentication ------------------------------------------
//
// The rendezvous KV signs every payload with the per-job
// HOROVOD_SECRET_KEY (runner/secret.py), but these sockets previously
// accepted any connecting process — an inconsistent trust model for the
// same deployment.  Every connection now performs a mutual HMAC-SHA256
// challenge-response keyed by the same job secret (reference trust model:
// run/common/util/secret.py usage in gloo_run), so a stray or malicious
// local process can neither impersonate a rank nor a coordinator.
// SHA-256 per FIPS 180-4; no external crypto dependency.

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buffered = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t Rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void Block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void Update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len += n;
    while (n > 0) {
      size_t take = std::min(n, sizeof(buf) - buffered);
      std::memcpy(buf + buffered, p, take);
      buffered += take;
      p += take;
      n -= take;
      if (buffered == 64) {
        Block(buf);
        buffered = 0;
      }
    }
  }

  void Final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buffered != 56) Update(&zero, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; ++i) lb[i] = uint8_t(bits >> (56 - 8 * i));
    Update(lb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void HmacSha256(const std::string& key, const uint8_t* msg, size_t msg_len,
                uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 kh;
    kh.Update(key.data(), key.size());
    kh.Final(k);
  } else {
    std::memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 hi;
  hi.Update(ipad, 64);
  hi.Update(msg, msg_len);
  hi.Final(inner);
  Sha256 ho;
  ho.Update(opad, 64);
  ho.Update(inner, 32);
  ho.Final(out);
}

bool ConstTimeEqual(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t acc = 0;
  for (size_t i = 0; i < n; ++i) acc |= a[i] ^ b[i];
  return acc == 0;
}

void FillNonce(uint8_t out[32]) {
  int fd = ::open("/dev/urandom", O_RDONLY);
  if (fd >= 0) {
    size_t got = 0;
    while (got < 32) {
      ssize_t n = ::read(fd, out + got, 32 - got);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        break;
      }
      got += static_cast<size_t>(n);
    }
    ::close(fd);
    if (got == 32) return;
  }
  std::random_device rd;
  for (int i = 0; i < 32; i += 4) {
    uint32_t v = rd();
    std::memcpy(out + i, &v, 4);
  }
}

std::string JobSecret() {
  const char* s = ::getenv("HOROVOD_SECRET_KEY");
  return s ? std::string(s) : std::string();
}

// Handshake wire (client -> coordinator first):
//   auth mode:    magic=kMagicAuth(4) rank(4) client_nonce(32)
//     coord  ->   server_nonce(32) HMAC(S, "hvd-coord" || client_nonce)(32)
//     client ->   HMAC(S, "hvd-rank" || rank_le(4) || server_nonce)(32)
//   no-auth mode: magic=kMagicPlain(4) rank(4)   (only when neither side
//     has HOROVOD_SECRET_KEY — standalone/debug use)
constexpr uint32_t kMagicAuth = 0x48764131;   // "Hv A1"
constexpr uint32_t kMagicPlain = 0x48764130;  // "Hv A0"
constexpr char kCoordTag[] = "hvd-coord";
constexpr char kRankTag[] = "hvd-rank";

// Handshake integers ride the wire (and enter the HMAC transcripts) as
// explicit little-endian bytes, so a mixed-endianness cluster fails with
// honest protocol errors instead of a misleading "secret key mismatch".
inline void PutLe32(uint8_t* out, uint32_t v) {
  out[0] = static_cast<uint8_t>(v);
  out[1] = static_cast<uint8_t>(v >> 8);
  out[2] = static_cast<uint8_t>(v >> 16);
  out[3] = static_cast<uint8_t>(v >> 24);
}
inline uint32_t GetLe32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

}  // namespace

SocketComm::~SocketComm() { Shutdown(); }

void SocketComm::Shutdown() {
  for (int fd : peer_fds_)
    if (fd >= 0) ::close(fd);
  peer_fds_.clear();
  if (listen_fd_ >= 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
}

bool SocketComm::Init(int rank, int size, const std::string& addr, int port,
                      double timeout_sec, std::string* err) {
  rank_ = rank;
  size_ = size;
  if (size <= 1) return true;

  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::duration<double>(timeout_sec);

  const std::string secret = JobSecret();

  if (rank == 0) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) {
      *err = std::string("socket(): ") + strerror(errno);
      return false;
    }
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
      *err = std::string("bind(:") + std::to_string(port) + "): " + strerror(errno);
      return false;
    }
    if (::listen(listen_fd_, size) < 0) {
      *err = std::string("listen(): ") + strerror(errno);
      return false;
    }
    peer_fds_.assign(size, -1);
    int connected = 0;
    // A connection failing the handshake is dropped and the loop keeps
    // accepting: a stray or wrong-key process must not be able to take a
    // legitimate rank's slot OR abort the job's bootstrap.
    while (connected < size - 1) {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      if (left.count() <= 0) {
        *err = "coordinator: timed out waiting for " +
               std::to_string(size - 1 - connected) + " rank(s)";
        return false;
      }
      pollfd pfd{listen_fd_, POLLIN, 0};
      int pr = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(
                                   left.count(), 1000)));
      if (pr < 0 && errno != EINTR) {
        *err = std::string("poll(): ") + strerror(errno);
        return false;
      }
      if (pr <= 0 || !(pfd.revents & POLLIN)) continue;
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) continue;
      SetNoDelay(fd);
      // ABSOLUTE per-connection handshake deadline: a connection that
      // goes silent or dribbles bytes (port scanner, health probe, slow-
      // loris) is dropped after a small fixed budget — it can neither
      // block the accept loop past the bootstrap deadline nor hold it
      // one recv-timeout at a time.  Legitimate handshakes complete in
      // microseconds; 2s absorbs scheduler hiccups.
      auto hs_deadline =
          std::chrono::steady_clock::now() +
          std::chrono::milliseconds(std::min<int64_t>(left.count(), 2000));
      SetIoTimeoutMs(fd, 2000);  // bounds the verdict/reply sends too
      uint8_t hdr[8];
      if (!RecvAllBy(fd, hdr, 8, hs_deadline)) {
        ::close(fd);
        continue;
      }
      const uint32_t magic = GetLe32(hdr);
      const int32_t peer_rank = static_cast<int32_t>(GetLe32(hdr + 4));
      const bool peer_auth = magic == kMagicAuth;
      if ((!peer_auth && magic != kMagicPlain) ||
          (secret.empty() != !peer_auth)) {
        HVD_LOG(Warning) << "rejecting connection: "
                      << (peer_auth ? "unauthenticated coordinator cannot "
                                      "verify an authenticating client"
                                    : "client did not authenticate");
        SendVerdict(fd, false);
        ::close(fd);
        continue;
      }
      if (peer_auth) {
        uint8_t client_nonce[32], server_nonce[32], reply[64], proof[32];
        if (!RecvAllBy(fd, client_nonce, 32, hs_deadline)) {
          ::close(fd);
          continue;
        }
        FillNonce(server_nonce);
        // reply = server_nonce || HMAC(S, "hvd-coord" || client_nonce)
        std::vector<uint8_t> msg(sizeof(kCoordTag) - 1 + 32);
        std::memcpy(msg.data(), kCoordTag, sizeof(kCoordTag) - 1);
        std::memcpy(msg.data() + sizeof(kCoordTag) - 1, client_nonce, 32);
        std::memcpy(reply, server_nonce, 32);
        HmacSha256(secret, msg.data(), msg.size(), reply + 32);
        if (!SendAll(fd, reply, 64) ||
            !RecvAllBy(fd, proof, 32, hs_deadline)) {
          ::close(fd);
          continue;
        }
        std::vector<uint8_t> expect_msg(sizeof(kRankTag) - 1 + 4 + 32);
        std::memcpy(expect_msg.data(), kRankTag, sizeof(kRankTag) - 1);
        PutLe32(expect_msg.data() + sizeof(kRankTag) - 1,
                static_cast<uint32_t>(peer_rank));
        std::memcpy(expect_msg.data() + sizeof(kRankTag) - 1 + 4,
                    server_nonce, 32);
        uint8_t expect[32];
        HmacSha256(secret, expect_msg.data(), expect_msg.size(), expect);
        if (!ConstTimeEqual(proof, expect, 32)) {
          HVD_LOG(Warning) << "rejecting connection claiming rank " << peer_rank
                        << ": HMAC challenge failed (secret key mismatch?)";
          SendVerdict(fd, false);
          ::close(fd);
          continue;
        }
      }
      if (peer_rank < 1 || peer_rank >= size || peer_fds_[peer_rank] != -1) {
        HVD_LOG(Warning) << "rejecting connection: bad or duplicate rank "
                      << peer_rank;
        SendVerdict(fd, false);
        ::close(fd);
        continue;
      }
      if (!SendVerdict(fd, true)) {
        ::close(fd);
        continue;
      }
      SetIoTimeoutMs(fd, 0);  // steady-state negotiation blocks indefinitely
      peer_fds_[peer_rank] = fd;
      ++connected;
    }
  } else {
    // Resolve coordinator address.
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (::getaddrinfo(addr.c_str(), port_s.c_str(), &hints, &res) != 0 || !res) {
      *err = "getaddrinfo(" + addr + ") failed";
      return false;
    }
    int fd = -1;
    // Retry with backoff: the coordinator may not be listening yet.
    for (;;) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
      if (fd >= 0) ::close(fd);
      fd = -1;
      if (std::chrono::steady_clock::now() > deadline) {
        ::freeaddrinfo(res);
        *err = "connect(" + addr + ":" + port_s + ") timed out";
        return false;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    SetNoDelay(fd);
    // Handshake must respect the bootstrap deadline: a coordinator that
    // accepted but went silent must not block past connect_timeout_sec.
    {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      SetIoTimeoutMs(fd, std::max<int64_t>(1, left.count()));
    }
    const uint32_t magic = secret.empty() ? kMagicPlain : kMagicAuth;
    const int32_t my_rank = rank;
    uint8_t hello[40];
    PutLe32(hello, magic);
    PutLe32(hello + 4, static_cast<uint32_t>(my_rank));
    size_t hello_len = 8;
    uint8_t client_nonce[32];
    if (!secret.empty()) {
      FillNonce(client_nonce);
      std::memcpy(hello + 8, client_nonce, 32);
      hello_len = 40;
    }
    if (!SendAll(fd, hello, hello_len)) {
      *err = "rank handshake send failed";
      ::close(fd);
      return false;
    }
    if (!secret.empty()) {
      // Verify the coordinator knows the job secret BEFORE trusting any
      // negotiation state from it, then prove our own rank claim.
      // RecvAllBy: absolute deadline — a byte-dribbling squatter on the
      // coordinator port must not hold this rank past the bootstrap
      // deadline (mirror of the coordinator-side hardening).
      uint8_t reply[64];
      if (!RecvAllBy(fd, reply, 64, std::chrono::time_point_cast<
              std::chrono::steady_clock::duration>(deadline))) {
        *err = "coordinator closed during authentication (secret key "
               "mismatch, or the coordinator does not authenticate?)";
        ::close(fd);
        return false;
      }
      std::vector<uint8_t> msg(sizeof(kCoordTag) - 1 + 32);
      std::memcpy(msg.data(), kCoordTag, sizeof(kCoordTag) - 1);
      std::memcpy(msg.data() + sizeof(kCoordTag) - 1, client_nonce, 32);
      uint8_t expect[32];
      HmacSha256(secret, msg.data(), msg.size(), expect);
      if (!ConstTimeEqual(reply + 32, expect, 32)) {
        *err = "coordinator failed the HMAC challenge (HOROVOD_SECRET_KEY "
               "mismatch): refusing to join this control plane";
        ::close(fd);
        return false;
      }
      std::vector<uint8_t> proof_msg(sizeof(kRankTag) - 1 + 4 + 32);
      std::memcpy(proof_msg.data(), kRankTag, sizeof(kRankTag) - 1);
      PutLe32(proof_msg.data() + sizeof(kRankTag) - 1,
              static_cast<uint32_t>(my_rank));
      std::memcpy(proof_msg.data() + sizeof(kRankTag) - 1 + 4, reply, 32);
      uint8_t proof[32];
      HmacSha256(secret, proof_msg.data(), proof_msg.size(), proof);
      if (!SendAll(fd, proof, 32)) {
        *err = "authentication proof send failed";
        ::close(fd);
        return false;
      }
    }
    // Explicit accept/reject verdict in BOTH modes, so a rejected client
    // (auth-policy mismatch, wrong key, duplicate rank) learns at init()
    // time instead of failing later with an unrelated negotiation error.
    uint8_t verdict = 0;
    if (!RecvAllBy(fd, &verdict, 1, std::chrono::time_point_cast<
            std::chrono::steady_clock::duration>(deadline)) || verdict != 1) {
      *err = secret.empty()
                 ? "coordinator rejected this connection (does the job "
                   "require HOROVOD_SECRET_KEY?)"
                 : "coordinator rejected this connection (secret key "
                   "mismatch or duplicate rank)";
      ::close(fd);
      return false;
    }
    SetIoTimeoutMs(fd, 0);  // steady-state negotiation blocks indefinitely
    peer_fds_.assign(1, fd);
  }
  return true;
}

bool SocketComm::SendFrame(int fd, const std::vector<uint8_t>& payload,
                           std::string* err) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!SendAll(fd, &len, 4) ||
      (len > 0 && !SendAll(fd, payload.data(), payload.size()))) {
    *err = std::string("send: ") + strerror(errno);
    return false;
  }
  return true;
}

bool SocketComm::RecvFrame(int fd, std::vector<uint8_t>* payload, std::string* err) {
  uint32_t len = 0;
  if (!RecvAll(fd, &len, 4)) {
    *err = "recv: peer closed";
    return false;
  }
  payload->resize(len);
  if (len > 0 && !RecvAll(fd, payload->data(), len)) {
    *err = "recv: truncated frame";
    return false;
  }
  return true;
}

bool SocketComm::Gather(const std::vector<uint8_t>& payload,
                        std::vector<std::vector<uint8_t>>* out, std::string* err) {
  out->clear();
  if (size_ <= 1) {
    out->push_back(payload);
    return true;
  }
  if (rank_ == 0) {
    out->resize(size_);
    (*out)[0] = payload;
    for (int r = 1; r < size_; ++r)
      if (!RecvFrame(peer_fds_[r], &(*out)[r], err)) return false;
    return true;
  }
  return SendFrame(peer_fds_[0], payload, err);
}

bool SocketComm::Bcast(std::vector<uint8_t>* payload, std::string* err) {
  if (size_ <= 1) return true;
  if (rank_ == 0) {
    for (int r = 1; r < size_; ++r)
      if (!SendFrame(peer_fds_[r], *payload, err)) return false;
    return true;
  }
  return RecvFrame(peer_fds_[0], payload, err);
}

bool SocketComm::AllreduceBits(std::vector<uint64_t>* bits, bool is_and,
                               std::string* err) {
  if (size_ <= 1) return true;
  std::vector<uint8_t> payload(bits->size() * 8);
  std::memcpy(payload.data(), bits->data(), payload.size());
  std::vector<std::vector<uint8_t>> gathered;
  if (!Gather(payload, &gathered, err)) return false;
  if (rank_ == 0) {
    std::vector<uint64_t> acc = *bits;
    for (int r = 1; r < size_; ++r) {
      if (gathered[r].size() != payload.size()) {
        *err = "bit-vector size mismatch across ranks";
        return false;
      }
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(gathered[r].data());
      for (size_t i = 0; i < acc.size(); ++i)
        acc[i] = is_and ? (acc[i] & peer[i]) : (acc[i] | peer[i]);
    }
    std::memcpy(payload.data(), acc.data(), payload.size());
  }
  if (!Bcast(&payload, err)) return false;
  std::memcpy(bits->data(), payload.data(), payload.size());
  return true;
}

bool SocketComm::AllreduceBitsAndOr(const std::vector<uint64_t>& bits,
                                    std::vector<uint64_t>* bits_and,
                                    std::vector<uint64_t>* bits_or,
                                    std::string* err) {
  *bits_and = bits;
  *bits_or = bits;
  if (size_ <= 1) return true;
  size_t nbytes = bits.size() * 8;
  std::vector<uint8_t> payload(nbytes);
  std::memcpy(payload.data(), bits.data(), nbytes);
  std::vector<std::vector<uint8_t>> gathered;
  if (!Gather(payload, &gathered, err)) return false;
  std::vector<uint8_t> wire;
  if (rank_ == 0) {
    // Ranks may briefly disagree on bit-vector length while a cache
    // capacity change (autotuner) propagates.  Treat missing words as 0:
    // AND clears those cache slots, which re-enter negotiation via the
    // divergence slow path — self-healing instead of a hard error.
    size_t max_words = bits.size();
    for (int r = 1; r < size_; ++r)
      max_words = std::max(max_words, gathered[r].size() / 8);
    std::vector<uint64_t> all_and(max_words, 0), all_or(max_words, 0);
    std::memcpy(all_and.data(), bits.data(), nbytes);
    std::memcpy(all_or.data(), bits.data(), nbytes);
    for (int r = 1; r < size_; ++r) {
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(gathered[r].data());
      size_t peer_words = gathered[r].size() / 8;
      for (size_t i = 0; i < max_words; ++i) {
        uint64_t w = i < peer_words ? peer[i] : 0;
        all_and[i] &= w;
        all_or[i] |= w;
      }
    }
    wire.resize(2 * max_words * 8);
    std::memcpy(wire.data(), all_and.data(), max_words * 8);
    std::memcpy(wire.data() + max_words * 8, all_or.data(), max_words * 8);
  }
  if (!Bcast(&wire, err)) return false;
  // Adopt the coordinator's (max) length rather than truncating to the
  // local one: divergence beyond this rank's current capacity must still
  // force the slow-path round on EVERY rank, or ranks disagree on whether
  // a Gather/Bcast round happens and the stream desynchronizes.  The
  // controller's divergence scan iterates whatever length arrives here.
  size_t wire_words = wire.size() / 16;
  bits_and->assign(wire_words, 0);
  bits_or->assign(wire_words, 0);
  std::memcpy(bits_and->data(), wire.data(), wire_words * 8);
  std::memcpy(bits_or->data(), wire.data() + wire_words * 8, wire_words * 8);
  return true;
}

bool SocketComm::Barrier(std::string* err) {
  std::vector<uint64_t> bits(1, 0);
  return AllreduceBits(&bits, /*is_and=*/true, err);
}

}  // namespace hvd
