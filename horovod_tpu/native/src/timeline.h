// Chrome-tracing timeline writer.
//
// Re-implements the reference's Timeline/TimelineWriter
// (horovod/common/timeline.{h,cc}): per-tensor lifecycle events
// (NEGOTIATE -> QUEUE -> EXECUTE) appended to a chrome://tracing JSON file
// by a dedicated writer thread, fed through a queue so the negotiation hot
// loop never blocks on file IO.  The reference uses a boost lock-free SPSC
// ring; control-plane event rates (a few per tensor per step) don't justify
// a vendored dependency, so this uses a mutex+condvar MPSC queue.
#ifndef HVD_NATIVE_TIMELINE_H
#define HVD_NATIVE_TIMELINE_H

#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvd {

class Timeline {
 public:
  // Activity names follow the reference's constants (common/common.h:31-59),
  // minus the phases SPMD compilation removed.
  static constexpr const char* kNegotiate = "NEGOTIATE";
  static constexpr const char* kQueue = "QUEUE";
  static constexpr const char* kExecute = "EXECUTE";

  Timeline() = default;
  ~Timeline() { Shutdown(); }

  bool Initialize(const std::string& path);
  void Shutdown();
  bool Initialized() const { return initialized_; }

  void Begin(const std::string& tensor, const char* activity);
  void End(const std::string& tensor, const char* activity);
  void MarkCycle();  // optional cycle tick (HOROVOD_TIMELINE_MARK_CYCLES)

 private:
  struct Event {
    char ph;  // 'B', 'E', or 'i' (instant)
    std::string tensor;
    std::string activity;
    int64_t ts_us;
  };
  void Push(Event e);
  void WriterLoop();
  int64_t NowUs() const;

  bool initialized_ = false;
  FILE* file_ = nullptr;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> queue_;
  bool stop_ = false;
  std::thread writer_;
  std::unordered_map<std::string, int> tensor_tids_;
  bool first_record_ = true;
};

}  // namespace hvd

#endif  // HVD_NATIVE_TIMELINE_H
