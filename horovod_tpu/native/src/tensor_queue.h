// Thread-safe pending-request queue + in-flight handle table.
//
// Mirrors the reference's TensorQueue (horovod/common/tensor_queue.{h,cc})
// with one structural difference: the reference's table maps name ->
// TensorTableEntry holding framework tensor pointers; here tensor payloads
// stay in the host language (PJRT owns device buffers), so the table maps
// name -> handle metadata and the duplicate-submission race check
// (reference tensor_queue.cc:29-31, DUPLICATE_NAME_ERROR common.h:160-163)
// is enforced on names alone.
#ifndef HVD_NATIVE_TENSOR_QUEUE_H
#define HVD_NATIVE_TENSOR_QUEUE_H

#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvd {

struct HandleState {
  int64_t handle = -1;
  bool done = false;
  Status status;
};

class TensorQueue {
 public:
  // Enqueue a request; returns the handle, -1 on duplicate-name race, or
  // -2 if the queue is closed (runtime aborted/shut down — accepting the
  // request would hang the caller since nothing will ever pop it).
  int64_t Add(const Request& req);

  // Pop all pending requests (one negotiation cycle's worth — reference
  // PopMessagesFromQueue, controller.cc:71).
  std::vector<Request> PopAll();

  // Mark every tensor in `names` complete with `status` and wake waiters.
  void Complete(const std::vector<std::string>& names, const Status& status);

  // Fail everything (pending + in-flight) and close the queue — shutdown
  // path (reference operations.cc:515-521 SHUT_DOWN_ERROR delivery).
  void AbortAll(const Status& status);

  // Re-open after a full runtime shutdown/re-init cycle.
  void Reopen();

  // Handle API.
  bool Poll(int64_t handle);
  Status Wait(int64_t handle);  // blocks; erases the handle when done
  size_t PendingCount();

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  bool closed_ = false;  // set by AbortAll under mu_; rejects further Adds
  int64_t next_handle_ = 0;
  std::deque<Request> pending_;
  std::unordered_map<std::string, int64_t> name_to_handle_;
  std::unordered_map<int64_t, HandleState> handles_;
};

}  // namespace hvd

#endif  // HVD_NATIVE_TENSOR_QUEUE_H
