#include "controller.h"

#include <algorithm>

#include "logging.h"

namespace hvd {

namespace {
constexpr size_t kFlagBits = 2;  // bit0 = no-uncached-work, bit1 = not-joined

inline void SetBit(std::vector<uint64_t>& v, size_t i) {
  v[i / 64] |= (uint64_t{1} << (i % 64));
}
inline bool GetBit(const std::vector<uint64_t>& v, size_t i) {
  return (v[i / 64] >> (i % 64)) & 1;
}
}  // namespace

bool Controller::ComputeResponseList(std::vector<Request> pending,
                                     bool local_join, bool want_shutdown,
                                     ResponseList* out, std::string* err) {
  out->responses.clear();
  out->shutdown = false;

  // Requests deferred by earlier cycles (cache-divergence holds) rejoin
  // ahead of the fresh batch.
  if (!carryover_.empty()) {
    pending.insert(pending.begin(),
                   std::make_move_iterator(carryover_.begin()),
                   std::make_move_iterator(carryover_.end()));
    carryover_.clear();
  }

  // ---- Cache coordination (reference controller.cc:125-193) -------------
  // Partition pending requests into cache hits and misses, then agree
  // globally with one bit-vector AND.
  size_t nbits = kFlagBits + cache_.capacity();
  std::vector<uint64_t> bits((nbits + 63) / 64, 0);
  std::vector<Request> uncached;
  std::vector<std::pair<size_t, Request>> cached;  // (bit, request)
  for (auto& req : pending) {
    if (req.type == ReqType::JOIN) {
      uncached.push_back(std::move(req));
      continue;
    }
    if (renegotiate_names_.erase(req.name) > 0) {
      // Defer bound exceeded last cycle: force the slow path via the
      // uncached list (clears bit0 -> globally-agreed slow round).
      uncached.push_back(std::move(req));
      continue;
    }
    size_t bit = cache_.Lookup(req);
    if (bit == ResponseCache::kNotCached) {
      uncached.push_back(std::move(req));
    } else {
      SetBit(bits, kFlagBits + bit);
      cached.emplace_back(bit, std::move(req));
    }
  }
  // Stall inspection must run every cycle, not only when a slow-path round
  // happens to occur (a stalled tensor generates no new traffic, so waiting
  // for the next ingest would never fire).  A stall-shutdown forces a
  // slow-path round (by withholding bit0) so the abort reaches every rank.
  if (comm_->rank() == 0 && stall_.CheckForStalls(comm_->size()))
    stall_abort_ = true;

  bool has_join_request =
      std::any_of(uncached.begin(), uncached.end(),
                  [](const Request& r) { return r.type == ReqType::JOIN; });
  if (uncached.empty() && !want_shutdown && !stall_abort_) SetBit(bits, 0);
  if (!local_join && !has_join_request) SetBit(bits, 1);
  // A joined rank must not veto other ranks' cached work: it contributes
  // zeros, so its bit-vector is all-ones for cache slots.
  if (local_join)
    for (size_t b = 0; b < cache_.capacity(); ++b) SetBit(bits, kFlagBits + b);

  std::vector<uint64_t> and_bits, or_bits;
  if (!comm_->AllreduceBitsAndOr(bits, &and_bits, &or_bits, err)) return false;

  bool nobody_joined = GetBit(and_bits, 1);

  std::vector<Response> single;  // single-tensor responses, execution order
  if (nobody_joined) {
    // Fast path: globally-agreed cache bits execute straight from cache.
    // Bits cleared by the AND (some rank missed) fall back to the slow
    // path (reference: CacheCoordinator::sync -> invalid bits rejoin the
    // request list).  Iterate in BIT order, not local submission order:
    // execution order must be identical on every rank (the reference's
    // CacheCoordinator keeps its hits in a std::set for the same reason),
    // and ranks may have submitted the same tensors in different orders.
    std::sort(cached.begin(), cached.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    for (auto& [bit, req] : cached) {
      if (GetBit(and_bits, kFlagBits + bit)) {
        cache_.CountHit();
        cache_.Touch(bit);  // keep hot steady-state entries off the LRU tail
        defer_counts_.erase(req.name);
        single.push_back(cache_.Get(bit));
      } else if (++defer_counts_[req.name] <= kMaxDeferCycles) {
        // Some peer hasn't set this bit yet (routine cycle skew): HOLD
        // the request — next cycle usually agrees on the fast path,
        // saving the gather+bcast renegotiation round.
        if (defer_counts_[req.name] == 1) {
          // Entry into deferral is visible at debug so deferred-latency
          // stalls are diagnosable before the stall inspector fires
          // (routine one-cycle skew is common; don't warn).
          HVD_LOG(Debug) << "deferring cached tensor '" << req.name
                         << "' (peer cache-bit mismatch)";
        }
        carryover_.push_back(std::move(req));
      } else {
        // Held long enough; renegotiate through next cycle's uncached
        // list so the slow round stays a globally-derived decision.
        // Exceeding the bound means genuine cache divergence (e.g.
        // capacity skew), worth surfacing: completion for this tensor
        // lagged ~kMaxDeferCycles cycles and now pays a slow round.
        HVD_LOG(Warning) << "cached tensor '" << req.name
                         << "' exceeded the defer bound ("
                         << kMaxDeferCycles
                         << " cycles); forcing renegotiation";
        defer_counts_.erase(req.name);
        renegotiate_names_.insert(req.name);
        carryover_.push_back(std::move(req));
      }
    }
  } else {
    // Join in flight somewhere: the cache's stored responses don't carry
    // the live joined set, so everything renegotiates this cycle.
    for (auto& [bit, req] : cached) uncached.push_back(std::move(req));
  }

  // The slow path is a COLLECTIVE round: every rank must enter it whenever
  // any rank has uncached work, so the decision may only depend on the
  // globally-agreed vectors.  Two triggers:
  //   (1) some rank had uncached requests at submission time (bit0 AND
  //       cleared) — including requests whose defer bound expired last
  //       cycle (renegotiate_names_ routes them through uncached);
  //   (2) a join is in flight (everything renegotiates with join
  //       accounting).
  // A diverged cache bit (OR set, AND cleared) no longer forces a round:
  // the holders DEFER the request up to kMaxDeferCycles — routine
  // submission skew (a peer popping the same tensor one cycle later)
  // then completes on the fast path instead of paying a gather+bcast,
  // and genuinely-diverged caches (capacity skew) still self-heal
  // through the bounded-defer renegotiation.
  (void)or_bits;
  bool need_slow = !GetBit(and_bits, 0) || !nobody_joined;

  // ---- Slow path: full gather + construct + bcast -----------------------
  if (need_slow) {
    RequestList mine;
    mine.rank = comm_->rank();
    mine.shutdown = want_shutdown;
    mine.requests = std::move(uncached);

    std::vector<std::vector<uint8_t>> gathered;
    if (!comm_->Gather(mine.Serialize(), &gathered, err)) return false;

    ResponseList constructed;
    if (comm_->rank() == 0) {
      std::vector<RequestList> lists;
      lists.reserve(gathered.size());
      for (auto& buf : gathered) lists.push_back(RequestList::Parse(buf));
      CoordinatorIngest(lists, &constructed);
    }
    std::vector<uint8_t> wire = constructed.Serialize();
    if (!comm_->Bcast(&wire, err)) return false;
    constructed = ResponseList::Parse(wire);

    out->shutdown = constructed.shutdown;
    // Park this cycle's slow-path submissions: their responses may arrive
    // many cycles later (readiness waits for the slowest rank).
    for (const auto& r : mine.requests)
      if (r.type != ReqType::JOIN) local_pending_[r.name] = r;
    // Insert fresh single-tensor responses into the cache — every rank does
    // this in identical bcast order, keeping bit positions aligned.  The
    // cache KEY is this rank's own submitted request, not the
    // coordinator's response metadata: allgather/alltoall first dims
    // legitimately vary per rank (reference response_cache.h:45-102
    // carries per-rank sizes for the same reason), so keying on the local
    // request lets those ops ride the fast path too — next cycle's
    // Lookup compares against what THIS rank will resubmit.  A rank whose
    // first dim changes misses locally, clearing its bit and forcing a
    // global renegotiation that re-Puts the entry everywhere in lockstep.
    for (auto& resp : constructed.responses) {
      bool cacheable_type =
          resp.type == RespType::ALLREDUCE ||
          resp.type == RespType::BROADCAST ||
          resp.type == RespType::ALLGATHER ||
          resp.type == RespType::ALLTOALL ||
          resp.type == RespType::REDUCESCATTER;
      if (cacheable_type && resp.joined_ranks.empty() &&
          resp.tensor_names.size() == 1) {
        auto it = local_pending_.find(resp.tensor_names[0]);
        // Readiness requires every non-joined rank to have submitted, so
        // the local request exists for every cacheable response; skip
        // defensively if not (e.g. future op kinds with other semantics).
        if (it != local_pending_.end()) cache_.Put(it->second, resp);
      }
      for (const auto& n : resp.tensor_names) local_pending_.erase(n);
      single.push_back(std::move(resp));
    }
  }

  out->responses = Fuse(single);
  return true;
}

void Controller::CoordinatorIngest(const std::vector<RequestList>& lists,
                                   ResponseList* out) {
  bool shutdown = false;
  for (const auto& list : lists) {
    shutdown = shutdown || list.shutdown;
    for (const auto& req : list.requests) {
      if (req.type == ReqType::JOIN) {
        if (joined_ranks_.insert(list.rank).second) {
          last_joined_rank_ = list.rank;  // arrival order at cycle granularity
          joined_count_.store(static_cast<int>(joined_ranks_.size()));
        }
        continue;
      }
      auto& entry = message_table_[req.name];
      if (!entry.ranks.count(list.rank)) {
        entry.requests.push_back(req);
        entry.ranks.insert(list.rank);
        stall_.RecordRank(req.name, list.rank);
      }
    }
  }

  // Readiness: all non-joined ranks have submitted (reference
  // IncrementTensorCount: count == size - joined_size).
  int needed = comm_->size() - static_cast<int>(joined_ranks_.size());
  std::vector<std::string> ready;
  for (const auto& kv : message_table_) {
    if (static_cast<int>(kv.second.ranks.size()) >= needed)
      ready.push_back(kv.first);
  }
  // Barriers dispatch LAST within their cycle: a rank returning from a
  // barrier wait may immediately run a direct-path (un-negotiated)
  // collective, which is only safe once every co-ready response has been
  // dispatched on every rank (dispatch is sequential per rank and the
  // response order is common, so barrier-last makes the flush total).
  std::stable_sort(ready.begin(), ready.end(),
                   [this](const std::string& a, const std::string& b) {
                     bool ab = message_table_.at(a).requests.front().type ==
                               ReqType::BARRIER;
                     bool bb = message_table_.at(b).requests.front().type ==
                               ReqType::BARRIER;
                     return !ab && bb;
                   });
  for (const auto& name : ready) {
    out->responses.push_back(ConstructResponse(name));
    message_table_.erase(name);
    stall_.RemoveTensor(name);
  }

  // All ranks joined: emit the JOIN response that resets join state
  // everywhere (reference controller.cc:291-298).
  if (static_cast<int>(joined_ranks_.size()) == comm_->size()) {
    Response j;
    j.type = RespType::JOIN;
    j.tensor_names.push_back("join");
    j.shapes.push_back({});
    // root_rank carries the LAST rank to join (reference DoJoin contract:
    // torch/mpi_ops_v2.cc — callers broadcast final state from it).
    j.root_rank = last_joined_rank_;
    out->responses.push_back(j);
    joined_ranks_.clear();
    last_joined_rank_ = -1;
    joined_count_.store(0);
  }

  out->shutdown = shutdown || stall_abort_;
}

bool Controller::CheckConsistency(const std::vector<Request>& reqs,
                                  std::string* error) {
  const Request& first = reqs.front();
  for (const auto& r : reqs) {
    if (r.type != first.type) {
      *error = "Mismatched collective operations submitted for tensor '" +
               first.name + "'";
      return false;
    }
    if (r.dtype != first.dtype) {
      *error = "Mismatched data types for tensor '" + first.name + "'";
      return false;
    }
    if ((r.type == ReqType::ALLREDUCE || r.type == ReqType::REDUCESCATTER) &&
        (r.op != first.op || r.shape != first.shape ||
         r.prescale != first.prescale || r.postscale != first.postscale)) {
      *error = "Mismatched allreduce shape/op for tensor '" + first.name + "'";
      return false;
    }
    if (r.type == ReqType::BROADCAST &&
        (r.shape != first.shape || r.root_rank != first.root_rank)) {
      *error = "Mismatched broadcast shape or root rank for tensor '" +
               first.name + "'";
      return false;
    }
    if ((r.type == ReqType::ALLGATHER || r.type == ReqType::ALLTOALL) &&
        r.shape.size() == first.shape.size() && !r.shape.empty()) {
      // First dim may vary; trailing dims must match.
      for (size_t d = 1; d < r.shape.size(); ++d) {
        if (r.shape[d] != first.shape[d]) {
          *error = "Mismatched trailing dimensions for gathered tensor '" +
                   first.name + "'";
          return false;
        }
      }
    } else if ((r.type == ReqType::ALLGATHER || r.type == ReqType::ALLTOALL) &&
               r.shape.size() != first.shape.size()) {
      *error = "Mismatched rank (ndim) for gathered tensor '" + first.name + "'";
      return false;
    }
  }
  return true;
}

Response Controller::ConstructResponse(const std::string& name) {
  auto& entry = message_table_[name];
  const Request& first = entry.requests.front();
  Response resp;
  resp.tensor_names.push_back(name);
  resp.shapes.push_back(first.shape);
  resp.op = first.op;
  resp.dtype = first.dtype;
  resp.root_rank = first.root_rank;
  resp.prescale = first.prescale;
  resp.postscale = first.postscale;

  std::string error;
  if (!CheckConsistency(entry.requests, &error)) {
    resp.type = RespType::ERROR;
    resp.error = error;
    return resp;
  }
  // Gather/broadcast are unsupported while ranks are joined (reference
  // controller.cc:445-449, 519-523).
  if (!joined_ranks_.empty() && first.type != ReqType::ALLREDUCE &&
      first.type != ReqType::BARRIER) {
    resp.type = RespType::ERROR;
    resp.error = "Allgather/broadcast/alltoall/reducescatter are not "
                 "supported while a rank has joined; tensor '" + name + "'";
    return resp;
  }
  switch (first.type) {
    case ReqType::ALLREDUCE: resp.type = RespType::ALLREDUCE; break;
    case ReqType::ALLGATHER: resp.type = RespType::ALLGATHER; break;
    case ReqType::BROADCAST: resp.type = RespType::BROADCAST; break;
    case ReqType::ALLTOALL: resp.type = RespType::ALLTOALL; break;
    case ReqType::BARRIER: resp.type = RespType::BARRIER; break;
    case ReqType::JOIN: resp.type = RespType::JOIN; break;
    case ReqType::REDUCESCATTER: resp.type = RespType::REDUCESCATTER; break;
  }
  resp.joined_ranks.assign(joined_ranks_.begin(), joined_ranks_.end());
  return resp;
}

std::vector<Response> Controller::Fuse(
    const std::vector<Response>& responses) const {
  std::vector<Response> fused;
  for (const auto& r : responses) {
    bool can_merge =
        !fused.empty() && r.type == RespType::ALLREDUCE &&
        fused.back().type == RespType::ALLREDUCE &&
        fused.back().op == r.op && fused.back().dtype == r.dtype &&
        fused.back().prescale == r.prescale &&
        fused.back().postscale == r.postscale &&
        fused.back().joined_ranks == r.joined_ranks && r.error.empty() &&
        fused.back().error.empty() &&
        fused.back().NumBytes() + r.NumBytes() <= fusion_bytes_;
    if (can_merge) {
      auto& dst = fused.back();
      dst.tensor_names.insert(dst.tensor_names.end(), r.tensor_names.begin(),
                              r.tensor_names.end());
      dst.shapes.insert(dst.shapes.end(), r.shapes.begin(), r.shapes.end());
    } else {
      fused.push_back(r);
    }
  }
  return fused;
}

}  // namespace hvd
