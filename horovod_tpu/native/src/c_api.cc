// C API exported to the host language over ctypes.
//
// Plays the role of the reference's C exports (horovod/common/operations.cc:
// 650-788 horovod_init/... and 792-943 EnqueueTensorAllreduce/...) with a
// handle-based completion model like the PyTorch binding's HandleManager
// (horovod/torch/handle_manager.cc:21-55): enqueue returns a handle, the
// host polls/waits it; the actual collective execution is delegated back to
// the host through hvd_set_execute_callback.
#include <cstring>
#include <string>
#include <vector>

#include "message.h"
#include "runtime.h"

using hvd::Request;
using hvd::Runtime;

namespace {
void CopyErr(const std::string& s, char* buf, int len) {
  if (buf && len > 0) {
    std::strncpy(buf, s.c_str(), static_cast<size_t>(len) - 1);
    buf[len - 1] = '\0';
  }
}
}  // namespace

extern "C" {

int hvd_init(int rank, int size, const char* coordinator_addr,
             int coordinator_port, double connect_timeout_sec,
             double cycle_time_ms, long long fusion_threshold_bytes,
             int cache_capacity, double stall_warn_sec,
             double stall_shutdown_sec, const char* timeline_path,
             int timeline_mark_cycles, char* err_buf, int err_len) {
  hvd::RuntimeOptions opts;
  opts.rank = rank;
  opts.size = size;
  opts.coordinator_addr = coordinator_addr ? coordinator_addr : "127.0.0.1";
  opts.coordinator_port = coordinator_port;
  opts.connect_timeout_sec = connect_timeout_sec;
  opts.cycle_time_ms = cycle_time_ms;
  opts.fusion_threshold_bytes = fusion_threshold_bytes;
  opts.cache_capacity = cache_capacity;
  opts.stall_warn_sec = stall_warn_sec;
  opts.stall_shutdown_sec = stall_shutdown_sec;
  opts.timeline_path = timeline_path ? timeline_path : "";
  opts.timeline_mark_cycles = timeline_mark_cycles != 0;
  std::string err;
  if (!Runtime::Get().Init(opts, &err)) {
    CopyErr(err, err_buf, err_len);
    return -1;
  }
  return 0;
}

void hvd_shutdown() { Runtime::Get().Shutdown(); }

int hvd_is_initialized() { return Runtime::Get().initialized() ? 1 : 0; }

void hvd_set_execute_callback(hvd::ExecuteFn fn) {
  Runtime::Get().set_execute_fn(fn);
}

// type/op/dtype use the enum values in common.h; shape is an int64 array.
long long hvd_enqueue(const char* name, int type, int reduce_op, int dtype,
                      const long long* shape, int ndim, int root_rank,
                      double prescale, double postscale) {
  Request req;
  req.name = name ? name : "";
  req.type = static_cast<hvd::ReqType>(type);
  req.op = static_cast<hvd::ReduceOp>(reduce_op);
  req.dtype = static_cast<hvd::DType>(dtype);
  req.root_rank = root_rank;
  req.prescale = prescale;
  req.postscale = postscale;
  req.shape.assign(shape, shape + ndim);
  return Runtime::Get().Enqueue(req);
}

long long hvd_enqueue_join() { return Runtime::Get().EnqueueJoin(); }

int hvd_poll(long long handle) {
  return Runtime::Get().Poll(handle) ? 1 : 0;
}

// Blocks until the handle completes; returns the StatusCode (0 = OK) and
// fills err_buf with the failure reason when nonzero.
int hvd_wait(long long handle, char* err_buf, int err_len) {
  hvd::Status s = Runtime::Get().Wait(handle);
  if (!s.ok()) CopyErr(s.reason, err_buf, err_len);
  return static_cast<int>(s.code);
}

long long hvd_cycles() { return Runtime::Get().cycles(); }
int hvd_last_joined_rank() { return Runtime::Get().last_joined(); }
int hvd_joined_count() { return Runtime::Get().joined_count(); }
long long hvd_cache_hits() { return Runtime::Get().cache_hits(); }
long long hvd_cache_entries() { return Runtime::Get().cache_entries(); }
void hvd_set_fusion_bytes(long long b) { Runtime::Get().set_fusion_bytes(b); }
void hvd_set_cycle_us(long long us) { Runtime::Get().set_cycle_us(us); }
void hvd_set_cache_capacity(int n) { Runtime::Get().set_cache_capacity(n); }

}  // extern "C"
