#include "tensor_queue.h"

namespace hvd {

int64_t TensorQueue::Add(const Request& req) {
  std::lock_guard<std::mutex> lk(mu_);
  if (closed_) return -2;
  if (name_to_handle_.count(req.name)) return -1;  // duplicate-name race
  int64_t h = next_handle_++;
  name_to_handle_[req.name] = h;
  handles_[h] = HandleState{h, false, Status::OK()};
  pending_.push_back(req);
  return h;
}

std::vector<Request> TensorQueue::PopAll() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<Request> out(pending_.begin(), pending_.end());
  pending_.clear();
  return out;
}

void TensorQueue::Complete(const std::vector<std::string>& names,
                           const Status& status) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const auto& n : names) {
    auto it = name_to_handle_.find(n);
    if (it == name_to_handle_.end()) continue;
    auto hit = handles_.find(it->second);
    if (hit != handles_.end()) {
      hit->second.done = true;
      hit->second.status = status;
    }
    name_to_handle_.erase(it);
  }
  cv_.notify_all();
}

void TensorQueue::AbortAll(const Status& status) {
  std::lock_guard<std::mutex> lk(mu_);
  closed_ = true;
  pending_.clear();
  for (auto& kv : handles_) {
    if (!kv.second.done) {
      kv.second.done = true;
      kv.second.status = status;
    }
  }
  name_to_handle_.clear();
  cv_.notify_all();
}

void TensorQueue::Reopen() {
  std::lock_guard<std::mutex> lk(mu_);
  closed_ = false;
}

bool TensorQueue::Poll(int64_t handle) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() || it->second.done;
}

Status TensorQueue::Wait(int64_t handle) {
  std::unique_lock<std::mutex> lk(mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end())
    return Status::Error(StatusCode::INVALID, "unknown handle");
  cv_.wait(lk, [&] { return handles_[handle].done; });
  Status s = handles_[handle].status;
  handles_.erase(handle);
  return s;
}

size_t TensorQueue::PendingCount() {
  std::lock_guard<std::mutex> lk(mu_);
  return pending_.size();
}

}  // namespace hvd
