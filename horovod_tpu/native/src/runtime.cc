#include "runtime.h"

#include <chrono>

#include "logging.h"

namespace hvd {

Runtime& Runtime::Get() {
  static Runtime* instance = new Runtime();
  return *instance;
}

bool Runtime::Init(const RuntimeOptions& opts, std::string* err) {
  if (initialized_.load()) return true;
  opts_ = opts;
  LogRank() = opts.rank;
  HVD_LOG(Info) << "init: size=" << opts.size << " coordinator="
                << opts.coordinator_addr << ":" << opts.coordinator_port
                << " cycle_ms=" << opts.cycle_time_ms
                << " fusion_bytes=" << opts.fusion_threshold_bytes
                << " cache=" << opts.cache_capacity;
  if (!comm_.Init(opts.rank, opts.size, opts.coordinator_addr,
                  opts.coordinator_port, opts.connect_timeout_sec, err))
    return false;
  controller_.reset(new Controller(&comm_, opts.cache_capacity,
                                   opts.fusion_threshold_bytes,
                                   opts.stall_warn_sec,
                                   opts.stall_shutdown_sec));
  if (!opts.timeline_path.empty() && opts.rank == 0)
    timeline_.Initialize(opts.timeline_path);
  cycle_us_.store(static_cast<int64_t>(opts.cycle_time_ms * 1000.0));
  queue_.Reopen();
  stop_.store(false);
  shutdown_requested_.store(false);
  bg_thread_ = std::thread([this] { BackgroundLoop(); });
  initialized_.store(true);
  return true;
}

void Runtime::Shutdown() {
  if (!initialized_.load()) return;
  // Ride the shutdown flag through one more coordination cycle so every
  // rank agrees to stop (reference: RequestList::shutdown honored at
  // controller.cc:247-250), then join the background thread.
  shutdown_requested_.store(true);
  if (bg_thread_.joinable()) bg_thread_.join();
  HVD_LOG(Info) << "shutdown after " << cycles_.load() << " cycles";
  queue_.AbortAll(Status::Error(StatusCode::SHUTDOWN, "horovod_tpu shut down"));
  timeline_.Shutdown();
  comm_.Shutdown();
  controller_.reset();
  initialized_.store(false);
}

int64_t Runtime::Enqueue(const Request& req) {
  // Reject after the background loop has exited (remote shutdown or
  // coordination failure): nothing will ever pop the queue, so accepting
  // the request would hang the caller forever.
  if (!initialized_.load() || stop_.load()) return -2;
  int64_t h = queue_.Add(req);
  if (h >= 0) timeline_.Begin(req.name, Timeline::kNegotiate);
  return h;
}

int64_t Runtime::EnqueueJoin() {
  Request req;
  req.type = ReqType::JOIN;
  req.name = "join";
  req.rank = opts_.rank;
  return Enqueue(req);
}

void Runtime::BackgroundLoop() {
  using clock = std::chrono::steady_clock;
  while (!stop_.load()) {
    auto start = clock::now();
    auto cycle = std::chrono::microseconds(cycle_us_.load());
    if (!RunLoopOnce()) break;
    cycles_.fetch_add(1);
    if (opts_.timeline_mark_cycles) timeline_.MarkCycle();
    auto elapsed = clock::now() - start;
    if (elapsed < cycle) std::this_thread::sleep_for(cycle - elapsed);
  }
  stop_.store(true);
}

bool Runtime::RunLoopOnce() {
  int new_cap = pending_cache_capacity_.exchange(-1);
  if (new_cap >= 0) controller_->set_cache_capacity(new_cap);

  std::vector<Request> pending = queue_.PopAll();
  for (const auto& r : pending)
    if (r.type == ReqType::JOIN) local_join_ = true;

  bool want_shutdown = shutdown_requested_.load();
  ResponseList out;
  std::string err;
  if (!controller_->ComputeResponseList(std::move(pending), local_join_,
                                        want_shutdown, &out, &err)) {
    HVD_LOG(Error) << "coordination failed: " << err;
    queue_.AbortAll(Status::Error(StatusCode::ABORTED,
                                  "coordination failed: " + err));
    return false;
  }
  for (const auto& resp : out.responses) {
    if (HVD_LOG_IS_ON(kDebug) && !resp.tensor_names.empty()) {
      HVD_LOG(Debug) << "dispatch " << resp.tensor_names.size()
                     << " tensor(s), first=" << resp.tensor_names[0];
    }
    Dispatch(resp);
  }
  if (out.shutdown) {
    queue_.AbortAll(
        Status::Error(StatusCode::SHUTDOWN, "shutdown requested"));
    return false;
  }
  return true;
}

void Runtime::Dispatch(const Response& resp) {
  for (const auto& n : resp.tensor_names)
    timeline_.End(n, Timeline::kNegotiate);

  switch (resp.type) {
    case RespType::ERROR:
      queue_.Complete(resp.tensor_names,
                      Status::Error(StatusCode::INVALID, resp.error));
      return;
    case RespType::JOIN:
      local_join_ = false;
      // The coordinator stamps the last-joined rank into root_rank; park
      // it for hvd_last_joined_rank() BEFORE releasing the waiter.
      last_joined_.store(resp.root_rank);
      queue_.Complete({"join"}, Status::OK());
      return;
    case RespType::BARRIER:
      queue_.Complete(resp.tensor_names, Status::OK());
      return;
    default:
      break;
  }

  for (const auto& n : resp.tensor_names)
    timeline_.Begin(n, Timeline::kExecute);
  Status status = Status::OK();
  if (execute_fn_ != nullptr) {
    Writer w;
    resp.Serialize(w);
    int rc = execute_fn_(w.buf.data(), static_cast<int>(w.buf.size()));
    if (rc != 0)
      status = Status::Error(static_cast<StatusCode>(rc),
                             "executor reported failure");
  } else {
    status = Status::Error(StatusCode::INVALID, "no executor registered");
  }
  for (const auto& n : resp.tensor_names)
    timeline_.End(n, Timeline::kExecute);
  queue_.Complete(resp.tensor_names, status);
}

}  // namespace hvd
