// LRU response cache — the steady-state negotiation bypass.
//
// Re-implements the reference's ResponseCache + CacheCoordinator
// (horovod/common/response_cache.{h,cc}; fast path wired at
// controller.cc:125-193): after a tensor has been negotiated once, later
// cycles only need to agree that every rank re-submitted the *same* tensor,
// which a bit-vector AND establishes in one round instead of a full
// gather+construct+bcast.  Entries are invalidated when a resubmission's
// metadata (shape/dtype/op) changes.
#ifndef HVD_NATIVE_RESPONSE_CACHE_H
#define HVD_NATIVE_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "message.h"

namespace hvd {

class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  static constexpr size_t kNotCached = SIZE_MAX;

  // Bit position for a request if cached AND metadata matches; kNotCached
  // otherwise (a metadata mismatch also evicts the stale entry, mirroring
  // the reference's invalidation on changed tensor params).
  size_t Lookup(const Request& req);

  // Insert a single-tensor response produced by a full negotiation.
  void Put(const Request& req, const Response& resp);

  const Response& Get(size_t bit) const { return entries_[bit].response; }
  const Request& GetRequest(size_t bit) const { return entries_[bit].request; }

  // Refresh LRU recency for a fast-path hit.  Every rank must call this
  // for the same bits in the same (globally agreed) order to keep
  // eviction in lockstep.
  void Touch(size_t bit);

  void Erase(const std::string& name);
  void Clear();

  size_t NumEntries() const { return entries_.size(); }
  size_t capacity() const { return capacity_; }
  // Resize drops all entries: bit positions are only meaningful while every
  // rank's cache evolves in lockstep, so a capacity change restarts from
  // empty (entries renegotiate through the slow path once).  Unchanged
  // capacity is a no-op — the autotuner re-sends its winning settings at
  // freeze time, which must not wipe the warm cache.
  void set_capacity(size_t n) {
    if (n == capacity_) return;
    Clear();
    capacity_ = n;
  }
  int64_t hits() const { return hits_; }
  void CountHit() { ++hits_; }

 private:
  struct Entry {
    Request request;
    Response response;
    std::list<size_t>::iterator lru_it;  // O(1) splice on Touch/Put
  };
  bool Matches(const Request& a, const Request& b) const;

  size_t capacity_;
  std::vector<Entry> entries_;                       // bit -> entry
  std::unordered_map<std::string, size_t> by_name_;  // name -> bit
  std::list<size_t> lru_;                            // front = most recent
  int64_t hits_ = 0;
};

}  // namespace hvd

#endif  // HVD_NATIVE_RESPONSE_CACHE_H
