// Leveled stderr logging for the native runtime, controlled by
// HOROVOD_LOG_LEVEL (trace|debug|info|warning|error|fatal) — the TPU
// re-design of the reference's logger (horovod/common/logging.{h,cc}):
// same env contract and rank-tagged lines, implemented as a single
// header with an iostream-style macro.
#ifndef HVD_LOGGING_H_
#define HVD_LOGGING_H_

#include <cctype>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <sstream>
#include <string>

namespace hvd {

enum class LogLevel : int {
  kTrace = 0,
  kDebug = 1,
  kInfo = 2,
  kWarning = 3,
  kError = 4,
  kFatal = 5,
};

inline LogLevel ParseLogLevelEnv() {
  const char* raw = std::getenv("HOROVOD_LOG_LEVEL");
  if (raw == nullptr || raw[0] == '\0') return LogLevel::kWarning;
  std::string v(raw);
  for (auto& c : v) c = static_cast<char>(std::tolower(c));
  if (v == "trace") return LogLevel::kTrace;
  if (v == "debug") return LogLevel::kDebug;
  if (v == "info") return LogLevel::kInfo;
  if (v == "warning" || v == "warn") return LogLevel::kWarning;
  if (v == "error") return LogLevel::kError;
  if (v == "fatal") return LogLevel::kFatal;
  return LogLevel::kWarning;
}

inline LogLevel MinLogLevel() {
  static LogLevel lvl = ParseLogLevelEnv();
  return lvl;
}

// Rank tag for log lines; set once at runtime init.
inline int& LogRank() {
  static int rank = -1;
  return rank;
}

class LogMessage {
 public:
  LogMessage(LogLevel level, const char* name) : level_(level) {
    stream_ << "[hvd_native";
    if (LogRank() >= 0) stream_ << " rank " << LogRank();
    stream_ << " " << name << "] ";
  }
  ~LogMessage() {
    stream_ << "\n";
    std::cerr << stream_.str();
    std::cerr.flush();
    if (level_ == LogLevel::kFatal) std::abort();
  }
  std::ostringstream& stream() { return stream_; }

 private:
  LogLevel level_;
  std::ostringstream stream_;
};

}  // namespace hvd

#define HVD_LOG_IS_ON(lvl) (::hvd::LogLevel::lvl >= ::hvd::MinLogLevel())
#define HVD_LOG(lvl)                         \
  if (!HVD_LOG_IS_ON(k##lvl)) {              \
  } else                                     \
    ::hvd::LogMessage(::hvd::LogLevel::k##lvl, #lvl).stream()

#endif  // HVD_LOGGING_H_
