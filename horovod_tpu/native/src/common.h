// Core shared types for the horovod_tpu native runtime.
//
// TPU-native re-design of the reference's common layer
// (reference: horovod/common/common.h).  The native runtime is the CONTROL
// plane only: it negotiates which tensors are globally ready, plans fusion,
// caches responses, detects stalls and writes the timeline.  Tensor bytes
// never enter this library — on TPU the data plane is XLA/PJRT and the
// execution of a negotiated (possibly fused) collective is delegated to the
// host language through a callback.
#ifndef HVD_NATIVE_COMMON_H
#define HVD_NATIVE_COMMON_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

// Request/response types (reference: horovod/common/message.h:49-60).
enum class ReqType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ALLTOALL = 4,
  BARRIER = 5,
  REDUCESCATTER = 6,
};

enum class RespType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ALLTOALL = 4,
  BARRIER = 5,
  ERROR = 6,
  REDUCESCATTER = 7,
};

// Reduce ops (reference exposes Average/Sum/Adasum; Min/Max/Product are
// TPU-side extensions mirrored from the Python layer).
enum class ReduceOp : uint8_t {
  AVERAGE = 0,
  SUM = 1,
  ADASUM = 2,
  MIN = 3,
  MAX = 4,
  PRODUCT = 5,
};

// Dtypes, numpy-aligned (reference: DataType in common/message.h).
enum class DType : uint8_t {
  UINT8 = 0,
  INT8 = 1,
  UINT16 = 2,
  INT16 = 3,
  INT32 = 4,
  INT64 = 5,
  FLOAT16 = 6,
  FLOAT32 = 7,
  FLOAT64 = 8,
  BOOL = 9,
  BFLOAT16 = 10,
};

inline int64_t DTypeSize(DType d) {
  switch (d) {
    case DType::UINT8:
    case DType::INT8:
    case DType::BOOL:
      return 1;
    case DType::UINT16:
    case DType::INT16:
    case DType::FLOAT16:
    case DType::BFLOAT16:
      return 2;
    case DType::INT32:
    case DType::FLOAT32:
      return 4;
    case DType::INT64:
    case DType::FLOAT64:
      return 8;
  }
  return 1;
}

// Completion status delivered to a waiting handle (reference:
// StatusType in common/common.h:143-151).
enum class StatusCode : uint8_t {
  OK = 0,
  ABORTED = 1,
  INVALID = 2,       // coordinator detected rank mismatch (shape/dtype/op)
  SHUTDOWN = 3,      // runtime shut down before completion
  DUPLICATE = 4,     // tensor name already pending (double-submission race)
};

struct Status {
  StatusCode code = StatusCode::OK;
  std::string reason;
  static Status OK() { return {}; }
  static Status Error(StatusCode c, std::string r) { return {c, std::move(r)}; }
  bool ok() const { return code == StatusCode::OK; }
};

}  // namespace hvd

#endif  // HVD_NATIVE_COMMON_H
