// Coordination protocol messages.
//
// Mirrors the reference's Request/Response pair (horovod/common/message.h:
// 47-100 Request, 132-192 Response, lists at 102-125/194-217) with the same
// roles: a Request travels worker -> coordinator announcing "this tensor is
// ready on my rank"; a Response travels coordinator -> workers announcing
// "this (fused set of) tensor(s) is ready everywhere — execute it now".
// Serialization is the hand-rolled wire format in wire.h instead of
// flatbuffers.
#ifndef HVD_NATIVE_MESSAGE_H
#define HVD_NATIVE_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"
#include "wire.h"

namespace hvd {

struct Request {
  int32_t rank = 0;
  ReqType type = ReqType::ALLREDUCE;
  ReduceOp op = ReduceOp::AVERAGE;
  DType dtype = DType::FLOAT32;
  std::string name;
  int32_t root_rank = 0;  // broadcast only
  std::vector<int64_t> shape;
  double prescale = 1.0;
  double postscale = 1.0;

  int64_t NumBytes() const {
    int64_t n = DTypeSize(dtype);
    for (int64_t d : shape) n *= d;
    return n;
  }

  void Serialize(Writer& w) const;
  static Request Parse(Reader& r);
};

struct RequestList {
  int32_t rank = 0;
  bool shutdown = false;  // rides the coordination message, reference
                          // message.h:112-114
  std::vector<Request> requests;

  std::vector<uint8_t> Serialize() const;
  static RequestList Parse(const std::vector<uint8_t>& buf);
};

struct Response {
  RespType type = RespType::ALLREDUCE;
  ReduceOp op = ReduceOp::AVERAGE;
  DType dtype = DType::FLOAT32;
  // All tensors fused into this response (>=1; >1 only for ALLREDUCE, like
  // the reference's FuseResponses, controller.cc:631-752).
  std::vector<std::string> tensor_names;
  std::vector<std::vector<int64_t>> shapes;
  int32_t root_rank = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  std::string error;             // RespType::ERROR only
  // Ranks that have Joined: the executor substitutes zeros for them
  // (reference: global_state.h:104-107 / controller.cc:780-803).
  std::vector<int32_t> joined_ranks;

  int64_t NumBytes() const {
    int64_t total = 0;
    for (const auto& s : shapes) {
      int64_t n = DTypeSize(dtype);
      for (int64_t d : s) n *= d;
      total += n;
    }
    return total;
  }

  void Serialize(Writer& w) const;
  static Response Parse(Reader& r);
};

struct ResponseList {
  bool shutdown = false;
  std::vector<Response> responses;

  std::vector<uint8_t> Serialize() const;
  static ResponseList Parse(const std::vector<uint8_t>& buf);
};

}  // namespace hvd

#endif  // HVD_NATIVE_MESSAGE_H
