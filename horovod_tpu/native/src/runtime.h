// Global runtime state + the background negotiation thread.
//
// Re-design of the reference's HorovodGlobalState + BackgroundThreadLoop /
// RunLoopOnce (horovod/common/global_state.h:42-122,
// common/operations.cc:333-537).  The loop's job here is pure control:
// pop pending requests, negotiate global readiness through the controller,
// then hand each (fused) response to the EXECUTOR CALLBACK registered by
// the host language, which runs the actual collective as an XLA program on
// the TPU data plane.  The reference's ready-event polling and fusion-buffer
// memcpys have no equivalent — XLA data dependencies and compiler fusion
// replace them (SURVEY.md §7).
#ifndef HVD_NATIVE_RUNTIME_H
#define HVD_NATIVE_RUNTIME_H

#include <atomic>
#include <string>
#include <thread>

#include "comm.h"
#include "controller.h"
#include "tensor_queue.h"
#include "timeline.h"

namespace hvd {

// Executor callback: receives a serialized Response (wire.h format),
// performs the collective, returns a StatusCode as int.
typedef int (*ExecuteFn)(const uint8_t* response, int len);

struct RuntimeOptions {
  int rank = 0;
  int size = 1;
  std::string coordinator_addr = "127.0.0.1";
  int coordinator_port = 9374;
  double connect_timeout_sec = 60.0;
  double cycle_time_ms = 1.0;  // reference default 5ms (operations.cc:416);
                               // control-plane-only cycles can run tighter
  int64_t fusion_threshold_bytes = 64 << 20;  // reference operations.cc:408
  int cache_capacity = 1024;                  // reference global_state.h:88
  double stall_warn_sec = 60.0;
  double stall_shutdown_sec = 0.0;
  std::string timeline_path;  // empty = disabled; rank 0 only
  bool timeline_mark_cycles = false;
};

class Runtime {
 public:
  static Runtime& Get();

  bool Init(const RuntimeOptions& opts, std::string* err);
  void Shutdown();
  bool initialized() const { return initialized_.load(); }

  void set_execute_fn(ExecuteFn fn) { execute_fn_ = fn; }

  int64_t Enqueue(const Request& req);
  int64_t EnqueueJoin();
  bool Poll(int64_t handle) { return queue_.Poll(handle); }
  Status Wait(int64_t handle) { return queue_.Wait(handle); }

  int64_t cycles() const { return cycles_.load(); }
  // Rank that joined LAST in the most recent completed join round
  // (reference DoJoin output tensor); -1 before any round completes.
  int last_joined() const { return last_joined_.load(); }
  // Coordinator-observed currently-joined rank count (0 on workers).
  int joined_count() { return controller_ ? controller_->joined_count() : 0; }
  int64_t cache_hits() { return controller_ ? controller_->cache_hits() : 0; }
  int64_t cache_entries() {
    return controller_ ? static_cast<int64_t>(controller_->cache_entries()) : 0;
  }
  void set_fusion_bytes(int64_t b) {
    if (controller_) controller_->set_fusion_bytes(b);
  }
  // Autotuner knobs (reference ParameterManager application points).
  // cycle time takes effect on the next loop iteration; the cache
  // capacity change is applied by the background thread between cycles
  // (the controller is bg-thread-owned).
  void set_cycle_us(int64_t us) { cycle_us_.store(us); }
  void set_cache_capacity(int n) { pending_cache_capacity_.store(n); }

 private:
  Runtime() = default;
  void BackgroundLoop();
  bool RunLoopOnce();
  void Dispatch(const Response& resp);

  RuntimeOptions opts_;
  SocketComm comm_;
  std::unique_ptr<Controller> controller_;
  TensorQueue queue_;
  Timeline timeline_;
  ExecuteFn execute_fn_ = nullptr;

  std::thread bg_thread_;
  std::atomic<bool> initialized_{false};
  std::atomic<bool> stop_{false};
  std::atomic<bool> shutdown_requested_{false};
  std::atomic<int64_t> cycles_{0};
  std::atomic<int64_t> cycle_us_{1000};
  std::atomic<int> pending_cache_capacity_{-1};
  std::atomic<int> last_joined_{-1};
  bool local_join_ = false;  // background-thread-only state
};

}  // namespace hvd

#endif  // HVD_NATIVE_RUNTIME_H
