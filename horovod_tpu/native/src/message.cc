#include "message.h"

namespace hvd {

void Request::Serialize(Writer& w) const {
  w.i32(rank);
  w.u8(static_cast<uint8_t>(type));
  w.u8(static_cast<uint8_t>(op));
  w.u8(static_cast<uint8_t>(dtype));
  w.str(name);
  w.i32(root_rank);
  w.shape(shape);
  w.f64(prescale);
  w.f64(postscale);
}

Request Request::Parse(Reader& r) {
  Request q;
  q.rank = r.i32();
  q.type = static_cast<ReqType>(r.u8());
  q.op = static_cast<ReduceOp>(r.u8());
  q.dtype = static_cast<DType>(r.u8());
  q.name = r.str();
  q.root_rank = r.i32();
  q.shape = r.shape();
  q.prescale = r.f64();
  q.postscale = r.f64();
  return q;
}

std::vector<uint8_t> RequestList::Serialize() const {
  Writer w;
  w.i32(rank);
  w.u8(shutdown ? 1 : 0);
  w.u32(static_cast<uint32_t>(requests.size()));
  for (const auto& q : requests) q.Serialize(w);
  return std::move(w.buf);
}

RequestList RequestList::Parse(const std::vector<uint8_t>& buf) {
  Reader r(buf);
  RequestList l;
  l.rank = r.i32();
  l.shutdown = r.u8() != 0;
  uint32_t n = r.u32();
  l.requests.reserve(n);
  for (uint32_t i = 0; i < n; ++i) l.requests.push_back(Request::Parse(r));
  return l;
}

void Response::Serialize(Writer& w) const {
  w.u8(static_cast<uint8_t>(type));
  w.u8(static_cast<uint8_t>(op));
  w.u8(static_cast<uint8_t>(dtype));
  w.u32(static_cast<uint32_t>(tensor_names.size()));
  for (size_t i = 0; i < tensor_names.size(); ++i) {
    w.str(tensor_names[i]);
    w.shape(i < shapes.size() ? shapes[i] : std::vector<int64_t>{});
  }
  w.i32(root_rank);
  w.f64(prescale);
  w.f64(postscale);
  w.str(error);
  w.u32(static_cast<uint32_t>(joined_ranks.size()));
  for (int32_t jr : joined_ranks) w.i32(jr);
}

Response Response::Parse(Reader& r) {
  Response p;
  p.type = static_cast<RespType>(r.u8());
  p.op = static_cast<ReduceOp>(r.u8());
  p.dtype = static_cast<DType>(r.u8());
  uint32_t n = r.u32();
  p.tensor_names.reserve(n);
  p.shapes.reserve(n);
  for (uint32_t i = 0; i < n; ++i) {
    p.tensor_names.push_back(r.str());
    p.shapes.push_back(r.shape());
  }
  p.root_rank = r.i32();
  p.prescale = r.f64();
  p.postscale = r.f64();
  p.error = r.str();
  uint32_t j = r.u32();
  p.joined_ranks.reserve(j);
  for (uint32_t i = 0; i < j; ++i) p.joined_ranks.push_back(r.i32());
  return p;
}

std::vector<uint8_t> ResponseList::Serialize() const {
  Writer w;
  w.u8(shutdown ? 1 : 0);
  w.u32(static_cast<uint32_t>(responses.size()));
  for (const auto& p : responses) p.Serialize(w);
  return std::move(w.buf);
}

ResponseList ResponseList::Parse(const std::vector<uint8_t>& buf) {
  Reader r(buf);
  ResponseList l;
  l.shutdown = r.u8() != 0;
  uint32_t n = r.u32();
  l.responses.reserve(n);
  for (uint32_t i = 0; i < n; ++i) l.responses.push_back(Response::Parse(r));
  return l;
}

}  // namespace hvd
