// Coordinator-side stall detection.
//
// Re-implements the reference's StallInspector
// (horovod/common/stall_inspector.{h,cc}; wired into the controller at
// controller.cc:112-121): if some ranks submitted a tensor and others have
// not after `warn_sec`, log which ranks are missing; after `shutdown_sec`
// (if set) request a global abort — the semantic failure detector for
// "rank 3 never called allreduce on tensor X" hangs.
#ifndef HVD_NATIVE_STALL_INSPECTOR_H
#define HVD_NATIVE_STALL_INSPECTOR_H

#include <chrono>
#include <set>
#include <string>
#include <unordered_map>

namespace hvd {

class StallInspector {
 public:
  StallInspector(double warn_sec, double shutdown_sec)
      : warn_sec_(warn_sec), shutdown_sec_(shutdown_sec) {}

  void RecordRank(const std::string& tensor, int rank);
  void RemoveTensor(const std::string& tensor);

  // Scan for stalls; logs warnings to stderr (rank-0 process).  Returns
  // true if any tensor exceeded the shutdown bound.
  bool CheckForStalls(int world_size);

  double warn_sec() const { return warn_sec_; }

 private:
  struct Pending {
    std::chrono::steady_clock::time_point first_seen;
    std::set<int> ranks;
    bool warned = false;
  };
  double warn_sec_;
  double shutdown_sec_;
  std::unordered_map<std::string, Pending> pending_;
};

}  // namespace hvd

#endif  // HVD_NATIVE_STALL_INSPECTOR_H
