// Compact little-endian binary (de)serializer — the wire format used both
// between ranks (over TCP) and across the C boundary to the host language.
//
// Plays the role flatbuffers plays in the reference
// (horovod/common/wire/message.fbs) with a deliberately simpler scheme:
// fixed-width little-endian scalars, length-prefixed strings/vectors.  The
// control-plane messages are tiny (tensor names + shapes), so zero-copy
// access buys nothing here and a dependency-free format keeps the native
// library self-contained.
#ifndef HVD_NATIVE_WIRE_H
#define HVD_NATIVE_WIRE_H

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace hvd {

class Writer {
 public:
  std::vector<uint8_t> buf;

  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) { raw(&v, 4); }
  void i32(int32_t v) { raw(&v, 4); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    raw(s.data(), s.size());
  }
  void shape(const std::vector<int64_t>& dims) {
    u32(static_cast<uint32_t>(dims.size()));
    for (int64_t d : dims) i64(d);
  }

 private:
  void raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : data_(data), len_(len) {}
  explicit Reader(const std::vector<uint8_t>& v) : data_(v.data()), len_(v.size()) {}

  uint8_t u8() { return *take(1); }
  uint32_t u32() { uint32_t v; std::memcpy(&v, take(4), 4); return v; }
  int32_t i32() { int32_t v; std::memcpy(&v, take(4), 4); return v; }
  int64_t i64() { int64_t v; std::memcpy(&v, take(8), 8); return v; }
  double f64() { double v; std::memcpy(&v, take(8), 8); return v; }
  std::string str() {
    uint32_t n = u32();
    const uint8_t* p = take(n);
    return std::string(reinterpret_cast<const char*>(p), n);
  }
  std::vector<int64_t> shape() {
    uint32_t n = u32();
    std::vector<int64_t> dims(n);
    for (uint32_t i = 0; i < n; ++i) dims[i] = i64();
    return dims;
  }
  bool done() const { return pos_ == len_; }

 private:
  const uint8_t* take(size_t n) {
    if (pos_ + n > len_) throw std::runtime_error("wire: truncated message");
    const uint8_t* p = data_ + pos_;
    pos_ += n;
    return p;
  }
  const uint8_t* data_;
  size_t len_;
  size_t pos_ = 0;
};

}  // namespace hvd

#endif  // HVD_NATIVE_WIRE_H
