#include "timeline.h"

#include <chrono>

namespace hvd {

bool Timeline::Initialize(const std::string& path) {
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) return false;
  std::fputs("[\n", file_);
  stop_ = false;
  writer_ = std::thread([this] { WriterLoop(); });
  initialized_ = true;
  return true;
}

void Timeline::Shutdown() {
  if (!initialized_) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  std::fputs("\n]\n", file_);
  std::fclose(file_);
  file_ = nullptr;
  initialized_ = false;
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Timeline::Push(Event e) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(std::move(e));
  }
  cv_.notify_one();
}

void Timeline::Begin(const std::string& tensor, const char* activity) {
  if (!initialized_) return;
  Push(Event{'B', tensor, activity, NowUs()});
}

void Timeline::End(const std::string& tensor, const char* activity) {
  if (!initialized_) return;
  Push(Event{'E', tensor, activity, NowUs()});
}

void Timeline::MarkCycle() {
  if (!initialized_) return;
  Push(Event{'i', "", "CYCLE", NowUs()});
}

void Timeline::WriterLoop() {
  for (;;) {
    std::deque<Event> batch;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [&] { return stop_ || !queue_.empty(); });
      batch.swap(queue_);
      if (batch.empty() && stop_) return;
    }
    for (const auto& e : batch) {
      // Lane per tensor (chrome tracing "tid"), named on first sight via a
      // metadata record — the same layout the reference produces.
      int tid = 0;
      if (!e.tensor.empty()) {
        auto it = tensor_tids_.find(e.tensor);
        if (it == tensor_tids_.end()) {
          tid = static_cast<int>(tensor_tids_.size()) + 1;
          tensor_tids_[e.tensor] = tid;
          std::fprintf(file_,
                       "%s{\"name\": \"thread_name\", \"ph\": \"M\", \"pid\": 0,"
                       " \"tid\": %d, \"args\": {\"name\": \"%s\"}}",
                       first_record_ ? "" : ",\n", tid, e.tensor.c_str());
          first_record_ = false;
        } else {
          tid = it->second;
        }
      }
      if (e.ph == 'i') {
        std::fprintf(file_,
                     "%s{\"name\": \"%s\", \"ph\": \"i\", \"s\": \"g\","
                     " \"ts\": %lld, \"pid\": 0, \"tid\": 0}",
                     first_record_ ? "" : ",\n", e.activity.c_str(),
                     static_cast<long long>(e.ts_us));
      } else {
        std::fprintf(file_,
                     "%s{\"name\": \"%s\", \"ph\": \"%c\", \"ts\": %lld,"
                     " \"pid\": 0, \"tid\": %d}",
                     first_record_ ? "" : ",\n", e.activity.c_str(), e.ph,
                     static_cast<long long>(e.ts_us), tid);
      }
      first_record_ = false;
    }
    std::fflush(file_);
  }
}

}  // namespace hvd
