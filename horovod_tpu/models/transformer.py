"""Flagship model: decoder-only Transformer LM, written TPU-first in pure
JAX with explicit GSPMD sharding rules for dp / fsdp / tp / sp / pp / ep.

The reference framework carries no models of its own (its benchmarks import
tf.keras/torchvision models); this module is the flagship for OUR benchmark
and multi-parallelism story: pick a mesh (:mod:`horovod_tpu.parallel.
meshes`), annotate parameters and activations with the specs from
:func:`param_specs` / :func:`batch_specs`, jit, and XLA inserts all
collectives (psum for dp/fsdp grads, all-gathers for tp, collective-permute
for pp-sharded layer scan) over ICI.

Design notes (TPU):
* bfloat16 activations/compute, float32 parameters and softmax/logsumexp.
* Layers are stacked on a leading axis and scanned with ``lax.scan`` —
  constant compile time in depth; the stacked axis shards over ``pp``.
* RMSNorm + SwiGLU + rotary positions; causal mask built from iota (no
  materialized (S,S) python loop, static shapes throughout).
* Optional mixture-of-experts MLP (``n_experts > 1``): experts stacked on
  an axis sharded over ``ep``; top-1 routing computed densely (exact, and
  compiles to einsums the MXU likes at benchmark scales).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 1024
    n_experts: int = 0  # 0/1 = dense MLP
    # MoE dispatch: "switch" = sparse capacity-factor token dispatch
    # (horovod_tpu.ops.moe — each token computes ONE expert; under
    # shard_map with moe_axis bound, one all_to_all each way and only
    # RESIDENT experts compute, so the ep axis shards compute).  "dense"
    # = evaluate every expert and combine with the routing one-hot (the
    # exact oracle; dropless, O(E) FLOPs — right for tiny E and for
    # decoding).
    moe_impl: str = "switch"
    # Per-expert capacity multiplier for switch dispatch: each expert
    # accepts ceil(cf * T / E) tokens per step; overflow tokens pass
    # through the residual only (standard Switch training behavior).
    capacity_factor: float = 2.0
    # Switch dispatch mechanism: "sort" (argsort + gathers — the TPU
    # fast path) or "cumsum" (one-hot running-position oracle).  Both
    # produce identical outputs, gradients, and drop patterns.
    moe_dispatch: str = "sort"
    # Mesh axis for expert parallelism when running under shard_map
    # (None = single-device sparse dispatch; the GSPMD/jit path shards
    # the expert axis via param_specs instead).
    moe_axis: Optional[str] = None
    # Switch load-balancing auxiliary loss coefficient (Switch paper's
    # alpha, typically 1e-2).  When > 0, loss_fn adds
    # ``coeff * sum_over_layers(E * sum_e frac_e * pbar_e)`` so the
    # router is pushed toward uniform expert load — without it a learned
    # router under tight capacity route-collapses (all tokens -> one
    # expert, capacity drops eat the batch).  0 disables (the oracle /
    # equivalence-test setting).
    moe_aux_coeff: float = 0.0
    # Grouped-query attention: K/V heads (0 = n_heads, i.e. MHA).  With
    # ring attention the rotating K/V shards shrink by n_heads/n_kv_heads
    # — the long-context ICI-bandwidth lever (beyond-reference extension).
    n_kv_heads: int = 0
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # "reference" = O(S^2) XLA softmax-attention; "flash" = the Pallas
    # fused kernel (horovod_tpu.ops.attention); "ring" = sequence-parallel
    # ring attention over the ``sp`` mesh axis (requires running under
    # shard_map with sp bound and sequence sharded over it; chunks run the
    # flash kernel).  "ring_zigzag" = ring with the zigzag chunk layout
    # (device i holds global chunks (i, 2P-1-i)): balances the causal
    # work so no device idles — feed batches permuted by
    # ops.attention.zigzag_perm.  "ring_reference" keeps the masked-XLA
    # chunk math — the second oracle and the benchmarking control.
    attention_impl: str = "reference"
    # Rematerialize each layer in the backward pass (jax.checkpoint):
    # activations are recomputed instead of stored, trading ~1/3 more
    # FLOPs for O(n_layers) less HBM — the standard long-context /
    # big-batch lever on TPU where HBM, not MXU, binds.
    remat: bool = False
    # Remat granularity: "full" recomputes everything (max memory
    # savings); "dots" keeps matmul outputs resident and recomputes only
    # the cheap elementwise work (jax checkpoint_dots policy) — much less
    # recompute when HBM still fits the dot outputs.
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % kv == 0, (self.n_heads, kv)
        return kv


# --- parameters --------------------------------------------------------------


def init_params(rng, cfg: TransformerConfig) -> Dict:
    keys = jax.random.split(rng, 10)
    D, H, Dh, F, L, V = (
        cfg.d_model,
        cfg.n_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.vocab_size,
    )
    E = max(cfg.n_experts, 0)

    def norm_init(k, shape, scale):
        return (jax.random.normal(k, shape) * scale).astype(jnp.float32)

    s_d = 1.0 / np.sqrt(D)
    s_f = 1.0 / np.sqrt(F)
    layers = {
        "ln1": jnp.ones((L, D), jnp.float32),
        "ln2": jnp.ones((L, D), jnp.float32),
        "wq": norm_init(keys[0], (L, D, H, Dh), s_d),
        "wk": norm_init(keys[1], (L, D, cfg.kv_heads, Dh), s_d),
        "wv": norm_init(keys[2], (L, D, cfg.kv_heads, Dh), s_d),
        "wo": norm_init(keys[3], (L, H, Dh, D), s_d),
    }
    if E > 1:
        layers.update(
            router=norm_init(keys[4], (L, D, E), s_d),
            w_gate=norm_init(keys[5], (L, E, D, F), s_d),
            w_up=norm_init(keys[6], (L, E, D, F), s_d),
            w_down=norm_init(keys[7], (L, E, F, D), s_f),
        )
    else:
        layers.update(
            w_gate=norm_init(keys[5], (L, D, F), s_d),
            w_up=norm_init(keys[6], (L, D, F), s_d),
            w_down=norm_init(keys[7], (L, F, D), s_f),
        )
    return {
        "embed": norm_init(keys[8], (V, D), 1.0),
        "layers": layers,
        "ln_f": jnp.ones((D,), jnp.float32),
        "head": norm_init(keys[9], (D, V), s_d),
    }


def param_specs(cfg: TransformerConfig) -> Dict:
    """GSPMD sharding rules.  Axes: tp shards heads/ffn/vocab, fsdp shards
    the d_model dim of weights (ZeRO-3 style), pp shards the stacked layer
    axis, ep shards experts."""
    layers = {
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "wq": P("pp", "fsdp", "tp", None),
        "wk": P("pp", "fsdp", "tp", None),
        "wv": P("pp", "fsdp", "tp", None),
        "wo": P("pp", "tp", None, "fsdp"),
    }
    if cfg.n_experts > 1:
        layers.update(
            router=P("pp", None, None),
            w_gate=P("pp", "ep", "fsdp", "tp"),
            w_up=P("pp", "ep", "fsdp", "tp"),
            w_down=P("pp", "ep", "tp", "fsdp"),
        )
    else:
        layers.update(
            w_gate=P("pp", "fsdp", "tp"),
            w_up=P("pp", "fsdp", "tp"),
            w_down=P("pp", "tp", "fsdp"),
        )
    return {
        "embed": P("tp", "fsdp"),
        "layers": layers,
        "ln_f": P(None),
        "head": P("fsdp", "tp"),
    }


def batch_specs() -> Dict:
    """Activations: batch over dp(+fsdp), sequence over sp."""
    return {"tokens": P(("dp", "fsdp"), "sp"), "targets": P(("dp", "fsdp"), "sp")}


# --- forward -----------------------------------------------------------------


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    out = x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)
    return (out * scale).astype(x.dtype)


def _rope(q, k, theta: float, pos_offset=0, positions=None):
    """Rotary position embedding over the head dim (applied to q and k).
    Shapes: (B, S, H, Dh).  ``pos_offset`` shifts positions when the
    sequence axis is sharded (ring attention: shard r starts at
    r*S_local); ``positions`` overrides with EXPLICIT global positions —
    ``(S,)`` per sequence row (zigzag layout: this shard's rows are
    non-contiguous) or ``(B, S)`` per BATCH row (continuous-batching
    decode: every cache slot sits at a different depth)."""
    B, S, H, Dh = q.shape
    half = Dh // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = (positions.astype(jnp.float32) if positions is not None
           else pos_offset + jnp.arange(S, dtype=jnp.float32))
    ang = pos[..., None] * freqs  # (S, half) or (B, S, half)
    if ang.ndim == 2:
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :]  # (1 | B, S, 1, half)
    sin = jnp.sin(ang)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xr1 = x1 * cos - x2 * sin
        xr2 = x2 * cos + x1 * sin
        return jnp.concatenate([xr1, xr2], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _qkv_proj(x, p, cfg: TransformerConfig, pos_offset=0, positions=None):
    """Project to per-head Q/K/V with RoPE applied -> head-major
    ``(B, H, S, Dh)`` / ``(B, H_kv, S, Dh)`` (shared by the training
    attention, prefill, and decode paths so the math cannot drift)."""
    q = jnp.einsum("bsd,dhk->bshk", x, p["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bshk", x, p["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, p["wv"].astype(cfg.dtype))
    q, k = _rope(q, k, cfg.rope_theta, pos_offset, positions=positions)
    return (jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
            jnp.moveaxis(v, 2, 1))


def _out_proj(oh, p, cfg: TransformerConfig):
    o = jnp.moveaxis(oh, 1, 2).astype(cfg.dtype)  # (B, S, H, Dh)
    return jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))


def _attention(x, p, cfg: TransformerConfig):
    B, S, D = x.shape
    from horovod_tpu.ops import attention as attn

    pos_offset = 0
    positions = None
    if cfg.attention_impl in ("ring", "ring_reference", "ulysses"):
        # Sequence is sharded over sp: this shard's tokens start at
        # sp_index * S_local in the global sequence.
        pos_offset = lax.axis_index("sp") * S
    elif cfg.attention_impl == "ring_zigzag":
        # Zigzag layout: this shard holds global chunks (i, 2P-1-i) —
        # non-contiguous positions (feed data permuted by zigzag_perm).
        positions = attn.zigzag_positions(S, "sp")

    qh, kh, vh = _qkv_proj(x, p, cfg, pos_offset, positions=positions)
    if cfg.attention_impl == "ring":
        # GQA shards stay small through the ring; expansion is per-chunk.
        oh = attn.ring_attention(qh, kh, vh, axis_name="sp", causal=True)
    elif cfg.attention_impl == "ring_zigzag":
        oh = attn.zigzag_ring_attention(qh, kh, vh, axis_name="sp")
    elif cfg.attention_impl == "ring_reference":
        oh = attn.ring_attention(qh, kh, vh, axis_name="sp", causal=True,
                                 impl="reference")
    elif cfg.attention_impl == "ulysses":
        oh = attn.ulysses_attention(qh, kh, vh, axis_name="sp", causal=True)
    elif cfg.attention_impl == "flash":
        oh = attn.flash_attention(qh, attn.expand_kv(kh, cfg.n_heads),
                                  attn.expand_kv(vh, cfg.n_heads), True)
    elif cfg.attention_impl == "reference":
        oh = attn.reference_attention(qh, attn.expand_kv(kh, cfg.n_heads),
                                      attn.expand_kv(vh, cfg.n_heads),
                                      causal=True)
    else:
        raise ValueError(
            f"unknown attention_impl {cfg.attention_impl!r}; expected "
            "'reference', 'flash', 'ring', 'ring_reference' or 'ulysses'")
    return _out_proj(oh, p, cfg)


def _dense_mlp(x, p, cfg: TransformerConfig):
    g = jnp.einsum("bsd,df->bsf", x, p["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,df->bsf", x, p["w_up"].astype(cfg.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u, p["w_down"].astype(cfg.dtype))


def _moe_mlp_dense(x, p, cfg: TransformerConfig, return_aux: bool = False):
    """Top-1 MoE, dense dispatch: compute routing probs, evaluate every
    expert, combine with the routing one-hot.  Exact and dropless — the
    oracle for the sparse path, and the right choice for decoding (a
    handful of tokens) and tiny E."""
    logits = jnp.einsum("bsd,de->bse", x, p["router"].astype(cfg.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top = jnp.argmax(probs, axis=-1)  # (B, S)
    gate = jnp.max(probs, axis=-1)  # (B, S) top-1 prob
    onehot = jax.nn.one_hot(top, cfg.n_experts, dtype=cfg.dtype)
    g = jnp.einsum("bsd,edf->besf", x, p["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,edf->besf", x, p["w_up"].astype(cfg.dtype))
    y = jnp.einsum("besf,efd->besd", jax.nn.silu(g) * u, p["w_down"].astype(cfg.dtype))
    y = jnp.einsum("besd,bse->bsd", y, onehot)
    y = y * gate[..., None].astype(cfg.dtype)
    if not return_aux:
        return y
    frac = onehot.astype(jnp.float32).reshape(-1, cfg.n_experts).mean(0)
    pbar = probs.reshape(-1, cfg.n_experts).mean(0)
    return y, cfg.n_experts * jnp.sum(frac * pbar)


def _moe_mlp(x, p, cfg: TransformerConfig, impl: Optional[str] = None,
             return_aux: bool = False):
    """Mixture-of-experts FFN; ``impl`` overrides ``cfg.moe_impl``:
    "switch" (capacity-factor sparse dispatch — training), "dense"
    (every-expert oracle — per-step decode, tiny E), "dropless"
    (grouped ragged matmuls, exact at 1/E dense FLOPs — prefill/serving).
    With ``return_aux`` also returns the layer's Switch load-balancing
    loss (ops/moe.py switch_moe(return_aux=True); same formula for
    dense)."""
    impl = impl or cfg.moe_impl
    if impl == "dense":
        return _moe_mlp_dense(x, p, cfg, return_aux=return_aux)
    from horovod_tpu.ops import moe

    if impl == "dropless":
        if return_aux:
            raise ValueError(
                "moe_impl='dropless' is the serving dispatch — train with "
                "'switch' (+ moe_aux_coeff) for the balance loss")
        return moe.dropless_moe(
            x, p["router"], p["w_gate"].astype(cfg.dtype),
            p["w_up"].astype(cfg.dtype), p["w_down"].astype(cfg.dtype))
    if impl != "switch":
        raise ValueError(f"unknown moe_impl {impl!r}; "
                         "expected 'switch', 'dense', or 'dropless'")
    return moe.switch_moe(
        x, p["router"], p["w_gate"].astype(cfg.dtype),
        p["w_up"].astype(cfg.dtype), p["w_down"].astype(cfg.dtype),
        capacity_factor=cfg.capacity_factor, axis_name=cfg.moe_axis,
        return_aux=return_aux, dispatch=cfg.moe_dispatch)


def _mlp_block(x, p, cfg: TransformerConfig, moe_impl: Optional[str] = None,
               return_aux: bool = False):
    """Residual MLP half of a layer, shared by forward, the pipeline, and
    the decode step.  Dense MLPs are bit-identical across all three; MoE
    decode/prefill force dense dispatch, so forward-vs-decode equivalence
    holds exactly when switch dispatch drops no tokens (capacity_factor
    >= n_experts guarantees that) and diverges by the dropped tokens'
    contributions otherwise — capacity drops are a training-time
    behavior, not part of the serving contract.  ``return_aux`` threads
    the MoE balance loss out (0 for dense MLPs so callers can accumulate
    unconditionally)."""
    m = _rmsnorm(x, p["ln2"])
    if cfg.n_experts > 1:
        out = _moe_mlp(m, p, cfg, impl=moe_impl, return_aux=return_aux)
        if return_aux:
            y, aux = out
            return x + y, aux
        return x + out
    y = x + _dense_mlp(m, p, cfg)
    return (y, jnp.float32(0.0)) if return_aux else y


def _layer_body(x, p, cfg: TransformerConfig, return_aux: bool = False):
    x = x + _attention(_rmsnorm(x, p["ln1"]), p, cfg)
    return _mlp_block(x, p, cfg, return_aux=return_aux)


def _remat(layer, cfg: TransformerConfig):
    if cfg.remat_policy == "full":
        return jax.checkpoint(layer)
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}; "
                     "expected 'full' or 'dots'")


def _lm_head(y, ln_f, head, cfg: TransformerConfig):
    """Final RMSNorm + vocabulary projection (f32 logits) — the ONE copy
    shared by forward, decode/prefill, and both pipeline schedules."""
    h = _rmsnorm(y, ln_f)
    return jnp.einsum("bsd,dv->bsv", h, head.astype(cfg.dtype)).astype(
        jnp.float32)


def _xent_sum(logits, targets):
    """SUM of next-token cross-entropy over all positions (divide by the
    token count for a mean) — shared by loss_fn and the pipelines."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None], axis=-1).squeeze(-1)
    return jnp.sum(logz - gold)


def forward(params: Dict, tokens, cfg: TransformerConfig,
            return_aux: bool = False):
    """Logits for next-token prediction.  ``tokens``: (B, S) int32.

    ``return_aux`` additionally returns the SUM over layers of the MoE
    load-balancing auxiliary loss (0.0 for dense models) — accumulated
    in the layer-scan carry."""
    x = params["embed"].astype(cfg.dtype)[tokens]

    if return_aux:
        def layer(carry, p):
            x, aux = carry
            x, a = _layer_body(x, p, cfg, return_aux=True)
            return (x, aux + a), None
    else:
        def layer(x, p):
            return _layer_body(x, p, cfg), None

    if cfg.remat:
        layer = _remat(layer, cfg)
    if return_aux:
        (x, aux), _ = lax.scan(layer, (x, jnp.float32(0.0)), params["layers"])
        return _lm_head(x, params["ln_f"], params["head"], cfg), aux
    x, _ = lax.scan(layer, x, params["layers"])
    return _lm_head(x, params["ln_f"], params["head"], cfg)


def loss_fn(params: Dict, batch: Dict, cfg: TransformerConfig):
    """Mean next-token cross-entropy.  ``batch = {tokens, targets}``.

    With ``cfg.moe_aux_coeff > 0`` on an MoE config, adds
    ``coeff * sum_over_layers(aux)`` — the Switch balance term that keeps
    the learned router from collapsing onto few experts."""
    if cfg.n_experts > 1 and cfg.moe_aux_coeff > 0.0:
        logits, aux = forward(params, batch["tokens"], cfg, return_aux=True)
        xent = _xent_sum(logits, batch["targets"]) / batch["targets"].size
        return xent + cfg.moe_aux_coeff * aux
    logits = forward(params, batch["tokens"], cfg)
    return _xent_sum(logits, batch["targets"]) / batch["targets"].size


def expert_load(params: Dict, tokens, cfg: TransformerConfig):
    """Routing observability: ``(n_layers, n_experts)`` fraction of tokens
    whose top-1 route lands on each expert, measured on the activations
    actually entering every MoE block.  Uniform rows (≈ 1/E) mean a
    balanced router; a collapsed router shows one column near 1.0 (and,
    under tight capacity, most tokens dropped).  Pair with
    ``cfg.moe_aux_coeff`` — the balance term that keeps this histogram
    flat during training."""
    if cfg.n_experts <= 1:
        raise ValueError("expert_load needs an MoE config (n_experts > 1)")
    x = params["embed"].astype(cfg.dtype)[tokens]

    def layer(x, p):
        att = x + _attention(_rmsnorm(x, p["ln1"]), p, cfg)
        m = _rmsnorm(att, p["ln2"])
        logits = (m.astype(jnp.float32).reshape(-1, cfg.d_model)
                  @ p["router"].astype(jnp.float32))
        frac = jax.nn.one_hot(
            jnp.argmax(logits, axis=-1), cfg.n_experts,
            dtype=jnp.float32).mean(0)
        return _mlp_block(att, p, cfg), frac

    _, fracs = lax.scan(layer, x, params["layers"])
    return fracs


# --- autoregressive decoding (KV cache) ---------------------------------------


def serving_shardings(mesh, cfg: TransformerConfig):
    """``(param_shardings, cache_shardings)`` as ``NamedSharding`` trees
    for a tp serving mesh — the one-call recipe for
    :func:`sample_decode`'s ``cache_shardings`` plus the ``device_put``
    placement of restored params (see docs/inference.md)."""
    from jax.sharding import NamedSharding

    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), serving_param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))
    cache_sh = {k: NamedSharding(mesh, s) for k, s in cache_specs().items()}
    return param_sh, cache_sh


def serving_param_specs(cfg: TransformerConfig, axes=("tp",)) -> Dict:
    """:func:`param_specs` restricted to the mesh axes available at
    SERVING time (default a tp-only mesh): any training-only axis (pp,
    fsdp, ep, ...) is replicated, so a model trained with tp>1 restores
    onto a tp serving mesh without resharding logic — heads/ffn/vocab
    stay sharded, everything else replicates."""
    def keep(spec):
        return P(*[a if a in axes else None for a in spec])

    return jax.tree_util.tree_map(
        keep, param_specs(cfg), is_leaf=lambda x: isinstance(x, P))


def cache_specs() -> Dict:
    """KV-cache shardings for tp serving: the cache's kv-head dim shards
    over ``tp`` (cache layout ``(L, B, H_kv, T, Dh)``), matching the
    head-sharded K/V projections so no resharding happens on the decode
    hot path.  Requires ``cfg.kv_heads % tp == 0``.

    The same specs cover :func:`prefill` / :func:`prefill_with_prefix`
    OUTPUT blocks (``(L, K, H_kv, bucket, Dh)`` — axis 1 is the
    admission batch instead of the slot pool, but the sharded axis is
    the same H_kv dim), so a sharded prefill lands into a sharded page
    pool with a purely local scatter."""
    return {
        "k": P(None, None, "tp", None, None),
        "v": P(None, None, "tp", None, None),
        "pos": P(),
    }


def paged_pool_specs(quantized: bool = False) -> Dict:
    """Page-pool shardings for tp serving: the pool's kv-head dim
    shards over ``tp`` (pool layout ``(L, P, H_kv, page, Dh)``) —
    pages are sharded BY HEAD, never by page id, so the page table
    stays replicated host data and grants/COW/attach need no
    sharding awareness at all.  int8 pools' per-vector scales
    (``(L, P, H_kv, page)``) ride the identical head split.  Per-slot
    ``pos`` is replicated (tick data, like the table)."""
    specs = {
        "k": P(None, None, "tp", None, None),
        "v": P(None, None, "tp", None, None),
        "pos": P(),
    }
    if quantized:
        specs["k_scale"] = P(None, None, "tp", None)
        specs["v_scale"] = P(None, None, "tp", None)
    return specs


def paged_kernel_specs(quantized: bool = False):
    """Operand/result PartitionSpecs for the fused paged-attention
    kernel under a tp mesh — the ONE ordering contract
    :func:`_paged_kernel_attend`'s ``shard_map`` and
    :meth:`~horovod_tpu.serving.sharding.ServingSharding.
    paged_kernel_shardings` both read.  The kernel's grid is
    per-(slot, kv-head) with no cross-head communication, so grouped
    queries, the per-layer pool, and int8 scales all split at the
    kv-head dim over ``tp`` while the page table and per-slot limits
    stay replicated host data; outputs come back head-sharded, matching
    the out-projection that consumes them.  Returns ``(in_specs,
    out_specs)`` ordered as ``(q, k_pool, v_pool[, k_scale, v_scale],
    table, limit)`` / ``(o, lse)``."""
    head = P(None, "tp", None, None)
    scale = P(None, "tp", None)
    in_specs = (head, head, head)
    if quantized:
        in_specs = in_specs + (scale, scale)
    return in_specs + (P(), P()), (head, P(None, "tp", None))


def prefix_kv_specs():
    """Sharding for a gathered shared-prefix block
    (:func:`~horovod_tpu.serving.cache.gather_prefix_pages` output,
    ``(L, H_kv, n * page, Dh)``): head dim over ``tp``, matching the
    pool it was gathered from and the suffix prefill that attends it."""
    return P(None, "tp", None, None)


def shard_params(params: Dict, mesh, cfg: TransformerConfig) -> Dict:
    """Place a parameter tree on a serving mesh per
    :func:`serving_param_specs` (heads/ffn/vocab over ``tp``,
    everything else replicated) — the one-call placement for an engine
    or a restored checkpoint.  The sharding tree itself comes from
    :func:`serving_shardings` (the ONE spec→NamedSharding mapping)."""
    param_sh, _ = serving_shardings(mesh, cfg)
    return jax.device_put(params, param_sh)


def shard_kv_pool(pool: Dict, mesh) -> Dict:
    """Place a paged KV pool (:func:`~horovod_tpu.serving.cache.
    init_page_pool`) on a serving mesh per :func:`paged_pool_specs` —
    head-dim sharded payload (and int8 scales), replicated ``pos``."""
    from jax.sharding import NamedSharding

    specs = paged_pool_specs(quantized="k_scale" in pool)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in pool.items()}


def init_cache(cfg: TransformerConfig, batch: int, max_len: int = 0) -> Dict:
    """Per-layer KV cache for autoregressive decoding.

    Shapes are STATIC — ``(L, B, H_kv, T, Dh)`` in ``cfg.dtype`` with a
    traced write position — so the decode step compiles once and every
    token reuses the executable (the XLA-friendly formulation; no
    growing arrays).  GQA (``n_kv_heads``) shrinks the cache by
    ``n_heads / kv_heads`` — the serving-memory lever."""
    T = max_len or cfg.max_seq
    return {
        "k": jnp.zeros((cfg.n_layers, batch, cfg.kv_heads, T, cfg.head_dim),
                       cfg.dtype),
        "v": jnp.zeros((cfg.n_layers, batch, cfg.kv_heads, T, cfg.head_dim),
                       cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def _cache_attend(qh, k_cache, v_cache, mask):
    """One query token per row against the full cache — the ONE copy of
    the decode attention math, shared by the scalar-position path
    (:func:`_attention_decode`) and the per-slot path
    (:func:`_attention_decode_slots`) so the bandwidth discipline cannot
    fork.  ``mask`` is broadcastable to ``(B, H_kv, G, T)``.

    Bandwidth discipline (decode is cache-bandwidth-bound): the cache is
    dotted IN ITS STORED DTYPE with f32 MXU accumulation
    (``preferred_element_type``) — an ``astype(f32)`` here materializes
    a 2× copy of the whole cache per token, and GQA expansion is done by
    GROUPING THE QUERIES (``(B, H_kv, G, ...)``) instead of broadcasting
    K/V to ``H`` — together these were a measured 3.6× decode
    throughput on chip.  For f32 caches the math is bit-identical to the
    upcast formulation; for bf16 caches the products round to bf16
    (standard TPU practice; accumulation stays f32)."""
    B, H, _, Dh = qh.shape
    Hkv = k_cache.shape[1]
    G = H // Hkv
    qg = qh.reshape(B, Hkv, G, Dh)                  # one token: drop q dim
    s = jnp.einsum("bkgd,bktd->bkgt", qg.astype(k_cache.dtype), k_cache,
                   preferred_element_type=jnp.float32) / np.sqrt(Dh)
    s = jnp.where(mask, s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", w.astype(v_cache.dtype), v_cache,
                   preferred_element_type=jnp.float32)
    return o.reshape(B, H, 1, Dh)


def _attention_decode(x, p, cfg: TransformerConfig, k_cache, v_cache, pos):
    """One-token attention against the cache: write this position's K/V
    at ``pos``, attend q over positions <= pos (static-shape mask; the
    attention math itself lives in :func:`_cache_attend`)."""
    qh, k_t, v_t = _qkv_proj(x, p, cfg, pos)        # qh: (B, H, 1, Dh)
    k_cache = lax.dynamic_update_slice_in_dim(
        k_cache, k_t.astype(k_cache.dtype), pos, axis=2)
    v_cache = lax.dynamic_update_slice_in_dim(
        v_cache, v_t.astype(v_cache.dtype), pos, axis=2)
    T = k_cache.shape[2]
    mask = (lax.broadcasted_iota(jnp.int32, (T,), 0) <= pos)
    o = _cache_attend(qh, k_cache, v_cache, mask[None, None, None, :])
    return _out_proj(o.astype(cfg.dtype), p, cfg), k_cache, v_cache


def decode_step(params: Dict, tokens_t, cache: Dict, cfg: TransformerConfig):
    """One autoregressive step.

    ``tokens_t``: (B,) int32 — the token at position ``cache["pos"]``.
    Returns ``(logits (B, V) float32, updated cache)``; the logits match
    :func:`forward`'s at that position exactly (teacher-forcing
    equivalence, ``tests/test_models.py``).  The reference has no decode
    path (it is a training framework); this completes the serving story
    of docs/inference.md with a TPU-idiomatic static-shape cache.

    CONTRACT: at most ``max_len`` (the cache's static T) calls per
    cache — past capacity, ``dynamic_update_slice`` clamps the write to
    the last slot and output silently degrades.  Eager misuse raises;
    under jit the position is traced, so callers must size the cache
    (``init_cache(max_len=prompt + steps)``, as greedy_decode does)."""
    pos = cache["pos"]
    T_cache = cache["k"].shape[3]
    if not isinstance(pos, jax.core.Tracer) and int(pos) >= T_cache:
        raise ValueError(
            f"decode_step past cache capacity (pos {int(pos)} >= "
            f"{T_cache}); init_cache with a larger max_len")
    x = params["embed"].astype(cfg.dtype)[tokens_t][:, None]  # (B, 1, D)

    def layer(x, inp):
        p, k_c, v_c = inp
        h, k_new, v_new = _attention_decode(
            _rmsnorm(x, p["ln1"]), p, cfg, k_c, v_c, pos)
        return _mlp_block(x + h, p, cfg, moe_impl="dense"), (k_new, v_new)

    x, (k_all, v_all) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(x, params["ln_f"], params["head"], cfg)
    return logits[:, 0], {"k": k_all, "v": v_all, "pos": pos + 1}


def _attention_decode_slots(x, p, cfg: TransformerConfig, k_cache, v_cache,
                            pos):
    """Per-slot positioned one-token attention: row ``b`` writes its K/V
    at ``pos[b]`` and attends positions ``<= pos[b]`` — continuous
    batching, where every batch row is an independent request at its own
    depth.  The attention math (and its bandwidth discipline) is the
    shared :func:`_cache_attend`; the per-row write is a vmapped
    ``dynamic_update_slice`` (a scatter touching one position per row,
    not a cache-sized ``where``)."""
    qh, k_t, v_t = _qkv_proj(x, p, cfg, positions=pos[:, None])
    upd = jax.vmap(
        lambda c, t, p_: lax.dynamic_update_slice_in_dim(c, t, p_, axis=1))
    k_cache = upd(k_cache, k_t.astype(k_cache.dtype), pos)
    v_cache = upd(v_cache, v_t.astype(v_cache.dtype), pos)
    T = k_cache.shape[2]
    mask = lax.broadcasted_iota(jnp.int32, (T,), 0)[None, :] <= pos[:, None]
    o = _cache_attend(qh, k_cache, v_cache, mask[:, None, None, :])
    return _out_proj(o.astype(cfg.dtype), p, cfg), k_cache, v_cache


def decode_step_slots(params: Dict, tokens_t, cache: Dict,
                      cfg: TransformerConfig, active):
    """One continuous-batching decode tick over a pool of S cache slots.

    ``tokens_t``: (S,) int32 — each slot's last emitted token;
    ``cache``: a SLOT cache (:func:`horovod_tpu.serving.cache.
    init_slot_cache`) whose ``pos`` is a PER-SLOT (S,) int32 vector;
    ``active``: (S,) bool — which slots hold live requests.  Returns
    ``(logits (S, V) float32, updated cache)``.

    Inactive rows compute on zeros (the Join-style zero-substitution the
    eager runtime uses for absent ranks — ``horovod_tpu/join.py``) and
    their positions do not advance, so ONE compiled executable serves
    every admit/retire pattern: shapes are static in S and the live set
    is data, not structure.  Row ``s`` of the logits equals
    :func:`decode_step`'s for the same request decoded alone at position
    ``pos[s]`` (token-identity: ``tests/test_serving.py``).

    Inactive rows still scatter their (zero-computed) K/V at their stale
    position — harmless by construction: decode always writes position
    ``p`` in the same step that first attends it, so anything a freed
    slot left behind is overwritten before the next tenant can see it
    (the same argument that makes right-padded bucketed prefill safe;
    see :func:`prefill`)."""
    pos = cache["pos"]
    T_cache = cache["k"].shape[3]
    if not isinstance(pos, jax.core.Tracer) and not isinstance(
            active, jax.core.Tracer):
        over = np.asarray(active) & (np.asarray(pos) >= T_cache)
        if over.any():
            raise ValueError(
                f"decode_step_slots past cache capacity (slots "
                f"{np.nonzero(over)[0].tolist()} at pos >= {T_cache}); "
                "init_slot_cache with a larger max_len")
    x = params["embed"].astype(cfg.dtype)[tokens_t][:, None]  # (S, 1, D)
    x = jnp.where(active[:, None, None], x, jnp.zeros_like(x))

    def layer(x, inp):
        p, k_c, v_c = inp
        h, k_new, v_new = _attention_decode_slots(
            _rmsnorm(x, p["ln1"]), p, cfg, k_c, v_c, pos)
        return _mlp_block(x + h, p, cfg, moe_impl="dense"), (k_new, v_new)

    x, (k_all, v_all) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(x, params["ln_f"], params["head"], cfg)
    return logits[:, 0], {
        "k": k_all, "v": v_all,
        "pos": pos + active.astype(jnp.int32),
    }


# --- paged KV cache (block tables resolved inside the tick) -------------------


_KV_QUANT_EPS = 1e-8


def kv_quantize(x):
    """Symmetric per-vector int8 quantization over the trailing head
    dim (the KIVI/KVQuant-style per-token granularity): each ``(..., Dh)``
    vector gets its own f32 scale, so a later write never has to
    re-quantize earlier positions — the scale is written once, in the
    same scatter as the int8 payload, and write-before-attend carries
    over to quantized pages unchanged.  Returns ``(q int8, scale f32)``
    with ``scale`` lacking the trailing dim."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, _KV_QUANT_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale, dtype):
    """Inverse of :func:`kv_quantize`: ``q * scale`` cast to ``dtype``.

    PINNED compute dtype: the multiply happens in f32 — even when
    ``dtype`` is bf16 — and only the final cast narrows.  The fused
    paged-attention kernel replicates this exact f32-multiply-then-cast
    in its load (:data:`horovod_tpu.ops.paged_attention.DEQUANT_COMPUTE`
    is the single shared constant), so the unfused fallback and the
    fused path round int8 pages identically; change one and you must
    change both (``tests/test_paged.py`` pins the contract)."""
    return (q.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(dtype)


def _gather_pages(pool_l, table):
    """Resolve one layer's page pool through a page table: ``pool_l``
    ``(P, H_kv, page, Dh)`` gathered by ``table`` ``(S, max_pages)`` ->
    the per-slot LOGICAL cache ``(S, H_kv, max_pages * page, Dh)``.
    The table is DATA (int32 indices), so the gather is one executable
    for every allocation pattern — pages can come, go, grow, and be
    shared without recompiling the tick."""
    S, max_pages = table.shape
    _, Hkv, ps, Dh = pool_l.shape
    g = pool_l[table]                      # (S, max_pages, H_kv, ps, Dh)
    return jnp.moveaxis(g, 1, 2).reshape(S, Hkv, max_pages * ps, Dh)


def _gather_scales(scale_l, table):
    """Scale companion of :func:`_gather_pages`: ``(P, H_kv, page)`` ->
    ``(S, H_kv, max_pages * page)``."""
    S, max_pages = table.shape
    _, Hkv, ps = scale_l.shape
    g = scale_l[table]                     # (S, max_pages, H_kv, ps)
    return jnp.moveaxis(g, 1, 2).reshape(S, Hkv, max_pages * ps)


def _paged_kernel_attend(qg, k_pool, v_pool, k_scale, v_scale, table,
                         limit, cfg: TransformerConfig, mesh=None):
    """Call the fused paged-attention kernel for one layer, under
    ``shard_map`` when a tp mesh is given.

    The kernel's grid is per-(slot, kv-head) with NO cross-head
    communication, so the tp=N head-sharded pool (``paged_pool_specs``)
    maps onto it shard-locally: each device runs the kernel over its
    own ``H_kv / tp`` heads against its own pool shard, with the table
    and per-slot limits replicated (host tick data).  Outputs come back
    head-sharded, matching the projection that consumes them.  Without
    a mesh the kernel is called directly (single-device serving)."""
    from horovod_tpu.ops import paged_attention as _pa

    quantized = k_scale is not None
    if mesh is None:
        return _pa.paged_attend(qg, k_pool, v_pool, k_scale, v_scale,
                                table, limit, compute_dtype=cfg.dtype)

    from horovod_tpu import spmd

    in_specs, out_specs = paged_kernel_specs(quantized)
    if quantized:
        fn = spmd.shard(
            lambda q_, k_, v_, ks_, vs_, t_, l_: _pa.paged_attend(
                q_, k_, v_, ks_, vs_, t_, l_, compute_dtype=cfg.dtype),
            in_specs=in_specs, out_specs=out_specs, mesh=mesh)
        return fn(qg, k_pool, v_pool, k_scale, v_scale, table, limit)
    fn = spmd.shard(
        lambda q_, k_, v_, t_, l_: _pa.paged_attend(
            q_, k_, v_, None, None, t_, l_, compute_dtype=cfg.dtype),
        in_specs=in_specs, out_specs=out_specs, mesh=mesh)
    return fn(qg, k_pool, v_pool, table, limit)


def _attention_decode_paged(x, p, cfg: TransformerConfig, k_pool, v_pool,
                            k_scale, v_scale, table, pos, active,
                            kernel=False, mesh=None):
    """Per-slot one-token attention against a PAGED cache: row ``s``
    writes its K/V at logical position ``pos[s]`` — resolved through
    the page table to ``(page table[s, pos//page], offset pos%page)`` —
    then gathers its pages back into logical order and attends
    positions ``<= pos[s]`` (the shared :func:`_cache_attend` math).

    Inactive rows are routed to physical page 0, the reserved NULL/
    trash page no live slot's table ever maps below its own position:
    unlike the slot-contiguous layout, a stale write here could land in
    a page that has since been re-granted or shared, so the inactive
    scribble is not merely harmless-by-overwrite — it must be (and is)
    aimed somewhere no one attends.  Active rows never collide: the
    host allocator guarantees every active slot's write page is
    PRIVATE (refcount 1; copy-on-write splits a shared page before any
    write targets it).

    ``k_scale``/``v_scale`` are the per-(head, position) f32 scales of
    int8 pools (None for bf16/f32 storage): the payload is dequantized
    AFTER the gather, so only the logical view — not the whole pool —
    is ever materialized at compute dtype.

    ``kernel=True`` replaces the gather -> dequant -> attend tail with
    the fused Pallas flash-decoding kernel (:mod:`horovod_tpu.ops.
    paged_attention`): the pages stream through VMEM with int8 dequant
    in the load and NOTHING materialized at logical shape.  The scatter
    (write-before-attend) is identical under both paths, so the fused
    tick attends exactly the same pool state; ``mesh`` routes the
    kernel through ``shard_map`` for tp head-sharded pools."""
    S = x.shape[0]
    max_pages = table.shape[1]
    ps = k_pool.shape[2]
    quantized = k_scale is not None
    qh, k_t, v_t = _qkv_proj(x, p, cfg, positions=pos[:, None])
    k_t1 = k_t[:, :, 0, :]                      # (S, H_kv, Dh)
    v_t1 = v_t[:, :, 0, :]
    idx = jnp.clip(pos // ps, 0, max_pages - 1)
    phys = jnp.where(active, table[jnp.arange(S), idx], 0)
    off = pos % ps
    if quantized:
        qk, sk = kv_quantize(k_t1)
        qv, sv = kv_quantize(v_t1)
        k_pool = k_pool.at[phys, :, off, :].set(qk)
        v_pool = v_pool.at[phys, :, off, :].set(qv)
        k_scale = k_scale.at[phys, :, off].set(sk)
        v_scale = v_scale.at[phys, :, off].set(sv)
    else:
        k_pool = k_pool.at[phys, :, off, :].set(k_t1.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, :, off, :].set(v_t1.astype(v_pool.dtype))
    B, H, _, Dh = qh.shape
    if kernel:
        # Fused path: attend positions <= pos ⇔ logical < pos + 1,
        # zeroed for inactive rows so their (NULL-page-routed) writes
        # are never attended.
        limit = jnp.where(active, pos + 1, 0)
        Hkv = k_pool.shape[1]
        qg = qh.reshape(B, Hkv, H // Hkv, Dh)
        o, _ = _paged_kernel_attend(qg, k_pool, v_pool, k_scale, v_scale,
                                    table, limit, cfg, mesh)
        o = o.reshape(B, H, 1, Dh)
    else:
        if quantized:
            kg = kv_dequantize(_gather_pages(k_pool, table),
                               _gather_scales(k_scale, table), cfg.dtype)
            vg = kv_dequantize(_gather_pages(v_pool, table),
                               _gather_scales(v_scale, table), cfg.dtype)
        else:
            kg = _gather_pages(k_pool, table)
            vg = _gather_pages(v_pool, table)
        T = max_pages * ps
        mask = (lax.broadcasted_iota(jnp.int32, (T,), 0)[None, :]
                <= pos[:, None])
        o = _cache_attend(qh, kg, vg, mask[:, None, None, :])
    return (_out_proj(o.astype(cfg.dtype), p, cfg),
            k_pool, v_pool, k_scale, v_scale)


def decode_step_paged(params: Dict, tokens_t, pool: Dict, table,
                      cfg: TransformerConfig, active, *, kernel=False,
                      mesh=None):
    """One continuous-batching decode tick over a PAGED KV cache.

    ``pool``: the page pool (:func:`horovod_tpu.serving.cache.
    init_page_pool`) — ``k``/``v`` shaped ``(L, P, H_kv, page, Dh)``
    (plus ``k_scale``/``v_scale`` ``(L, P, H_kv, page)`` for int8
    storage) and per-slot ``pos`` ``(S,)``; ``table``: ``(S,
    max_pages)`` int32 page ids, logical position ``t`` of slot ``s``
    living at ``(table[s, t // page], t % page)``.  Shapes are static
    in S, P, and max_pages; the table and the live mask are DATA, so
    ONE compiled executable serves every allocation pattern — requests
    coming, going, growing pages, and sharing prefix pages never
    recompile the tick (the paged analogue of
    :func:`decode_step_slots`, whose per-row logits it matches exactly
    for any table that lays the slot's positions out in order).

    Returns ``(logits (S, V) float32, updated pool)`` — the table is
    host-owned and passed back unchanged.

    ``kernel=True`` routes every layer's attention through the fused
    Pallas flash-decoding kernel (gather/dequant/attend in one VMEM
    pass — :mod:`horovod_tpu.ops.paged_attention`); logits stay greedy-
    token-identical to the unfused path.  ``kernel``/``mesh`` are
    trace-time Python values, so flipping them selects a DIFFERENT
    executable rather than recompiling an existing one."""
    pos = pool["pos"]
    T_cap = table.shape[1] * pool["k"].shape[3]
    if not isinstance(pos, jax.core.Tracer) and not isinstance(
            active, jax.core.Tracer):
        over = np.asarray(active) & (np.asarray(pos) >= T_cap)
        if over.any():
            raise ValueError(
                f"decode_step_paged past table capacity (slots "
                f"{np.nonzero(over)[0].tolist()} at pos >= {T_cap}); "
                "init_page_pool with more pages per slot")
    x = params["embed"].astype(cfg.dtype)[tokens_t][:, None]  # (S, 1, D)
    x = jnp.where(active[:, None, None], x, jnp.zeros_like(x))
    quantized = "k_scale" in pool

    def layer(x, inp):
        if quantized:
            p, k_c, v_c, ks_c, vs_c = inp
        else:
            (p, k_c, v_c), ks_c, vs_c = inp, None, None
        h, k_new, v_new, ks_new, vs_new = _attention_decode_paged(
            _rmsnorm(x, p["ln1"]), p, cfg, k_c, v_c, ks_c, vs_c,
            table, pos, active, kernel=kernel, mesh=mesh)
        out = (k_new, v_new) + ((ks_new, vs_new) if quantized else ())
        return _mlp_block(x + h, p, cfg, moe_impl="dense"), out

    xs = (params["layers"], pool["k"], pool["v"])
    if quantized:
        xs = xs + (pool["k_scale"], pool["v_scale"])
    x, new = lax.scan(layer, x, xs)
    logits = _lm_head(x, params["ln_f"], params["head"], cfg)
    out = {"k": new[0], "v": new[1],
           "pos": pos + active.astype(jnp.int32)}
    if quantized:
        out["k_scale"], out["v_scale"] = new[2], new[3]
    return logits[:, 0], out


# --- speculative decoding (draft / verify multi-token ticks) ------------------
#
# Leviathan et al., "Fast Inference from Transformers via Speculative
# Decoding": draft K cheap tokens, verify them in ONE batched target
# forward, accept the agreeing prefix plus the target's correction token.
# Under GREEDY decoding the emitted tokens are ALWAYS the target's own
# argmax continuations — draft quality moves only the acceptance rate
# (tokens per tick), never the output — so byte-identity to the
# non-speculative path is a property of the verify kernel alone.


def draft_propose_paged(params: Dict, tokens_t, pool: Dict, table,
                        cfg: TransformerConfig, active, k: int, *,
                        kernel=False, mesh=None):
    """``k`` greedy draft tokens per slot from a (shallow) draft model:
    ``k + 1`` sequential :func:`decode_step_paged` steps in one trace —
    step ``i`` feeds the previous step's argmax, so the scan writes the
    draft's OWN K/V for every token it proposes (plus one extra step so
    the last draft's K/V lands too; its logits are discarded).  The
    draft pool's ``pos`` advances by ``k + 1`` — the caller rolls it
    back to the verified position, and write-before-attend makes the
    rejected tail's stale K/V inert (the next tick's draft overwrites
    position ``p`` before attending it, exactly the slot-reuse
    argument).  Returns ``(drafts (S, k) int32, updated draft pool)``."""

    def step(carry, _):
        tok, pl = carry
        logits, pl = decode_step_paged(params, tok, pl, table, cfg, active,
                                       kernel=kernel, mesh=mesh)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, pl), nxt

    (_, pool), ds = lax.scan(step, (tokens_t, pool), None, length=k + 1)
    return jnp.moveaxis(ds, 0, 1)[:, :k], pool


def ngram_propose(hist, pos, k: int):
    """Draft ``k`` tokens per slot by PROMPT LOOKUP (n-gram
    self-speculation — no second model): find the most recent earlier
    occurrence of the slot's final bigram in its committed token
    history and propose the ``k`` tokens that followed it.

    ``hist``: (S, T) committed tokens, position ``pos[s]`` holding slot
    ``s``'s last committed token; ``pos``: (S,) int32.  Slots with no
    earlier match (or fewer than two committed tokens) fall back to
    repeating the last token.  Entirely data-dependent gathers — one
    executable for every history.  Draft quality only moves the
    acceptance rate; the verify kernel owns correctness."""
    S, T = hist.shape
    rows = jnp.arange(S)
    last = hist[rows, jnp.clip(pos, 0, T - 1)]
    prev = hist[rows, jnp.clip(pos - 1, 0, T - 1)]
    iota = lax.broadcasted_iota(jnp.int32, (S, T), 1)
    nxt = jnp.concatenate([hist[:, 1:], jnp.zeros((S, 1), hist.dtype)],
                          axis=1)
    match = ((hist == prev[:, None]) & (nxt == last[:, None])
             & (iota + 1 < pos[:, None]))
    idx = jnp.max(jnp.where(match, iota, -1), axis=1)  # most recent
    found = idx >= 0
    gidx = (idx + 2)[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    drafts = jnp.take_along_axis(hist, jnp.clip(gidx, 0, T - 1), axis=1)
    # Gate the copy window to COMMITTED positions (<= pos): a match
    # near the end of history would otherwise draft uncommitted zeros
    # — on a pure repeat ("a a a a", where the most recent match ends
    # one short of the final bigram) that would cap acceptance at 1/k.
    # Past the committed region, fall back to repeating the last token
    # (exactly right for period-1 repeats, harmlessly wrong otherwise).
    ok = found[:, None] & (gidx <= pos[:, None])
    return jnp.where(ok, drafts, last[:, None])


def decode_verify_paged(params: Dict, window, pool: Dict, table,
                        cfg: TransformerConfig, active, spec_on=None,
                        sample=None, *, kernel=False, mesh=None):
    """One batched W-position VERIFY forward over a paged cache — the
    speculative tick's target-model half.

    ``window``: (S, W) int32 — column 0 is each slot's last COMMITTED
    token, columns 1..W-1 its drafts.  The window runs as a
    prefill-style multi-position forward: query offset ``j`` (logical
    position ``pos[s] + j``) attends the slot's committed pages
    (positions ``< pos[s]``, gathered through the table exactly like
    :func:`decode_step_paged`) plus window offsets ``<= j``, with the
    window K/V attended AFTER a storage-dtype round trip (int8
    quantize-dequantize for quantized pools) so every position's logits
    are bit-identical to the sequential one-token path, which always
    reads its own K/V back from the pool.

    Acceptance is computed IN-KERNEL and is DATA: ``t = argmax`` per
    position is the target's greedy continuation, and ``acc[s]`` is the
    length of the agreeing draft prefix (``window[s, 1 + i] ==
    t[s, i]``), so a slot emits tokens ``t[s, 0..acc[s]]`` — the
    accepted drafts (identical to the target's own picks) plus the
    correction/bonus token.  Varying acceptance never recompiles.

    K/V is then scattered for ACCEPTED window offsets only (offset 0,
    the committed token, always writes): the rejected tail — and any
    position past the table's capacity — is routed to physical page 0,
    the reserved NULL/trash page, so a draft the target disagreed with
    can never contaminate a page another slot (or a COW prefix sharer)
    may come to own.  ``spec_on`` (optional (S,) bool) forces
    ``acc = 0`` for opted-out slots — they emit exactly the one greedy
    token per tick through the same executable.

    ``sample`` (optional ``(temperature, top_k, top_p, rng)`` per-slot
    columns — :func:`sample_token_rows`): rows with ``temperature > 0``
    replace the offset-0 token with a SAMPLED pick from the same
    logits (key index ``pos + 1``, the token's logical position — the
    identical schedule the plain tick and the oracle use) and have
    ``acc`` forced to 0: drafts are verified by argmax agreement, so a
    sampled stream never accepts them — it emits exactly one sampled
    token per tick through this executable, which is what lets mixed
    sampled/greedy-speculating batches share the program.

    Returns ``(target_tokens (S, W) int32, max_logits (S, W) f32,
    accepted (S,) int32, updated pool)`` with ``pos`` advanced by
    ``acc + 1`` per active slot.

    ``kernel=True`` splits each layer's attention into the fused Pallas
    kernel over the COMMITTED pages (positions ``< pos[s]``, streamed
    through VMEM with int8 dequant in the load) plus a dense causal
    pass over the W-wide window, merged by logsumexp — the standard
    flash-decoding cross-source combine.  The in-window K/V still takes
    its storage-dtype round trip first, so verify logits keep their
    bit-identity to the sequential one-token path."""
    pos = pool["pos"]
    S, W = window.shape
    max_pages = table.shape[1]
    ps = pool["k"].shape[3]
    T_cap = max_pages * ps
    quantized = "k_scale" in pool
    storage = pool["k"].dtype
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    G = H // Hkv

    x = params["embed"].astype(cfg.dtype)[window]  # (S, W, D)
    x = jnp.where(active[:, None, None], x, jnp.zeros_like(x))
    positions = pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    # (S, 1, 1, W, T + W) mask: committed cache strictly below pos[s]
    # (page-tail junk and ungranted NULL-page garbage are >= pos, so
    # they are never attended), window causal within itself.
    cache_vis = (lax.broadcasted_iota(jnp.int32, (T_cap,), 0)[None, :]
                 < pos[:, None])
    cache_vis = jnp.broadcast_to(cache_vis[:, None, :], (S, W, T_cap))
    win_vis = (lax.broadcasted_iota(jnp.int32, (W, W), 1)
               <= lax.broadcasted_iota(jnp.int32, (W, W), 0))
    win_vis = jnp.broadcast_to(win_vis[None], (S, W, W))
    mask = jnp.concatenate([cache_vis, win_vis], axis=2)[:, None, None]
    # Committed-page limit for the fused kernel (strictly < pos, shared
    # by every window offset) — zeroed for inactive rows.
    climit = jnp.where(active, pos, 0)
    wmask = win_vis[:, None, None]              # (S, 1, 1, W, W)

    def layer(x, inp):
        if quantized:
            p, k_c, v_c, ks_c, vs_c = inp
        else:
            (p, k_c, v_c), ks_c, vs_c = inp, None, None
        h = _rmsnorm(x, p["ln1"])
        qh, kh, vh = _qkv_proj(h, p, cfg, positions=positions)
        if quantized:
            qk, sk = kv_quantize(kh)
            qv, sv = kv_quantize(vh)
            kh_a = kv_dequantize(qk, sk, cfg.dtype)
            vh_a = kv_dequantize(qv, sv, cfg.dtype)
            ys = (qk, sk, qv, sv)
        else:
            kh_a = kh.astype(storage)
            vh_a = vh.astype(storage)
            ys = (kh_a, vh_a)
        qg = qh.reshape(S, Hkv, G, W, Dh)
        if kernel:
            # Fused kernel over the committed pages: W*G query rows per
            # (slot, kv-head) in one pass, pre-scatter pool (same state
            # the unfused gather reads), int8 dequant in the load.
            o_c, lse_c = _paged_kernel_attend(
                qg.reshape(S, Hkv, G * W, Dh), k_c, v_c, ks_c, vs_c,
                table, climit, cfg, mesh)
            o_c = o_c.reshape(S, Hkv, G, W, Dh)
            lse_c = lse_c.reshape(S, Hkv, G, W)
            # Dense causal attention within the window (post round-trip
            # K/V), kept unnormalized alongside its own logsumexp.
            sw = jnp.einsum("bkgsd,bktd->bkgst", qg.astype(kh_a.dtype),
                            kh_a, preferred_element_type=jnp.float32
                            ) / np.sqrt(Dh)
            sw = jnp.where(wmask, sw, -1e30)
            mw = jnp.max(sw, axis=-1)           # (S, Hkv, G, W)
            pw = jnp.exp(sw - mw[..., None])
            lw = jnp.sum(pw, axis=-1)           # >= 1: diagonal visible
            o_w = jnp.einsum("bkgst,bktd->bkgsd", pw.astype(vh_a.dtype),
                             vh_a, preferred_element_type=jnp.float32
                             ) / lw[..., None]
            lse_w = mw + jnp.log(lw)
            # Cross-source LSE combine; a_c underflows to exactly 0 for
            # rows with no committed context (lse_c == NEG_INF).
            m = jnp.maximum(lse_c, lse_w)
            a_c = jnp.exp(lse_c - m)
            a_w = jnp.exp(lse_w - m)
            o = ((a_c[..., None] * o_c + a_w[..., None] * o_w)
                 / (a_c + a_w)[..., None])
        else:
            if quantized:
                kg = kv_dequantize(_gather_pages(k_c, table),
                                   _gather_scales(ks_c, table), cfg.dtype)
                vg = kv_dequantize(_gather_pages(v_c, table),
                                   _gather_scales(vs_c, table), cfg.dtype)
            else:
                kg = _gather_pages(k_c, table)
                vg = _gather_pages(v_c, table)
            k_full = jnp.concatenate([kg, kh_a], axis=2)  # (S,Hkv,T+W,Dh)
            v_full = jnp.concatenate([vg, vh_a], axis=2)
            # Grouped-query attention, W queries wide — _cache_attend's
            # bandwidth discipline (stored dtype, f32 MXU accumulation).
            sc = jnp.einsum("bkgsd,bktd->bkgst", qg.astype(k_full.dtype),
                            k_full, preferred_element_type=jnp.float32
                            ) / np.sqrt(Dh)
            sc = jnp.where(mask, sc, -1e30)
            w = jax.nn.softmax(sc, axis=-1)
            o = jnp.einsum("bkgst,bktd->bkgsd", w.astype(v_full.dtype),
                           v_full, preferred_element_type=jnp.float32)
        out = _out_proj(o.reshape(S, H, W, Dh).astype(cfg.dtype), p, cfg)
        return _mlp_block(x + out, p, cfg, moe_impl="dense"), ys

    xs = (params["layers"], pool["k"], pool["v"])
    if quantized:
        xs = xs + (pool["k_scale"], pool["v_scale"])
    x, ys = lax.scan(layer, x, xs)
    logits = _lm_head(x, params["ln_f"], params["head"], cfg)  # (S,W,V)
    t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    mx = jnp.max(logits, axis=-1)
    match = (window[:, 1:] == t[:, :-1]).astype(jnp.int32)
    acc = jnp.cumprod(match, axis=1).sum(axis=1)  # agreeing prefix len
    if spec_on is not None:
        acc = jnp.where(spec_on, acc, 0)
    if sample is not None:
        # Sampled rows: offset 0 becomes the sampled pick (same logits,
        # same key schedule as the plain tick), and acc is forced to 0
        # — argmax-verified drafts are never valid for a sampled
        # stream, whatever the host-side mask said.
        temp, s_tk, s_tp, s_rng = sample
        s0 = sample_token_rows(logits[:, 0, :], temp, s_tk, s_tp, s_rng,
                               pos + 1, jnp.zeros((S,), jnp.int32))
        t = t.at[:, 0].set(jnp.where(temp > 0.0, s0, t[:, 0]))
        acc = jnp.where(temp > 0.0, 0, acc)
    acc = jnp.where(active, acc, 0)

    # Accepted-only scatter: window offset j lands at logical position
    # pos[s] + j through the table iff accepted (j <= acc) and within
    # capacity; everything else — rejected drafts, inactive rows,
    # out-of-capacity positions — routes to the NULL page (physical 0).
    j = jnp.arange(W, dtype=jnp.int32)[None, :]
    wpos = pos[:, None] + j
    ok = active[:, None] & (j <= acc[:, None]) & (wpos < T_cap)
    idxp = jnp.clip(wpos // ps, 0, max_pages - 1)
    phys = jnp.where(ok, jnp.take_along_axis(table, idxp, axis=1), 0)
    off = wpos % ps

    def scatter(pool_l, vals_l):
        # pool_l (P, Hkv, ps, Dh); vals_l (S, Hkv, W, Dh) -> indexed
        # result dims (S, W) lead, giving (S, W, Hkv, Dh) values.
        return pool_l.at[phys, :, off, :].set(jnp.moveaxis(vals_l, 2, 1))

    def scatter_scale(scale_l, vals_l):
        return scale_l.at[phys, :, off].set(jnp.moveaxis(vals_l, 2, 1))

    if quantized:
        qk, sk, qv, sv = ys
        out = {
            "k": jax.vmap(scatter)(pool["k"], qk),
            "v": jax.vmap(scatter)(pool["v"], qv),
            "k_scale": jax.vmap(scatter_scale)(pool["k_scale"], sk),
            "v_scale": jax.vmap(scatter_scale)(pool["v_scale"], sv),
        }
    else:
        kh_all, vh_all = ys
        out = {"k": jax.vmap(scatter)(pool["k"], kh_all),
               "v": jax.vmap(scatter)(pool["v"], vh_all)}
    out["pos"] = pos + jnp.where(active, acc + 1, 0)
    return t, mx, acc, out


def prefill_with_prefix(params: Dict, suffix, prefix_k, prefix_v,
                        prefix_len, cfg: TransformerConfig, *,
                        true_len, moe_impl: str = "dropless"):
    """Prefill a (K, S0) SUFFIX whose first ``prefix_len`` logical
    positions already exist as cached K/V — the prefix-sharing prefill:
    a registered system prompt is prefilled ONCE, and every request
    that starts with it runs only its suffix through the model,
    attending the shared prefix K/V read back from its (refcounted)
    pages.

    ``prefix_k``/``prefix_v``: ``(L, H_kv, P0, Dh)`` with ``P0 >=
    prefix_len`` (page-granular gathers round up; positions ``>=
    prefix_len`` are masked out, so page-tail junk is inert), shared by
    every row.  ``true_len``: ``(K,)`` per-row REAL suffix token counts
    (rows are right-padded to the bucket S0).  Suffix queries sit at
    global positions ``prefix_len + i`` (RoPE) and attend the full
    prefix plus their causal suffix span.  Returns ``(last-real-
    position logits (K, V), {"k": (L, K, H_kv, S0, Dh), "v": ...,
    "pos": prefix_len + true_len})`` — the suffix K/V for page landing,
    exactly :func:`prefill`'s contract shifted by the prefix.

    Position-wise the suffix K/V (and logits) match a full-prompt
    :func:`prefill` bit-for-bit at f32: K/V at a position depend only
    on the tokens at and before it, and the shared math
    (``_qkv_proj`` / ``_cache_attend``-style grouped attention /
    ``_mlp_block`` / ``_lm_head``) is the same code."""
    K, S0 = suffix.shape
    P0 = prefix_k.shape[2]
    p0 = jnp.asarray(prefix_len, jnp.int32)
    true_len = jnp.asarray(true_len, jnp.int32)
    positions = p0 + jnp.arange(S0, dtype=jnp.int32)
    x = params["embed"].astype(cfg.dtype)[suffix]
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    G = H // Hkv
    # (S0, P0 + S0) mask: the real prefix is fully visible, page-tail
    # junk (>= p0) never, and the suffix is causal within itself.
    pre_vis = lax.broadcasted_iota(jnp.int32, (P0,), 0)[None, :] < p0
    pre_vis = jnp.broadcast_to(pre_vis, (S0, P0))
    suf_vis = (lax.broadcasted_iota(jnp.int32, (S0, S0), 1)
               <= lax.broadcasted_iota(jnp.int32, (S0, S0), 0))
    mask = jnp.concatenate([pre_vis, suf_vis], axis=1)[None, None, None]

    def layer(x, inp):
        p, pk, pv = inp
        h = _rmsnorm(x, p["ln1"])
        qh, kh, vh = _qkv_proj(h, p, cfg, positions=positions)
        k_full = jnp.concatenate(
            [jnp.broadcast_to(pk[None].astype(kh.dtype), (K, Hkv, P0, Dh)),
             kh], axis=2)
        v_full = jnp.concatenate(
            [jnp.broadcast_to(pv[None].astype(vh.dtype), (K, Hkv, P0, Dh)),
             vh], axis=2)
        # Grouped-query attention with the prefix mask — the same
        # bandwidth discipline as _cache_attend, S0 queries wide.
        qg = qh.reshape(K, Hkv, G, S0, Dh)
        s = jnp.einsum("bkgsd,bktd->bkgst", qg.astype(k_full.dtype),
                       k_full, preferred_element_type=jnp.float32
                       ) / np.sqrt(Dh)
        s = jnp.where(mask, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkgst,bktd->bkgsd", w.astype(v_full.dtype),
                       v_full, preferred_element_type=jnp.float32)
        oh = o.reshape(K, H, S0, Dh)
        out = _out_proj(oh.astype(cfg.dtype), p, cfg)
        return _mlp_block(x + out, p, cfg, moe_impl=moe_impl), (kh, vh)

    x, (k_all, v_all) = lax.scan(
        layer, x, (params["layers"], prefix_k, prefix_v))
    last = jnp.take_along_axis(x, (true_len - 1)[:, None, None], axis=1)
    logits = _lm_head(last, params["ln_f"], params["head"], cfg)
    return logits[:, 0], {"k": k_all, "v": v_all, "pos": p0 + true_len}


def _attention_prefill(x, p, cfg: TransformerConfig):
    """Full-sequence attention that ALSO returns the (unexpanded,
    post-RoPE) per-layer K/V for cache filling.  Shares the projection
    math with :func:`_attention` via ``_qkv_proj``/``_out_proj`` and
    honors ``attention_impl='reference'``; the sequence-parallel impls
    need a bound mesh axis, so they prefill through the flash kernel
    (which falls back to fused XLA for untileable prompts)."""
    from horovod_tpu.ops import attention as attn

    qh, kh, vh = _qkv_proj(x, p, cfg, 0)  # kh/vh: (B, H_kv, S0, Dh)
    if cfg.attention_impl == "reference":
        oh = attn.reference_attention(
            qh, attn.expand_kv(kh, cfg.n_heads),
            attn.expand_kv(vh, cfg.n_heads), causal=True)
    else:
        oh = attn.flash_attention(qh, attn.expand_kv(kh, cfg.n_heads),
                                  attn.expand_kv(vh, cfg.n_heads), True)
    return _out_proj(oh, p, cfg), kh, vh


def prefill(params: Dict, prompt, cache: Dict, cfg: TransformerConfig,
            *, moe_impl: str = "dropless", true_len=None):
    """Fill a FRESH cache with a (B, S0) prompt in ONE forward pass
    (the serving-shape prefill: batched MXU work instead of S0 serial
    decode steps) and return ``(last-position logits (B, V), cache)``
    with ``pos = S0``.  Continue with :func:`decode_step`.

    ``moe_impl`` selects the MoE dispatch for MoE configs: "dropless"
    (grouped ragged matmuls — exact at 1/E of dense FLOPs, the default)
    or "dense" (the every-expert oracle; benchmarking/fallback).

    ``true_len`` supports BUCKETED prefill (the serving engine's
    compile-stability lever): the prompt is RIGHT-padded to a bucket
    length S0 and ``true_len`` is its real token count — logits come
    from position ``true_len - 1`` and the returned ``pos`` is
    ``true_len``, so one compiled prefill per bucket serves every
    length in the bucket.  A SCALAR ``true_len`` (int or traced) keeps
    the scalar-``pos`` cache contract for :func:`decode_step`; a
    ``(B,)`` VECTOR gives every row its own length — the batch-K
    multi-request prefill the continuous-batching engine admits with —
    and the returned ``pos`` is the ``(B,)`` per-row count (consumed by
    ``serving.cache.insert_prefill_batch``, one slot per row).
    Causality makes the padding inert for the logits (position
    ``true_len - 1`` never attends past itself), and the junk K/V it
    leaves at positions ``>= true_len`` is never read: decode writes
    position ``p`` in the same step that first attends it."""
    pos = cache["pos"]
    if not isinstance(pos, jax.core.Tracer) and int(pos) != 0:
        raise ValueError("prefill requires a fresh cache (pos == 0)")
    S0 = prompt.shape[1]
    T_cache = cache["k"].shape[3]
    if S0 > T_cache:  # shapes are static, so this raises under jit too
        raise ValueError(
            f"prompt ({S0} tokens) exceeds cache capacity ({T_cache}); "
            "init_cache with a larger max_len")
    x = params["embed"].astype(cfg.dtype)[prompt]

    def layer(x, p):
        h, kh, vh = _attention_prefill(_rmsnorm(x, p["ln1"]), p, cfg)
        # Prefill ingests whole prompts: DROPLESS grouped-matmul dispatch
        # by default — exact like dense but 1/E of its FFN FLOPs
        # (ops/moe.py dropless_moe).  Per-step decode keeps dense (a
        # handful of tokens; ragged grouping buys nothing there).
        return _mlp_block(x + h, p, cfg, moe_impl=moe_impl), (kh, vh)

    x, (k_all, v_all) = lax.scan(layer, x, params["layers"])
    # Only one position's logits are needed: slice BEFORE the (B, S0, V)
    # head projection.
    if true_len is None:
        last = x[:, -1:]
        new_pos = pos + S0
    elif jnp.ndim(true_len) == 0:
        true_len = jnp.asarray(true_len, jnp.int32)
        last = lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)
        new_pos = pos + true_len
    else:
        # Per-row lengths (batch-K multi-request prefill): row b's
        # logits come from ITS position true_len[b] - 1, and pos
        # becomes the (B,) vector of per-row counts.
        true_len = jnp.asarray(true_len, jnp.int32)
        last = jnp.take_along_axis(x, (true_len - 1)[:, None, None],
                                   axis=1)
        new_pos = pos + true_len
    logits = _lm_head(last, params["ln_f"], params["head"], cfg)
    cache = {
        "k": lax.dynamic_update_slice_in_dim(
            cache["k"], k_all.astype(cache["k"].dtype), 0, axis=3),
        "v": lax.dynamic_update_slice_in_dim(
            cache["v"], v_all.astype(cache["v"].dtype), 0, axis=3),
        "pos": new_pos,
    }
    return logits[:, 0], cache


def sample_token_rows(logits, temperature, top_k, top_p, rng, positions,
                      rows):
    """Pick one token per row with EVERY sampling parameter as DATA —
    the serving engine's per-slot sampling kernel, and the math
    :func:`sample_decode` (the per-request oracle) is defined by.  One
    compiled executable serves any mix of greedy / temperature / top-k
    / top-p rows: the parameters are columns, not structure, so request
    churn never recompiles the decode tick.

    ``logits``: (R, V) float32.  ``temperature``: (R,) f32 — ``<= 0``
    is greedy argmax (raw logits), exactly the scalar ``temperature=0``
    case.  ``top_k``: (R,) int32 — ``> 0`` restricts sampling to the k
    most likely tokens (``0`` = off; the k-th value comes from a full
    descending sort so k is data, matching ``lax.top_k``'s k-th value
    bit-for-bit).  ``top_p``: (R,) f32 — nucleus sampling: keep the
    smallest probability-sorted set whose cumulative mass reaches
    ``top_p`` (ties at the threshold are kept; ``0`` or ``>= 1`` =
    off), applied AFTER top-k on the temperature-scaled distribution.

    PRNG schedule (the contract resume/failover identity hangs on):
    the token at logical sequence position ``p`` of batch row ``r``
    draws from ``fold_in(fold_in(rng[r], p), r)``.  Keys are a pure
    function of (seed, position, row) — NOT of how generation was
    sliced across prefills — so re-prefilling ``prompt + emitted`` and
    continuing lands on the identical key stream: restart-resume,
    router failover, and the engine/oracle A/B all compose by
    construction.  ``rng``: (R, 2) uint32 base keys; ``positions``:
    (R,) int32; ``rows``: (R,) int32 (the engine passes zeros — each
    slot is row 0 of its own per-request oracle call)."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Greedy rows divide by 1.0 (their sampled value is discarded by
    # the final where, but NaN/Inf from a 0-division must never enter
    # the softmax); sampled rows divide by their exact temperature.
    scaled = logits / jnp.where(temperature > 0.0, temperature,
                                1.0)[:, None]
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]            # descending
    kth = jnp.take_along_axis(srt, (jnp.clip(top_k, 1, V) - 1)[:, None],
                              axis=1)
    scaled = jnp.where((top_k > 0)[:, None] & (scaled < kth),
                       -jnp.inf, scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    ps = jnp.sort(probs, axis=-1)[:, ::-1]
    csum = jnp.cumsum(ps, axis=-1)
    # Sorted index i is in the nucleus iff the mass BEFORE it is still
    # under top_p (index 0 always is); the smallest kept probability
    # becomes the threshold, so threshold ties stay in.
    keep = (csum - ps) < top_p[:, None]
    thr = jnp.min(jnp.where(keep, ps, jnp.inf), axis=-1, keepdims=True)
    p_on = (top_p > 0.0) & (top_p < 1.0)
    scaled = jnp.where(p_on[:, None] & (probs < thr), -jnp.inf, scaled)

    def pick(key, pos, row, lrow):
        key = jax.random.fold_in(jax.random.fold_in(key, pos), row)
        return jax.random.categorical(key, lrow)

    sampled = jax.vmap(pick)(rng, positions, rows, scaled)
    return jnp.where(temperature > 0.0, sampled.astype(jnp.int32), greedy)


def sample_decode(params: Dict, prompt, steps: int, cfg: TransformerConfig,
                  *, rng, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 0.0,
                  cache_shardings: Optional[Dict] = None):
    """Extend a (B, S0) prompt by ``steps`` SAMPLED tokens -> (B, steps).

    One batched :func:`prefill` forward fills the cache, then ``steps``
    compiled :func:`decode_step` calls generate.  ``temperature`` scales
    the logits; ``top_k > 0`` restricts sampling to the k most likely
    tokens (clamped to the vocabulary); ``top_p`` in (0, 1) keeps the
    nucleus — the smallest top-probability set whose mass reaches
    ``top_p`` — applied after top-k.  ``temperature=0`` is greedy
    (:func:`greedy_decode` is exactly that case).  The per-token pick
    is :func:`sample_token_rows` with every parameter broadcast to a
    column, which is what makes this THE per-request oracle for the
    serving engine's vectorized per-slot sampling.

    PRNG schedule: token ``i`` of row ``b`` (logical position
    ``S0 + i``) draws from ``fold_in(fold_in(rng, S0 + i), b)`` — keys
    depend on the token's absolute position, not the step count, so
    ``sample_decode(prompt + emitted, rng=same)`` continues the exact
    stream an interrupted call would have produced (the resume /
    failover identity the serving stack leans on).  Rows draw
    independent streams via the row fold.

    ``cache_shardings``: optional dict of ``NamedSharding`` matching
    :func:`cache_specs` — pins the KV cache's head dim over a ``tp``
    serving mesh so a model trained with tp>1 serves tp-sharded (the
    scan carry keeps the constraint for every decode step; GSPMD
    partitions the attention/FFN math and inserts the tp collectives)."""
    B, S0 = prompt.shape
    cache = init_cache(cfg, B, S0 + steps)
    if cache_shardings is not None:
        cache = {
            k: lax.with_sharding_constraint(v, cache_shardings[k])
            for k, v in cache.items()
        }
    logits, cache = prefill(params, prompt, cache, cfg)
    temp_col = jnp.full((B,), temperature, jnp.float32)
    tk_col = jnp.full((B,), top_k, jnp.int32)
    tp_col = jnp.full((B,), top_p, jnp.float32)
    keys = jnp.broadcast_to(jnp.asarray(rng, jnp.uint32), (B, 2))
    rows = jnp.arange(B, dtype=jnp.int32)

    def gen(carry, pos):
        cache, logits = carry
        tok = sample_token_rows(logits, temp_col, tk_col, tp_col, keys,
                                jnp.full((B,), pos, jnp.int32), rows)
        logits, cache = decode_step(params, tok, cache, cfg)
        return (cache, logits), tok

    _, toks = lax.scan(gen, (cache, logits),
                       jnp.arange(S0, S0 + steps, dtype=jnp.int32))
    return jnp.moveaxis(toks, 0, 1)


def greedy_decode(params: Dict, prompt, steps: int, cfg: TransformerConfig,
                  *, cache_shardings: Optional[Dict] = None):
    """Extend a (B, S0) prompt by ``steps`` greedy tokens -> (B, steps)."""
    return sample_decode(params, prompt, steps, cfg,
                         rng=jax.random.PRNGKey(0), temperature=0.0,
                         cache_shardings=cache_shardings)


# --- true pipeline parallelism ------------------------------------------------


def pipelined_forward(params: Dict, tokens, cfg: TransformerConfig, *,
                      axis_name: str = "pp",
                      n_microbatches: Optional[int] = None,
                      return_aux: bool = False):
    """``forward`` with the layer stack executed as a GPipe pipeline over
    the ``axis_name`` mesh axis (one stage of ``n_layers/P`` blocks per
    device, microbatched activations flowing via ppermute —
    :mod:`horovod_tpu.parallel.pipeline`).

    Call INSIDE ``shard_map`` with every input replicated over the axis
    (``P()`` specs): each device slices its own stage out of the full
    layer stack locally, so no parameter resharding collectives are
    emitted.  Numerically identical to :func:`forward`.

    ``return_aux`` additionally returns this STAGE's MoE balance-loss sum
    (``psum`` over the axis == :func:`forward`'s aux; kept local so each
    stage owns its aux gradient).
    """
    from horovod_tpu.parallel import pipeline as _pl

    B = tokens.shape[0]
    M, my_layers, stage_fn = _pipeline_stage_setup(
        params, cfg, axis_name, B, n_microbatches, return_aux=return_aux)
    x = params["embed"].astype(cfg.dtype)[tokens]
    mb = x.reshape(M, B // M, *x.shape[1:])
    out = _pl.pipeline_apply(stage_fn, my_layers, mb, axis_name=axis_name,
                             stage_aux=return_aux)
    if return_aux:
        out, aux_local = out
    x = out.reshape(B, *x.shape[1:])
    logits = _lm_head(x, params["ln_f"], params["head"], cfg)
    return (logits, aux_local) if return_aux else logits


def _pipeline_stage_setup(params: Dict, cfg: TransformerConfig,
                          axis_name: str, batch: int,
                          n_microbatches: Optional[int],
                          return_aux: bool = False):
    """Shared pipeline plumbing (both schedules): divisibility checks,
    this stage's layer slice, and the scanned stage function (aux-carrying
    when ``return_aux`` — the per-stage MoE balance sum)."""
    P_ = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    if cfg.n_layers % P_:
        raise ValueError(
            f"n_layers={cfg.n_layers} must divide over {P_} pipeline stages")
    per_stage = cfg.n_layers // P_
    M = n_microbatches or P_
    if batch % M:
        raise ValueError(f"batch {batch} must divide into {M} microbatches")
    my_layers = jax.tree_util.tree_map(
        lambda l: lax.dynamic_slice_in_dim(l, s * per_stage, per_stage, 0),
        params["layers"])

    if return_aux:
        def layer(carry, p):
            x, aux = carry
            x, a = _layer_body(x, p, cfg, return_aux=True)
            return (x, aux + a), None

        if cfg.remat:
            layer = _remat(layer, cfg)

        def stage_fn(lp_stack, xb):
            # Axis-varying zero init: the aux output is varying (computed
            # from the varying activations), so the scan carry init must
            # be too (shard_map VMA typing).
            aux0 = jnp.float32(0.0) + (s * 0).astype(jnp.float32)
            (out, aux), _ = lax.scan(layer, (xb, aux0), lp_stack)
            return out, aux

        return M, my_layers, stage_fn

    def layer(x, p):
        return _layer_body(x, p, cfg), None

    if cfg.remat:
        layer = _remat(layer, cfg)

    def stage_fn(lp_stack, xb):
        out, _ = lax.scan(layer, xb, lp_stack)
        return out

    return M, my_layers, stage_fn


def _varying_value_and_grad(local_loss_fn, params, s, axis_name):
    """value_and_grad of a replicated-parameter pipeline loss that is
    EXPLICITLY correct about gradient ownership under ANY shard_map VMA
    setting: ``local_loss_fn`` returns THIS DEVICE's gated loss
    contribution (NO psum inside — a psum's transpose is a psum, so a
    loss combined inside the differentiated function would multiply the
    seed cotangent by the axis size under ``check_vma=False``), params
    are made axis-VARYING before differentiation (no reliance on the
    implicit replicated-VJP psum that check_vma=False disables), and
    value + per-stage gradient partials combine with explicit psums
    OUTSIDE the grad.  Each parameter has exactly one owning stage in
    the gated construction (loss params on the last stage, embedding
    feed on stage 0, each layer via its dynamic_slice), so the psum
    adds one real contribution to zeros."""
    varying = jax.tree_util.tree_map(
        lambda a: a + (s * 0).astype(a.dtype), params)
    local, g_local = jax.value_and_grad(local_loss_fn)(varying)
    loss = lax.psum(local, axis_name)
    grads = jax.tree_util.tree_map(
        lambda x: lax.psum(x, axis_name), g_local)
    return loss, grads


def pipelined_value_and_grad(params: Dict, batch: Dict,
                             cfg: TransformerConfig, *,
                             axis_name: str = "pp",
                             n_microbatches: Optional[int] = None,
                             schedule: str = "gpipe",
                             n_virtual: int = 2):
    """Loss + EXACT full-parameter gradients of the pipelined model —
    call inside ``shard_map`` with params/batch replicated over the axis.

    ``schedule="gpipe"``: gradient accounting by construction rather than
    correction — the scalar loss is computed as ``psum(where(stage ==
    last, raw, 0))``, so the backward cotangent is nonzero only on the
    last stage for the head/ln_f path, only on stage 0 for the embedding
    path, and only on the owning stage for each layer (dynamic_slice
    VJP) — the psum that shard_map's transpose applies to each replicated
    parameter therefore sums one real contribution with zeros, giving
    gradients identical to ``jax.grad(loss_fn)`` with no replication
    factors to divide out.

    ``schedule="1f1b"``: the memory-bounded one-forward-one-backward
    schedule (:func:`horovod_tpu.parallel.pipeline_value_and_grad`) with
    the SAME full-parameter gradient contract: stage grads reassemble
    into the layer stack, the loss's head/ln_f grads come back via
    ``loss_params``, and the embedding grads via the returned input
    cotangents scattered through the token lookup.

    ``schedule="interleaved"``: virtual-stage (Megatron-interleaved)
    schedule — the layer stack splits into ``n_virtual * P`` chunks laid
    round-robin (:func:`horovod_tpu.parallel.interleaved_apply`), so the
    fill/drain bubble shrinks by ~``n_virtual`` at the cost of
    ``n_virtual×`` stage-boundary traffic; gradient construction is the
    gpipe one (loss gated to the last chunk's device, chunk slices taken
    inside the differentiated function so ``dynamic_slice``'s VJP
    scatters each chunk's gradient into the full stack).

    All three verified leaf-for-leaf against ``jax.grad(loss_fn)`` in
    ``tests/test_pipeline.py``.
    """
    P_ = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)

    aux_on = cfg.n_experts > 1 and cfg.moe_aux_coeff > 0.0

    if schedule == "gpipe":
        def _loss(p):
            if aux_on:
                logits, aux_local = pipelined_forward(
                    p, batch["tokens"], cfg, axis_name=axis_name,
                    n_microbatches=n_microbatches, return_aux=True)
            else:
                logits = pipelined_forward(p, batch["tokens"], cfg,
                                           axis_name=axis_name,
                                           n_microbatches=n_microbatches)
            raw = _xent_sum(logits, batch["targets"]) / batch["targets"].size
            local = jnp.where(s == P_ - 1, raw, 0.0)
            if aux_on:
                # Pipelined aux is computed PER MICROBATCH (the dispatch
                # group switch routing actually sees); the mean over
                # groups matches loss_fn's full-batch aux scale — and
                # equals it exactly at n_microbatches=1.  This stage's
                # LOCAL share; the psum happens outside the grad.
                M_ = n_microbatches or P_
                local = local + cfg.moe_aux_coeff * aux_local / M_
            return local

        return _varying_value_and_grad(_loss, params, s, axis_name)

    if schedule == "interleaved":
        from horovod_tpu.parallel import pipeline as _pl

        tokens, targets = batch["tokens"], batch["targets"]
        B, S = tokens.shape
        M, _, stage_fn = _pipeline_stage_setup(
            params, cfg, axis_name, B, n_microbatches, return_aux=aux_on)
        v = int(n_virtual)
        if cfg.n_layers % (v * P_):
            raise ValueError(
                f"n_layers={cfg.n_layers} must divide over "
                f"{v} virtual x {P_} stages")

        def _iloss(p):
            # Chunk slices taken INSIDE the differentiated function: the
            # dynamic_slice VJP scatters each chunk's gradient back into
            # the full (replicated) stack, same construction as gpipe.
            my_chunks = _pl.stack_to_chunks(p["layers"], P_, v, s)
            x = p["embed"].astype(cfg.dtype)[tokens]
            mbs = x.reshape(M, B // M, *x.shape[1:])
            if aux_on:
                outs, aux_local = _pl.interleaved_apply(
                    stage_fn, my_chunks, mbs, axis_name=axis_name,
                    n_virtual=v, stage_aux=True)
            else:
                outs = _pl.interleaved_apply(
                    stage_fn, my_chunks, mbs, axis_name=axis_name,
                    n_virtual=v)
            y = outs.reshape(B, *x.shape[1:])
            logits = _lm_head(y, p["ln_f"], p["head"], cfg)
            raw = _xent_sum(logits, targets) / targets.size
            local = jnp.where(s == P_ - 1, raw, 0.0)
            if aux_on:
                local = local + cfg.moe_aux_coeff * aux_local / M
            return local

        return _varying_value_and_grad(_iloss, params, s, axis_name)
    if schedule != "1f1b":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    from horovod_tpu.parallel import pipeline as _pl

    tokens, targets = batch["tokens"], batch["targets"]
    B, S = tokens.shape
    M, my_layers, stage_fn = _pipeline_stage_setup(
        params, cfg, axis_name, B, n_microbatches, return_aux=aux_on)
    per_stage = cfg.n_layers // P_
    n_tok = B * S

    x = params["embed"].astype(cfg.dtype)[tokens]
    xs = x.reshape(M, B // M, S, cfg.d_model)
    ts = targets.reshape(M, B // M, S)

    def loss_fn(lp, y, tgt):
        logits = _lm_head(y, lp["ln_f"], lp["head"], cfg)
        return _xent_sum(logits, tgt) / n_tok  # microbatch losses sum to mean

    loss, stage_grads, extras = _pl.pipeline_value_and_grad(
        stage_fn, my_layers, xs, ts, loss_fn, axis_name=axis_name,
        schedule="1f1b",
        loss_params={"ln_f": params["ln_f"], "head": params["head"]},
        return_input_grads=True,
        # Per-microbatch aux averaged over the M dispatch groups (see the
        # gpipe branch) — the weight folds the 1/M in.
        aux_weight=cfg.moe_aux_coeff / M if aux_on else None)

    # Reassemble the full layer-stack gradient: each stage owns its slice
    # (zeros elsewhere), so writing it at the stage offset and psumming
    # concatenates.
    def expand(g):
        full = jnp.zeros((cfg.n_layers,) + g.shape[1:], g.dtype)
        full = lax.dynamic_update_slice_in_dim(full, g, s * per_stage, 0)
        return lax.psum(full, axis_name)

    layer_grads = jax.tree_util.tree_map(expand, stage_grads)
    # Loss-param grads live on the last stage (zero elsewhere): psum.
    lp_grads = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), extras["loss_param_grads"])
    # Embedding grad: input cotangents live on stage 0 (zero elsewhere);
    # psum, then scatter-add through the token lookup's VJP.
    gx = lax.psum(extras["input_grads"], axis_name)  # (M, mb, S, D)
    embed_grad = (
        jnp.zeros(params["embed"].shape, cfg.dtype)
        .at[tokens.reshape(-1)]
        .add(gx.reshape(n_tok, cfg.d_model))
    ).astype(params["embed"].dtype)

    grads = {
        "embed": embed_grad,
        "layers": layer_grads,
        "ln_f": lp_grads["ln_f"],
        "head": lp_grads["head"],
    }
    return loss, grads


def synthetic_batch(rng, cfg: TransformerConfig, batch: int, seq: Optional[int] = None):
    seq = seq or cfg.max_seq
    k1, k2 = jax.random.split(jax.random.PRNGKey(rng) if isinstance(rng, int) else rng)
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return {"tokens": tokens, "targets": targets}
