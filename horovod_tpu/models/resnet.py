"""ResNet v1.5 family (flax) — the reference's headline benchmark model
(``examples/tensorflow2_synthetic_benchmark.py:36-131`` defaults to
ResNet-50; ``docs/benchmarks.rst`` reports ResNet-101).

Written fresh for TPU: NHWC layout (TPU-native), bfloat16 compute with
float32 batch-norm statistics and parameters, so convs hit the MXU at full
rate.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic (2-conv) residual block for ResNet-18/34."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1-3-1 bottleneck block for ResNet-50/101/152 (v1.5: stride on the
    3x3, which is what tf.keras.applications and the reference's benchmark
    use)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # "conv7": the canonical 7x7/2 stem.  "s2d": 2x2 space-to-depth then a
    # 4x4/1 conv — numerically the same function class (every 7x7/2 tap
    # maps to a unique (block, offset) weight; 4*4*12 >= 7*7*3), but the
    # conv sees 12 input channels instead of 3, which feeds the 128-lane
    # MXU 4x better (the MLPerf ResNet conv0 trick).
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            B, H, W, C = x.shape
            x = x.reshape(B, H // 2, 2, W // 2, 2, C)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2,
                                                      4 * C)
            # Output position i must see input blocks i-2..i+1 (= the
            # original 7x7 window rows 2i-3..2i+3 plus one padding row):
            # kernel 4, stride 1, padding (2, 1).
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)

MODELS = {
    "ResNet18": ResNet18,
    "ResNet34": ResNet34,
    "ResNet50": ResNet50,
    "ResNet101": ResNet101,
    "ResNet152": ResNet152,
}


def create(name: str = "ResNet50", num_classes: int = 1000,
           dtype=jnp.bfloat16, stem: str = "conv7"):
    return MODELS[name](num_classes=num_classes, dtype=dtype, stem=stem)


def init_variables(model, rng, image_size: int = 224, batch: int = 2):
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    return model.init(rng, dummy, train=True)
