"""Benchmark/example model zoo (the reference keeps models in examples/;
here they double as the flagship benchmark targets)."""
