"""Small MLP classifier — the MNIST example model
(reference: ``examples/tensorflow2_mnist.py`` / ``pytorch_mnist.py``)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp


def init_params(rng, sizes: Sequence[int] = (784, 256, 128, 10)) -> Dict:
    params = {}
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"w{i}"] = jax.random.normal(keys[i], (a, b)) / jnp.sqrt(a)
        params[f"b{i}"] = jnp.zeros((b,))
    return params


def forward(params: Dict, x):
    n = len(params) // 2
    h = x.reshape(x.shape[0], -1)
    for i in range(n):
        h = h @ params[f"w{i}"] + params[f"b{i}"]
        if i < n - 1:
            h = jax.nn.relu(h)
    return h


def loss_fn(params: Dict, batch):
    x, y = batch
    logits = forward(params, x)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, y[:, None], axis=-1).squeeze(-1)
    return jnp.mean(logz - gold)


def accuracy(params: Dict, batch):
    x, y = batch
    return jnp.mean(jnp.argmax(forward(params, x), axis=-1) == y)
