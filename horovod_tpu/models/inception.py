"""Inception V3, TPU-first: NHWC, bfloat16 compute, fp32 BatchNorm
statistics and head (same precision policy as :mod:`.resnet`).

The reference benchmarks Inception V3 alongside ResNet-101 as its
headline models (``docs/benchmarks.rst:13-14``); this is the standard
architecture (Szegedy et al. 2015, "Rethinking the Inception
Architecture") with the mixed blocks A/B/C/D/E and no aux head (the aux
classifier is a training-era regularizer the benchmark protocol doesn't
use).  Every branch concatenates on the channel (minor) axis, which is
the TPU-friendly layout — XLA fuses the BN+relu epilogues into the
convolutions per branch.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class BasicConv(nn.Module):
    """conv → BN → relu (the BasicConv2d everywhere in Inception)."""

    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = partial(BasicConv, dtype=self.dtype, train=self.train)
        b1 = conv(64, (1, 1))(x)
        b5 = conv(48, (1, 1))(x)
        b5 = conv(64, (5, 5))(b5)
        b3 = conv(64, (1, 1))(x)
        b3 = conv(96, (3, 3))(b3)
        b3 = conv(96, (3, 3))(b3)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(self.pool_features, (1, 1))(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35x35 -> 17x17 grid reduction."""

    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = partial(BasicConv, dtype=self.dtype, train=self.train)
        b3 = conv(384, (3, 3), (2, 2), padding="VALID")(x)
        bd = conv(64, (1, 1))(x)
        bd = conv(96, (3, 3))(bd)
        bd = conv(96, (3, 3), (2, 2), padding="VALID")(bd)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """17x17 blocks with factorized 7x7 (1x7 + 7x1) convolutions."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = partial(BasicConv, dtype=self.dtype, train=self.train)
        c7 = self.channels_7x7
        b1 = conv(192, (1, 1))(x)
        b7 = conv(c7, (1, 1))(x)
        b7 = conv(c7, (1, 7))(b7)
        b7 = conv(192, (7, 1))(b7)
        bd = conv(c7, (1, 1))(x)
        bd = conv(c7, (7, 1))(bd)
        bd = conv(c7, (1, 7))(bd)
        bd = conv(c7, (7, 1))(bd)
        bd = conv(192, (1, 7))(bd)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(192, (1, 1))(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17x17 -> 8x8 grid reduction."""

    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = partial(BasicConv, dtype=self.dtype, train=self.train)
        b3 = conv(192, (1, 1))(x)
        b3 = conv(320, (3, 3), (2, 2), padding="VALID")(b3)
        b7 = conv(192, (1, 1))(x)
        b7 = conv(192, (1, 7))(b7)
        b7 = conv(192, (7, 1))(b7)
        b7 = conv(192, (3, 3), (2, 2), padding="VALID")(b7)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """8x8 blocks with split 1x3 / 3x1 branches."""

    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = partial(BasicConv, dtype=self.dtype, train=self.train)
        b1 = conv(320, (1, 1))(x)
        b3 = conv(384, (1, 1))(x)
        b3 = jnp.concatenate(
            [conv(384, (1, 3))(b3), conv(384, (3, 1))(b3)], axis=-1)
        bd = conv(448, (1, 1))(x)
        bd = conv(384, (3, 3))(bd)
        bd = jnp.concatenate(
            [conv(384, (1, 3))(bd), conv(384, (3, 1))(bd)], axis=-1)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = conv(192, (1, 1))(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(BasicConv, dtype=self.dtype, train=train)
        x = x.astype(self.dtype)
        # stem: 299x299x3 -> 35x35x192
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x)
        x = conv(32, (3, 3), padding="VALID")(x)
        x = conv(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = conv(80, (1, 1), padding="VALID")(x)
        x = conv(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35x35
        x = InceptionA(32, dtype=self.dtype, train=train)(x)
        x = InceptionA(64, dtype=self.dtype, train=train)(x)
        x = InceptionA(64, dtype=self.dtype, train=train)(x)
        x = InceptionB(dtype=self.dtype, train=train)(x)
        # 17x17
        x = InceptionC(128, dtype=self.dtype, train=train)(x)
        x = InceptionC(160, dtype=self.dtype, train=train)(x)
        x = InceptionC(160, dtype=self.dtype, train=train)(x)
        x = InceptionC(192, dtype=self.dtype, train=train)(x)
        x = InceptionD(dtype=self.dtype, train=train)(x)
        # 8x8
        x = InceptionE(dtype=self.dtype, train=train)(x)
        x = InceptionE(dtype=self.dtype, train=train)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def create(name: str = "InceptionV3", num_classes: int = 1000,
           dtype=jnp.bfloat16):
    assert name == "InceptionV3", name
    return InceptionV3(num_classes=num_classes, dtype=dtype)


def init_variables(model, rng, image_size: int = 299, batch: int = 2):
    return jax.jit(model.init, static_argnames="train")(
        rng, jnp.zeros((batch, image_size, image_size, 3), jnp.float32),
        train=True)
