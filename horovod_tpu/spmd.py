"""SPMD step construction: the compiled replacement for the reference's
entire runtime hot path.

Where the reference enqueues each gradient to a background thread that
negotiates, fuses and launches NCCL (call stack SURVEY.md §3.2), here the
whole train step — forward, backward, allreduce, optimizer — is ONE jitted
SPMD program over the horovod mesh.  XLA overlaps the gradient collectives
with remaining backward computation (latency hiding, same effect as the
reference's async background thread) and schedules them on ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu import basics

try:  # jax >= 0.8 stable API (or the _compat re-export on older jax)
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# The replication-check kwarg was renamed check_rep -> check_vma when
# shard_map was promoted to the stable namespace; sniff the signature
# rather than the attribute location (horovod_tpu._compat re-exports the
# experimental one as jax.shard_map on older runtimes).
import inspect as _inspect

try:
    _SHARD_MAP_KW = "check_vma" in _inspect.signature(_shard_map).parameters
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _SHARD_MAP_KW = True


def shard(fn, *, in_specs, out_specs, mesh=None, check_replication: bool = False):
    """``shard_map`` over the horovod mesh with version-portable kwargs."""
    mesh = mesh or basics.mesh()
    if _SHARD_MAP_KW:
        return _shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_replication,
        )
    return _shard_map(  # pragma: no cover - older jax
        fn, mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_replication
    )


def run(fn, *args, in_specs, out_specs, mesh=None):
    """Run ``fn`` once under shard_map (eagerly jitted)."""
    return jax.jit(shard(fn, in_specs=in_specs, out_specs=out_specs, mesh=mesh))(
        *args
    )


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    mesh=None,
    axis: Optional[str] = None,
    donate: bool = True,
    has_aux: bool = False,
    hierarchical: Optional[bool] = None,
):
    """Build the canonical data-parallel train step.

    ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux``); ``optimizer`` is typically
    ``hvd.DistributedOptimizer(optax...)`` so the gradient allreduce is
    inside.  Batch arrays are sharded on dim 0 over the worker axis; params
    and optimizer state are replicated.  Returns
    ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``.

    This is the compiled equivalent of the reference's
    ``DistributedGradientTape`` + ``apply_gradients`` hot path
    (SURVEY.md §3.2) with negotiation/fusion/cache made unnecessary by
    SPMD compilation.

    ``hierarchical=True`` (default: the ``HOROVOD_HIERARCHICAL_ALLREDUCE``/
    ``ALLGATHER`` env flags, i.e. the launcher's ``--hierarchical-*``)
    builds the step over the 2-D ``(cross, local)`` mesh so collectives can
    use the two-level algorithms — the wiring for the reference's
    ``NCCLHierarchicalAllreduce`` configuration knob (``common.h:76-77``).
    """
    from horovod_tpu.ops import collectives as _C

    if hierarchical is None:
        hierarchical = (
            _C.hierarchical_allreduce_enabled()
            or _C.hierarchical_allgather_enabled()
        )
    if hierarchical and mesh is None and axis is None:
        hier = basics.hierarchical_mesh()
        if hier is not None:
            mesh = hier
            axis = (basics.CROSS_AXIS, basics.LOCAL_AXIS)
    mesh = mesh or basics.mesh()
    axis = axis or basics.axis_name()

    def _step(params, opt_state, batch):
        vg = jax.value_and_grad(loss_fn, has_aux=has_aux)
        val, grads = vg(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            loss, aux = val
        else:
            loss = val
        loss = lax.pmean(loss, axis)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    batch_spec = P(axis)
    sharded = shard(
        _step,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(), P(), P()) + ((batch_spec,) if has_aux else ()),
        mesh=mesh,
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


def make_gspmd_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    mesh,
    param_spec,
    batch_spec,
    donate: bool = True,
):
    """Build a train step in GSPMD style: parameters/batch carry
    NamedShardings over an N-D mesh (dp/fsdp/tp/sp/pp/ep — see
    :mod:`horovod_tpu.parallel.meshes`), and XLA's sharding propagation
    inserts every collective — gradient psums over dp/fsdp, tp
    all-gathers/reduce-scatters, sp/pp permutes.

    This is the second (TPU-idiomatic) face of the framework: where
    :func:`make_train_step` expresses Horovod's explicit-collective
    programming model, this one expresses "pick a mesh, annotate shardings,
    let XLA insert collectives" for arbitrary multi-axis parallelism the
    reference never had (SURVEY.md §2.6 extensions).
    """
    p_shard = jax.tree_util.tree_map(
        lambda s: jax.sharding.NamedSharding(mesh, s), param_spec
    )
    b_shard = jax.tree_util.tree_map(
        lambda s: jax.sharding.NamedSharding(mesh, s), batch_spec
    )
    repl = jax.sharding.NamedSharding(mesh, P())

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        _step,
        in_shardings=(p_shard, None, b_shard),
        out_shardings=(p_shard, None, repl),
        donate_argnums=(0, 1) if donate else (),
    )


def init_replicated(params, mesh=None):
    """Place a pytree replicated across the mesh (host → devices)."""
    mesh = mesh or basics.mesh()
    sharding = jax.sharding.NamedSharding(mesh, P())
    return jax.device_put(params, sharding)


def shard_batch(batch, mesh=None, axis: Optional[str] = None):
    """Place host batch arrays sharded on dim 0 over the worker axis."""
    mesh = mesh or basics.mesh()
    axis = axis or basics.axis_name()
    sharding = jax.sharding.NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda b: jax.device_put(b, sharding), batch)
