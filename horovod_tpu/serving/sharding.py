"""Tensor-parallel serving: one engine's compiled tick under GSPMD
over a ``tp`` mesh (docs/serving.md "Tensor-parallel replicas").

The paper's whole move is that the reference's background
negotiate/fuse/launch machinery becomes collectives COMPILED INTO the
XLA program; this module applies it to the serving tick so ONE engine
serves a model bigger than one chip.  Megatron-style tensor
parallelism, expressed purely as sharding annotations on the same
executables the single-chip engine runs:

* a ``tp`` mesh built from :class:`~horovod_tpu.parallel.meshes.
  MeshSpec` (the innermost/ICI-hungry axis of the training mesh
  convention), over the first ``tp`` local devices;
* params placed per :func:`~horovod_tpu.models.transformer.
  serving_param_specs` — attention heads and the MLP hidden dim split
  over ``tp``, embeddings at the vocab dim, norms replicated;
* the paged KV page pool head-dim sharded per :func:`~horovod_tpu.
  models.transformer.paged_pool_specs` — pages split BY HEAD, never by
  page id, so page tables, grants, refcounts, and COW stay host-side
  and sharding-oblivious (replicated tick data, exactly as before);
* every compiled tick body — ``decode_step_paged``,
  ``prefill_with_prefix``, ``decode_verify_paged``,
  ``sample_token_rows`` — jitted with in/out shardings so XLA inserts
  the head-gather / psum collectives itself.  Sharding is an
  ANNOTATION on the same code, which is why everything downstream
  (chunked prefill, speculative verify, sampling columns,
  journal/resume, SSE failover) composes unchanged and output stays
  token-identical to the tp=1 oracle.

Testable on CPU via forced host devices
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``, the
``tests/test_gspmd_multiprocess.py`` trick); :func:`ensure_devices`
arms that from inside a process when the backend is not yet up.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import transformer as T
from horovod_tpu.parallel.meshes import MeshSpec, make_mesh

__all__ = ["ShardingConfigError", "ServingSharding", "ensure_devices",
           "make_tp_mesh", "validate_tp"]

_FORCE_FLAG = "--xla_force_host_platform_device_count"


class ShardingConfigError(ValueError):
    """A tensor-parallel configuration the mesh/model cannot honor —
    raised TYPED at engine construction, never left to surface as an
    XLA shape crash mid-serving."""


def ensure_devices(n: int) -> None:
    """Best-effort: make at least ``n`` devices visible BEFORE the
    backend initializes (CPU hosts: the forced-host-device XLA flag;
    accelerators already expose their real topology).  The ONE copy of
    the flag-arming every ``--tp`` entry point (replica_main,
    examples/serve.py, benchmarks/serving.py) calls.  An already-set
    flag is respected, whatever its value — the supervisor/operator
    owns it then, and too few devices surface as the typed
    :class:`ShardingConfigError` at engine construction, not a silent
    misconfig.  Importing jax does not initialize the backend, so this
    is safe to call after imports as long as no op has run."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _FORCE_FLAG not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {_FORCE_FLAG}={n}".strip()


def validate_tp(cfg: "T.TransformerConfig", tp: int,
                n_devices: Optional[int] = None) -> None:
    """Typed divisibility/topology checks for a tp serving mesh.

    Heads are the unit tensor parallelism splits (wq/wo at ``n_heads``,
    wk/wv and the KV pool at ``kv_heads``), so both must divide by
    ``tp``; everything else (d_ff, vocab) GSPMD pads without a
    correctness cost.  Raises :class:`ShardingConfigError` — at
    construction, not as an XLA shape crash inside the first tick."""
    if tp < 1:
        raise ShardingConfigError(f"tp must be >= 1, got {tp}")
    if cfg.n_heads % tp:
        raise ShardingConfigError(
            f"n_heads={cfg.n_heads} not divisible by tp={tp}; "
            f"attention heads are the tensor-parallel split unit")
    if cfg.kv_heads % tp:
        raise ShardingConfigError(
            f"kv_heads={cfg.kv_heads} (n_kv_heads={cfg.n_kv_heads}) "
            f"not divisible by tp={tp}; the KV pool shards by kv head")
    if n_devices is not None and tp > n_devices:
        raise ShardingConfigError(
            f"tp={tp} exceeds the {n_devices} visible devices "
            f"(CPU hosts: XLA_FLAGS={_FORCE_FLAG}={tp})")


def make_tp_mesh(tp: int,
                 devices: Optional[Sequence[jax.Device]] = None):
    """A serving mesh with ``tp`` on the innermost axis (the
    :data:`~horovod_tpu.parallel.meshes.AXIS_ORDER` convention: tp maps
    to ICI neighbors), over ``devices`` or the first ``tp`` local
    devices.  Training-only axes exist at size 1, so
    ``serving_param_specs``'s replicate-unknown-axes rule applies
    unchanged."""
    if devices is None:
        devices = jax.devices()
        if tp > len(devices):
            raise ShardingConfigError(
                f"tp={tp} exceeds the {len(devices)} visible devices "
                f"(CPU hosts: XLA_FLAGS={_FORCE_FLAG}={tp})")
        devices = devices[:tp]
    if len(devices) != tp:
        raise ShardingConfigError(
            f"tp={tp} mesh needs exactly tp devices, got {len(devices)}")
    return make_mesh(MeshSpec(tp=tp), devices)


class ServingSharding:
    """One tp serving mesh plus every NamedSharding the engine's
    executables need — built once at engine construction, then handed
    to ``jax.jit`` as in/out shardings (and to ``device_put`` for
    params and the page pool).

    ``draft_cfg`` (speculative model drafts) is validated against the
    SAME mesh: the draft pool is slot-aligned with the target pool, so
    it shards by its own kv heads over the same ``tp`` axis.
    """

    def __init__(self, cfg: "T.TransformerConfig", tp: int, *,
                 devices: Optional[Sequence[jax.Device]] = None,
                 draft_cfg: Optional["T.TransformerConfig"] = None):
        validate_tp(cfg, tp,
                    len(devices) if devices is not None
                    else len(jax.devices()))
        if draft_cfg is not None:
            validate_tp(draft_cfg, tp)
        self.cfg = cfg
        self.tp = tp
        self.mesh = make_tp_mesh(tp, devices)
        #: the replicated sharding every host-data tick input (tokens,
        #: masks, tables, sampling columns) and every host-fetched
        #: output (next tokens, max logits, acceptance) pins to — a
        #: STABLE signature, so committed fed-back outputs and fresh
        #: host uploads hit the same executable (zero decode
        #: recompiles across churn).
        self.replicated = NamedSharding(self.mesh, P())

    # -- sharding trees ----------------------------------------------------

    def param_shardings(self,
                        cfg: Optional["T.TransformerConfig"] = None):
        # serving_shardings is the ONE spec->NamedSharding mapping
        # (T.shard_params routes through it too).
        param_sh, _ = T.serving_shardings(
            self.mesh, cfg if cfg is not None else self.cfg)
        return param_sh

    def shard_params(self, params: Dict,
                     cfg: Optional["T.TransformerConfig"] = None) -> Dict:
        return jax.device_put(params, self.param_shardings(cfg))

    def pool_shardings(self, quantized: bool = False) -> Dict:
        return {k: NamedSharding(self.mesh, s)
                for k, s in T.paged_pool_specs(quantized).items()}

    def prefill_cache_shardings(self) -> Dict:
        """Out-shardings for a prefill's ``(logits-companion) cache``
        block — head-sharded K/V, replicated per-row pos — so the
        landing scatter into the sharded pool is local."""
        specs = T.cache_specs()
        return {k: NamedSharding(self.mesh, specs[k])
                for k in ("k", "v", "pos")}

    def prefix_kv_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, T.prefix_kv_specs())

    def paged_kernel_shardings(self, quantized: bool = False):
        """NamedShardings for the fused paged-attention kernel's
        operands/results (:func:`~horovod_tpu.models.transformer.
        paged_kernel_specs` order: ``(q, k_pool, v_pool[, k_scale,
        v_scale], table, limit)`` / ``(o, lse)``).  The kernel runs
        per-(slot, kv-head) with no cross-head traffic, so the
        head-dim-sharded pool passes straight through: the tick's
        ``shard_map`` uses the raw specs, and these placements exist so
        callers (tests, benchmarks, ahead-of-time placement) can pin
        kernel operands consistently with the pool they came from."""
        in_specs, out_specs = T.paged_kernel_specs(quantized)
        return ([NamedSharding(self.mesh, s) for s in in_specs],
                [NamedSharding(self.mesh, s) for s in out_specs])

    # -- observability -----------------------------------------------------

    def describe(self) -> str:
        """The ``/stats`` ``mesh`` value: a stable, typed (str)
        one-liner of the mesh layout and device set, e.g.
        ``"tp=2 devices=cpu:0,1"``."""
        devs = list(self.mesh.devices.flat)
        ids = ",".join(str(d.id) for d in devs)
        return f"tp={self.tp} devices={devs[0].platform}:{ids}"
